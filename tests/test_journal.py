"""Durable control plane: write-ahead journal + crash recovery units.

Covers the :class:`~tpu_engine.journal.ControlPlaneJournal` itself
(bounded rotation, torn-tail-tolerant ingest, O(1) stats, never-raising
appends), ``FleetScheduler.restore`` (deterministic rebuild, orphan
re-adoption, vanished-training requeue, the HBM double-grant audit),
``ServingFleet.re_adopt`` (roster + held-request recovery) and the
component export/load hooks behind ``journal.collect_sections``. The
full kill-mid-storm A/B with exit gates lives in
``benchmarks/ctl_crash_sim.py`` (``twin.ctl_crash_lane``).
"""

import json
import threading
from types import SimpleNamespace

import pytest

from tests.test_scheduler import StubJob, cfg
from tpu_engine import journal as journal_mod
from tpu_engine.autopilot import AutopilotConfig, FleetAutopilot
from tpu_engine.hbm_estimate import estimate_job_hbm
from tpu_engine.journal import ControlPlaneJournal, collect_sections
from tpu_engine.prefix_plane import HOST_HOLDER, PrefixPlane
from tpu_engine.scheduler import FleetScheduler, SubmissionState
from tpu_engine.serving_fleet import ServingFleet, ServingReplicaSpec
from tpu_engine.spec_pool import SpecSpillController
from tpu_engine.tpu_manager import TPUDevice, TPUFleetStatus


@pytest.fixture(autouse=True)
def _fresh_journal_stats():
    journal_mod._reset_stats_for_tests()
    journal_mod.clear_active_journal()
    yield
    journal_mod._reset_stats_for_tests()
    journal_mod.clear_active_journal()


def _make_sched(**kw):
    """Pump-thread-free scheduler: tests drive poll() by hand."""
    kw.setdefault("job_factory", StubJob)
    kw.setdefault("poll_interval_s", 3600.0)
    kw.setdefault("grow_back", False)
    kw.setdefault("hetero_rebalance", False)
    s = FleetScheduler(**kw)
    s._ensure_thread = lambda: None
    return s


# ---------------------------------------------------------------------------
# the journal itself
# ---------------------------------------------------------------------------


def test_snapshot_resets_replay_suffix(tmp_path):
    clk = iter(range(1000))
    j = ControlPlaneJournal(
        str(tmp_path / "j.jsonl"), clock=lambda: float(next(clk))
    )
    j.append("sched.submit", {"sid": "a"})
    j.append("sched.submit", {"sid": "b"})
    j.snapshot({"scheduler": {"seq": 2}})
    j.append("sched.admit", {"sid": "a"})
    got = j.read()
    # Replay starts at the newest snapshot: only the suffix survives.
    assert got["snapshot"]["sections"]["scheduler"] == {"seq": 2}
    assert [e["kind"] for e in got["events"]] == ["sched.admit"]
    assert got["stats"]["accepted"] == 4 and got["stats"]["skipped"] == 0
    st = j.stats()
    assert st["appends_total"] == 3 and st["snapshots_total"] == 1


def test_read_skips_torn_and_unknown_lines(tmp_path):
    path = tmp_path / "j.jsonl"
    j = ControlPlaneJournal(str(path))
    j.append("sched.submit", {"sid": "a"})
    with open(path, "a", encoding="utf-8") as f:
        # Legacy line (pre-versioning): accepted.
        f.write(json.dumps({"record": "event", "kind": "legacy.ev",
                            "ts": 0.0, "payload": {}}) + "\n")
        # Future schema: skipped, never guessed at.
        f.write(json.dumps({"record": "event", "kind": "x",
                            "schema_version": 99, "payload": {}}) + "\n")
        # Unrecognized record kind.
        f.write(json.dumps({"record": "weird", "schema_version": 1}) + "\n")
        # Mid-file garbage is a parse error...
        f.write("{{{ not json\n")
        # ...but an undecodable FINAL line is the torn tail of the write
        # the crash interrupted.
        f.write('{"record":"event","kind":"sched.su')
    got = j.read()
    assert [e["kind"] for e in got["events"]] == ["sched.submit", "legacy.ev"]
    assert got["stats"]["legacy_lines"] == 1
    assert got["stats"]["skipped_by_reason"] == {
        "unknown_schema": 1, "unknown_record": 1,
        "parse_error": 1, "torn_tail": 1,
    }
    # Module-level read counters (the scrape surface) saw the same ingest.
    js = journal_mod.journal_stats()
    assert js["reads_total"] == 1
    assert js["read_skipped_lines_total"] == 4
    assert js["read_skipped_by_reason"]["torn_tail"] == 1


def test_append_never_raises(tmp_path):
    # Parent directory missing: every write fails — and is absorbed.
    j = ControlPlaneJournal(str(tmp_path / "no" / "such" / "dir" / "j.jsonl"))
    j.append("sched.submit", {"sid": "a"})
    j.snapshot({"scheduler": {}})
    st = j.stats()
    assert st["append_errors_total"] == 2
    got = j.read()
    assert got["snapshot"] is None and got["events"] == []


# ---------------------------------------------------------------------------
# scheduler restore
# ---------------------------------------------------------------------------


def test_restore_readopts_orphans_and_requeues_vanished(tmp_path):
    j = ControlPlaneJournal(str(tmp_path / "j.jsonl"))
    s1 = _make_sched(max_concurrent_jobs=2)
    s1.attach_journal(j)
    sub_a = s1.submit(cfg())
    sub_b = s1.submit(cfg())
    sub_c = s1.submit(cfg())
    s1.poll()
    assert sub_a.state == SubmissionState.RUNNING
    assert sub_b.state == SubmissionState.RUNNING
    assert sub_c.state == SubmissionState.QUEUED
    seq_b = sub_b.seq
    job_a = sub_a.job

    # Crash. Job A kept running (orphan); job B died with the host.
    appends_before = j.stats()["appends_total"]
    s2 = _make_sched(max_concurrent_jobs=2)
    r = s2.restore(j, live_jobs={sub_a.submission_id: job_a}, now=123.0)
    assert r["had_snapshot"] is False
    assert r["restored_submissions"] == 3
    assert r["events_replayed"] == 5  # 3 submits + 2 admits
    assert r["readopted"] == 1 and r["requeued_vanished"] == 1
    got_a = s2.get(sub_a.submission_id)
    assert got_a.state == SubmissionState.RUNNING and got_a.job is job_a
    got_b = s2.get(sub_b.submission_id)
    assert got_b.state == SubmissionState.QUEUED
    assert got_b.seq == seq_b  # requeued at its ORIGINAL position
    assert got_b.last_skip_reason == "requeued_at_recovery"
    assert s2.get(sub_c.submission_id).state == SubmissionState.QUEUED
    # restore() never writes — double recovery is byte-identical.
    assert j.stats()["appends_total"] == appends_before
    s3 = _make_sched(max_concurrent_jobs=2)
    s3.restore(j, live_jobs={sub_a.submission_id: job_a}, now=123.0)
    d2 = json.dumps(s2.snapshot_state(), sort_keys=True)
    d3 = json.dumps(s3.snapshot_state(), sort_keys=True)
    assert d2 == d3
    # Recovery counters landed on the module surface.
    cr = journal_mod.recovery_stats()
    assert cr["restores_total"] == 2 and cr["jobs_readopted_total"] == 2
    for job in (job_a, sub_b.job):
        if job is not None:
            job.finish()


def test_restore_detects_double_grants(tmp_path):
    est = estimate_job_hbm(cfg())
    cap = est.device_total_gib * 1.5  # fits one claimant, not two
    fleet = TPUFleetStatus(devices=[TPUDevice(index=0, hbm_total_gb=cap)])

    j = ControlPlaneJournal(str(tmp_path / "j.jsonl"))
    s1 = _make_sched(max_concurrent_jobs=2)
    sub_a = s1.submit(cfg())
    sub_b = s1.submit(cfg())
    # Doctor the snapshot into the inconsistent state a crash-interrupted
    # release leaves behind: both submissions journaled RUNNING with a
    # grant on device 0, which cannot hold both.
    snap = s1.snapshot_state()
    for e in snap["submissions"]:
        e["state"] = "running"
        e["attempts"] = 1
        e["placement"] = [0]
        e["hbm_estimate"] = est.model_dump(mode="json")
    j.snapshot({"scheduler": snap})

    live = {
        sub_a.submission_id: SimpleNamespace(_stop=threading.Event()),
        sub_b.submission_id: SimpleNamespace(_stop=threading.Event()),
    }
    s2 = _make_sched(max_concurrent_jobs=2, fleet_fn=lambda: fleet)
    r = s2.restore(j, live_jobs=live, now=99.0)
    assert r["readopted"] == 2 and r["double_grants"] == 1
    # The YOUNGEST claimant's grant is the bogus one: demoted, its job
    # stopped, the device quarantined with a structured reason.
    victim = s2.get(sub_b.submission_id)
    assert victim.state == SubmissionState.QUEUED
    assert victim.last_skip_reason == "double_grant_at_recovery"
    assert live[sub_b.submission_id]._stop.is_set()
    assert s2.get(sub_a.submission_id).state == SubmissionState.RUNNING
    q = s2._hetero_quarantined[0]
    assert q["source"] == "ctl_recovery:double_grant"
    assert s2._reserved[0] <= cap + 1e-9
    assert journal_mod.recovery_stats()["double_grants_total"] == 1


# ---------------------------------------------------------------------------
# serving fleet re-adoption
# ---------------------------------------------------------------------------


def test_re_adopt_recovers_roster_and_held_requests(tmp_path):
    j = ControlPlaneJournal(str(tmp_path / "j.jsonl"))
    s = _make_sched(max_concurrent_jobs=4)
    replica_sub = s.submit(cfg(), workload="serving")  # survived, still queued
    j.append("fleet.desired", {"n": 2})
    j.append("fleet.replica", {"sid": replica_sub.submission_id})
    j.append("fleet.replica", {"sid": "sub_gone"})  # vanished with the host
    j.append("fleet.request", {
        "fid": "r_1", "prompt": [1, 2, 3], "max_new_tokens": 8,
        "temperature": 0.0, "submitted_at": 1.0,
    })
    j.append("fleet.request", {
        "fid": "r_2", "prompt": [4, 5], "max_new_tokens": 4,
        "temperature": 0.5, "submitted_at": 2.0,
    })
    j.append("fleet.request_done", {"fid": "r_1"})

    spec = ServingReplicaSpec(model_name="gpt-tiny", max_slots=4, max_len=64)
    fleet = ServingFleet(s, spec)
    r = fleet.re_adopt(j, redispatch=False)
    assert r["replicas_readopted"] == 1
    assert r["replicas_redispatched"] == 0  # redispatch=False mints no ids
    assert r["requests_recovered"] == 1 and r["held_fids"] == ["r_2"]
    assert replica_sub.submission_id in fleet._replicas
    assert fleet.desired_replicas == 2
    assert fleet.requests_total == 2 and fleet.completed_total == 1
    assert fleet._req_seq == 2  # the next fid cannot collide with r_1/r_2
    held = fleet._requests["r_2"]
    assert held["prompt"] == [4, 5] and held["done"] is False
    # The journal is attached for subsequent write-ahead.
    before = j.stats()["appends_total"]
    fleet.submit_request([7, 8], max_new_tokens=2)
    assert j.stats()["appends_total"] == before + 1


# ---------------------------------------------------------------------------
# component export/load hooks + section assembly
# ---------------------------------------------------------------------------


def test_export_load_hooks_round_trip():
    # Spec-spill: spilled set, streaks and cooldown clocks survive.
    ctl = SpecSpillController(historian=None)
    ctl.load_state({"spilled": ["t1"], "streak": {"t1": 2, "t2": 1},
                    "last_fired": {"t1": 10.0}})
    assert ctl.is_spilled("t1") and not ctl.is_spilled("t2")
    ctl2 = SpecSpillController(historian=None)
    ctl2.load_state(ctl.export_state())
    assert ctl2.export_state() == ctl.export_state()

    # Autopilot: tuple-keyed hysteresis flattens to JSON and back.
    ap = FleetAutopilot(config=AutopilotConfig(), clock=lambda: 0.0)
    ap._streak = {("replan", "q"): 2}
    ap._last_action = {("rescale", "fleet"): 5.0}
    state = json.loads(json.dumps(ap.export_state()))  # must be JSON-safe
    ap2 = FleetAutopilot(config=AutopilotConfig(), clock=lambda: 0.0)
    ap2.load_state(state)
    assert ap2._streak == ap._streak
    assert ap2._last_action == ap._last_action

    # Prefix plane: the host-tier index re-parks as capacity entries.
    plane = PrefixPlane(prefix_tokens=4)
    assert plane.host.put((1, 2, 3, 4), nbytes=128)
    plane.index.insert((1, 2, 3, 4), HOST_HOLDER)
    state = plane.export_host_index()
    assert state["entries"] == [{"prefix": [1, 2, 3, 4], "nbytes": 128}]
    plane2 = PrefixPlane(prefix_tokens=4)
    assert plane2.load_host_index(json.loads(json.dumps(state))) == 1
    assert plane2.host.contains((1, 2, 3, 4))
    # Garbage tolerated: not-a-dict and half-shaped entries are skipped.
    assert plane2.load_host_index("nope") == 0
    assert plane2.load_host_index({"entries": [{"nbytes": 4}]}) == 0


def test_collect_sections_and_active_journal(tmp_path):
    s = _make_sched()
    sections = collect_sections(scheduler=s)
    assert set(sections) == {"scheduler"}
    sections = collect_sections(
        scheduler=s,
        autopilot=FleetAutopilot(config=AutopilotConfig(), clock=lambda: 0.0),
        spec_spill=SpecSpillController(historian=None),
        prefix_plane=PrefixPlane(prefix_tokens=4),
    )
    assert set(sections) == {
        "scheduler", "autopilot", "spec_spill", "prefix_host",
    }

    # No active journal: the scrape surface renders zeros, attached=False.
    js = journal_mod.journal_stats()
    assert js["attached"] is False and js["appends_total"] == 0
    j = ControlPlaneJournal(str(tmp_path / "j.jsonl"))
    journal_mod.set_active_journal(j)
    j.append("sched.submit", {"sid": "a"})
    js = journal_mod.journal_stats()
    assert js["attached"] is True and js["appends_total"] == 1
    journal_mod.note_mttr(3.5)
    assert journal_mod.recovery_stats()["last_mttr_seconds"] == 3.5
