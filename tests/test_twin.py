"""Digital-twin tests: trace ingestion hardening (rotation, torn tails,
schema versions), deterministic replay of recorder JSONL through the real
control-plane components, causal-chain preservation, replay fidelity vs
the source run's goodput decomposition, synthetic-generator parity with
the legacy sims, and the A/B policy scorecards."""

import json
import os

import pytest

from tpu_engine import twin
from tpu_engine.tracing import SCHEMA_VERSION, FlightRecorder
from tpu_engine.twin import (
    ReplayWorkload,
    TrainTwinParams,
    TwinEngine,
    VirtualClock,
    bursty_arrivals,
    chip_fault_timeline,
    decomposition_diff,
    default_policy_scorecard,
    deterministic_ids,
    diurnal_arrivals,
    goodput_lane,
    heavy_tail_prefill_arrivals,
    read_recorder_jsonl,
    replay_fidelity,
    replay_self_heal,
    twin_bench_line,
)


# -- virtual clock + deterministic ids ---------------------------------------


def test_virtual_clock_advances_and_sets():
    clock = VirtualClock(0.0)
    assert clock() == 0.0
    assert clock.now() == 0.0
    assert clock.advance(2.5) == 2.5
    assert clock.set(10.0) == 10.0
    assert clock() == 10.0


def test_deterministic_ids_reproduce_across_factories():
    a, b = deterministic_ids("x"), deterministic_ids("x")
    seq_a = [a() for _ in range(5)]
    seq_b = [b() for _ in range(5)]
    assert seq_a == seq_b
    assert len(set(seq_a)) == 5


# -- schema versioning --------------------------------------------------------


def test_recorder_jsonl_lines_carry_schema_version(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    rec = FlightRecorder(clock=lambda: 0.0, persist_path=path)
    tid = rec.new_trace_id()
    rec.record_span("root", kind="job", trace_id=tid, t0=0.0, t1=1.0)
    rec.event("submit", kind="scheduler", trace_id=tid, ts=0.0)
    lines = [
        json.loads(x)
        for x in open(path, encoding="utf-8").read().splitlines()
        if x.strip()
    ]
    assert lines
    for rec_line in lines:
        assert rec_line["schema_version"] == SCHEMA_VERSION


def test_ingester_rejects_unknown_schema_accepts_legacy(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    good = {"record": "span", "name": "s", "kind": "job", "span_id": "a",
            "trace_id": "t", "parent_id": None, "t0": 0.0, "t1": 1.0,
            "schema_version": SCHEMA_VERSION}
    legacy = dict(good, span_id="b")
    legacy.pop("schema_version")
    future = dict(good, span_id="c", schema_version=99)
    bad_type = dict(good, span_id="d", schema_version="one")
    with open(path, "w", encoding="utf-8") as f:
        for rec_line in (good, legacy, future, bad_type):
            f.write(json.dumps(rec_line) + "\n")
    records, stats = read_recorder_jsonl(path)
    assert stats["accepted"] == 2  # good + legacy
    assert stats["legacy_lines"] == 1
    assert stats["skipped_by_reason"] == {"unknown_schema": 2}
    assert [r["span_id"] for r in records] == ["a", "b"]


# -- ingestion hardening: rotation + torn tails -------------------------------


def _span_line(i, t0=0.0, t1=1.0):
    return json.dumps({
        "record": "span", "name": f"s{i}", "kind": "job",
        "span_id": f"sp-{i}", "trace_id": "t", "parent_id": None,
        "t0": t0, "t1": t1, "schema_version": SCHEMA_VERSION,
    })


def test_rotated_files_read_oldest_first(tmp_path):
    path = str(tmp_path / "trace.jsonl")
    with open(path + ".1", "w", encoding="utf-8") as f:
        f.write(_span_line(1) + "\n" + _span_line(2) + "\n")
    with open(path, "w", encoding="utf-8") as f:
        f.write(_span_line(3) + "\n")
    records, stats = read_recorder_jsonl(path)
    assert stats["files"] == 2
    assert [r["span_id"] for r in records] == ["sp-1", "sp-2", "sp-3"]


def test_torn_tail_and_parse_errors_skipped_not_raised(tmp_path):
    twin._reset_stats_for_tests()
    path = str(tmp_path / "trace.jsonl")
    with open(path + ".1", "w", encoding="utf-8") as f:
        f.write(_span_line(1) + "\n")
        f.write("{corrupt mid-file}\n")  # parse_error: not the live tail
    with open(path, "w", encoding="utf-8") as f:
        f.write(_span_line(2) + "\n")
        f.write(json.dumps({"record": "gc", "schema_version": 1}) + "\n")
        # Mid-append capture: the final line of the live file is truncated.
        f.write(_span_line(3)[: len(_span_line(3)) // 2])
    records, stats = read_recorder_jsonl(path)
    assert [r["span_id"] for r in records] == ["sp-1", "sp-2"]
    assert stats["skipped"] == 3
    assert stats["skipped_by_reason"] == {
        "parse_error": 1, "unknown_record": 1, "torn_tail": 1,
    }
    st = twin.twin_stats()
    assert st["ingest_files_total"] == 2
    assert st["ingest_skipped_lines_total"] == 3
    assert st["ingest_skipped_by_reason"]["torn_tail"] == 1
    assert st["ingest_skipped_by_reason"]["parse_error"] == 1


def test_torn_tail_only_applies_to_live_file_final_line(tmp_path):
    # A truncated final line of the *rotated* file is a parse error — only
    # the live file can be captured mid-append.
    path = str(tmp_path / "trace.jsonl")
    with open(path + ".1", "w", encoding="utf-8") as f:
        f.write(_span_line(1)[:20])  # no trailing newline
    with open(path, "w", encoding="utf-8") as f:
        f.write(_span_line(2) + "\n")
    _, stats = read_recorder_jsonl(path)
    assert stats["skipped_by_reason"] == {"parse_error": 1}


def test_missing_file_is_empty_workload(tmp_path):
    records, stats = read_recorder_jsonl(str(tmp_path / "absent.jsonl"))
    assert records == [] and stats["files"] == 0
    w = ReplayWorkload(records, stats)
    assert w.t_range == (0.0, 0.0)
    out = TwinEngine().replay(w)
    assert out["spans_replayed"] == 0 and out["traces"] == {}


# -- recorded chaos trace fixture --------------------------------------------


@pytest.fixture(scope="module")
def chaos_jsonl(tmp_path_factory):
    """A seeded self-heal run recorded to JSONL — the replay fixture."""
    path = str(tmp_path_factory.mktemp("twin") / "chaos.jsonl")
    params = TrainTwinParams()
    rec = FlightRecorder(
        max_spans=16384, max_events=16384, clock=lambda: 0.0,
        id_factory=deterministic_ids("src"), persist_path=path,
        persist_max_bytes=64 * 1024 * 1024,
    )
    tid = rec.new_trace_id()
    events = chip_fault_timeline(0, 12, params)
    heal = replay_self_heal(events, params, recorder=rec, trace_id=tid)
    source = goodput_lane(rec, tid, heal["wall_s"], full_gang=params.n_chips)
    return {"path": path, "trace_id": tid, "heal": heal, "source": source,
            "params": params}


def test_replay_reconstructs_workload_views(chaos_jsonl):
    w = ReplayWorkload.from_jsonl(chaos_jsonl["path"])
    assert w.ingest["skipped"] == 0
    assert len(w.jobs) == 1
    job = w.jobs[0]
    assert job["trace_id"] == chaos_jsonl["trace_id"]
    assert job["name"] == "job:chaos-self-heal"
    assert int(job["gang"]) == chaos_jsonl["params"].n_chips
    assert len(w.faults) == chaos_jsonl["heal"]["faults"]
    lo, hi = w.t_range
    # The goodput lane's counter-track events land on bucket boundaries,
    # so the trace horizon rounds up past the job's own wall clock.
    assert lo == 0.0 and hi >= chaos_jsonl["heal"]["wall_s"]


def test_replay_is_deterministic_byte_identical(chaos_jsonl):
    """Satellite 3: the same trace replayed twice produces byte-identical
    event orderings and identical goodput decompositions."""
    w = ReplayWorkload.from_jsonl(chaos_jsonl["path"])
    e1, e2 = TwinEngine(), TwinEngine()
    out1, out2 = e1.replay(w), e2.replay(w)
    s1 = json.dumps(e1.recorder.spans(limit=0), sort_keys=True)
    s2 = json.dumps(e2.recorder.spans(limit=0), sort_keys=True)
    assert s1 == s2
    ev1 = json.dumps(e1.recorder.events(limit=0), sort_keys=True)
    ev2 = json.dumps(e2.recorder.events(limit=0), sort_keys=True)
    assert ev1 == ev2
    assert out1["traces"] == out2["traces"]
    assert out1["spans_replayed"] == out2["spans_replayed"]


def test_replayed_self_heal_chain_causally_intact(chaos_jsonl):
    """Satellite 3: after ingest + replay, every fault's recovery chain
    detect → emergency_save → requeue → shrink_admit → compile → resume
    still links parent-to-child on the replayed recorder."""
    w = ReplayWorkload.from_jsonl(chaos_jsonl["path"])
    engine = TwinEngine()
    engine.replay(w)
    spans = engine.recorder.spans(limit=0)
    by_id = {s["span_id"]: s for s in spans}
    roots = [s for s in spans if s["name"] == "job:chaos-self-heal"]
    assert len(roots) == 1
    root = roots[0]
    detects = sorted(
        (s for s in spans if s["name"] == "detect"), key=lambda s: s["t0"]
    )
    assert len(detects) == chaos_jsonl["heal"]["faults"] > 0
    chain = ("emergency_save", "requeue", "shrink_admit", "compile", "resume")
    for detect in detects:
        assert by_id[detect["parent_id"]] is root
        tail = detect
        for name in chain:
            children = [
                s for s in spans
                if s["parent_id"] == tail["span_id"] and s["name"] == name
            ]
            assert len(children) == 1, (name, tail["name"])
            child = children[0]
            assert child["t0"] >= tail["t0"]
            tail = child
        assert tail["kind"] == "supervisor"
    # Grow-backs chain off a resume (or the root before the first fault).
    for grow in (s for s in spans if s["name"] == "grow_back"):
        parent = by_id[grow["parent_id"]]
        assert parent["name"] in ("resume", "job:chaos-self-heal")


def test_replay_fidelity_within_one_percent_and_fast(chaos_jsonl):
    """Acceptance gates: replayed decomposition within 1% of the source
    per category; >= 1000 simulated fleet-seconds per CPU-second."""
    w = ReplayWorkload.from_jsonl(chaos_jsonl["path"])
    engine = TwinEngine()
    out = engine.replay(w)
    side = out["traces"][chaos_jsonl["trace_id"]]
    source = chaos_jsonl["source"]
    # The source lane reports the fraction rounded to 4 decimals.
    assert side["goodput_fraction"] == pytest.approx(
        source["goodput_fraction"], abs=1e-4
    )
    diff = decomposition_diff(
        source["breakdown_s"], side["categories"], source["wall_s"]
    )
    assert diff["max_error_pct"] < 1.0
    assert out["fleet_seconds_per_cpu_second"] >= 1000.0


def test_replay_fidelity_end_to_end():
    fid = replay_fidelity(seed=0)
    assert fid["max_error_pct"] < 1.0
    assert fid["fleet_seconds_per_cpu_second"] >= 1000.0
    assert fid["ingest"]["skipped"] == 0
    assert fid["replay_goodput_fraction"] == pytest.approx(
        fid["source_goodput_fraction"], abs=1e-3
    )


def test_replay_bumps_health_counters(chaos_jsonl):
    twin._reset_stats_for_tests()
    w = ReplayWorkload.from_jsonl(chaos_jsonl["path"])
    TwinEngine().replay(w)
    st = twin.twin_stats()
    assert st["replays_total"] == 1
    assert st["replayed_spans_total"] == len(w.spans)
    assert st["replayed_events_total"] == len(w.events)
    assert st["fleet_seconds_total"] > 0.0
    assert st["last_fleet_seconds_per_cpu_second"] > 0.0


# -- synthetic traffic generators --------------------------------------------


def test_bursty_generator_matches_legacy_serving_sim():
    """The sims' seeded request traces must reproduce byte-for-byte
    through the shared generator (rng draw order is the contract)."""
    from benchmarks import serving_fleet_sim as sim

    assert sim.request_trace(3) == bursty_arrivals(
        3,
        duration_s=sim.SIM_DURATION_S,
        base_rps=sim.BASE_RATE_RPS,
        burst_rps=sim.BURST_RATE_RPS,
        burst_every_s=sim.BURST_EVERY_S,
        burst_len_s=sim.BURST_LEN_S,
        n_prefixes=sim.N_PREFIXES,
        prefix_len=sim.PREFIX_LEN,
        mean_new_tokens=sim.MEAN_NEW_TOKENS,
    )
    # The long-prefill trace draws from an offset seed stream so the two
    # legacy generators stay independent for the same seed.
    long_trace = sim.long_prefill_trace(5)
    assert long_trace and all("prefill_units" in r for r in long_trace)
    assert long_trace != sim.long_prefill_trace(6)
    assert sim.long_prefill_trace(5) == long_trace  # deterministic


def test_generators_are_seeded_and_shaped():
    bursty = bursty_arrivals(1, duration_s=120.0)
    assert bursty == bursty_arrivals(1, duration_s=120.0)
    assert bursty != bursty_arrivals(2, duration_s=120.0)
    assert all(r["n_new"] >= 8 and r["prompt"] for r in bursty)
    diurnal = diurnal_arrivals(1, duration_s=300.0)
    assert all(0.0 <= r["t"] < 300.0 for r in diurnal)
    heavy = heavy_tail_prefill_arrivals(1, duration_s=300.0)
    assert all(r["prefill_units"] >= 0.3 for r in heavy)
    # Pareto tail: the max prefill dwarfs the median.
    units = sorted(r["prefill_units"] for r in heavy)
    assert units[-1] > 4.0 * units[len(units) // 2]


# -- A/B scorecards -----------------------------------------------------------


def test_policy_scorecard_measures_real_deltas():
    card = default_policy_scorecard(seed=0)
    v = card["variants"]
    assert card["baseline"] == "ckpt100_index_on"
    assert set(v) == {"ckpt100_index_on", "ckpt50_index_on",
                      "ckpt200_index_on", "ckpt100_index_off"}
    # Checkpoint interval trades checkpoint time against... nothing here
    # (no lost steps), so the 200-step variant wins goodput.
    assert v["ckpt200_index_on"]["goodput_fraction"] > (
        v["ckpt50_index_on"]["goodput_fraction"]
    )
    # Warm compile index beats cold resumes on both goodput and MTTR.
    assert v["ckpt100_index_on"]["goodput_fraction"] > (
        v["ckpt100_index_off"]["goodput_fraction"]
    )
    assert v["ckpt100_index_on"]["mttr_mean_s"] < (
        v["ckpt100_index_off"]["mttr_mean_s"]
    )
    assert v["ckpt100_index_off"]["cold_resumes"] > 0
    assert v["ckpt100_index_on"]["warm_resumes"] > 0
    deltas = card["deltas_vs_baseline"]
    assert deltas["ckpt100_index_off"]["goodput_fraction"] < 0.0
    # Scorecards are deterministic run-to-run (cpu_s is wall time).
    again = default_policy_scorecard(seed=0)
    assert again["variants"] == card["variants"]
    assert again["deltas_vs_baseline"] == card["deltas_vs_baseline"]


def test_twin_bench_line_gates_all_pass():
    line = twin_bench_line(seed=0)
    assert line["metric"] == "twin_replay_policy_ab"
    assert line["gates"] == {
        "replay_within_1pct": True,
        "replay_fast_enough": True,
        "policy_delta_measured": True,
        "warm_beats_fifo": True,
    }
    assert line["ok"] is True
    assert line["ab_wait_warm_s"] < line["ab_wait_fifo_s"]
    assert line["ingest_skipped_lines"] == 0


# -- HTTP surface -------------------------------------------------------------


def test_twin_router_replay_endpoint(chaos_jsonl):
    from aiohttp.test_utils import TestClient, TestServer, loop_context

    from backend.main import create_app

    with loop_context() as loop:
        async def go():
            client = TestClient(TestServer(create_app()))
            await client.start_server()
            try:
                r = await client.get("/api/v1/twin")
                assert r.status == 200
                doc = await r.json()
                assert doc["schema_version"] == SCHEMA_VERSION
                r = await client.post(
                    "/api/v1/twin/replay",
                    json={"path": chaos_jsonl["path"]},
                )
                assert r.status == 200
                out = await r.json()
                assert out["dry_run"] is True
                assert out["spans_replayed"] > 0
                assert chaos_jsonl["trace_id"] in out["traces"]
                assert out["jobs"] == 1
                assert out["traces_truncated"] == 0
                r = await client.post(
                    "/api/v1/twin/replay",
                    json={"path": chaos_jsonl["path"] + ".nope"},
                )
                assert r.status == 404
                r = await client.post(
                    "/api/v1/twin/replay",
                    json={"path": chaos_jsonl["path"], "bucket_s": -1},
                )
                assert r.status == 400
            finally:
                await client.close()

        loop.run_until_complete(go())


def test_rotation_produces_readable_generations(tmp_path):
    """The recorder's own size-based rotation yields the path+'.1' layout
    the ingester reads — record enough spans to force at least one roll."""
    path = str(tmp_path / "rot.jsonl")
    rec = FlightRecorder(
        clock=lambda: 0.0, persist_path=path, persist_max_bytes=4096,
    )
    tid = rec.new_trace_id()
    for i in range(200):
        rec.record_span(
            f"s{i}", kind="step", trace_id=tid, t0=float(i), t1=float(i) + 0.5,
        )
    assert os.path.exists(path + ".1")
    records, stats = read_recorder_jsonl(path)
    assert stats["files"] == 2
    assert stats["skipped"] == 0
    # Oldest-first ordering across generations by construction time.
    t0s = [r["t0"] for r in records if r.get("record") == "span"]
    assert t0s == sorted(t0s)
