"""Pipeline parallelism: GPipe schedule over the ``pipe`` mesh axis.

The reference only claims PP in a docstring (``deepspeed_launcher.py:8``);
here it is real, so these tests hold it to the strictest standard available:
bit-level agreement with the non-pipelined gradient-accumulation path (the
same math, a different schedule), on the 8-virtual-device CPU mesh.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.models import transformer as tfm
from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
from tpu_engine.train import build_train_program


def _cfg(mesh, model_name="gpt-tiny", **kw):
    base = dict(
        model_name=model_name,
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=mesh,
        micro_batch_size=2,
        gradient_accumulation_steps=4,
        seq_len=64,
        precision=Precision.FP32,
        param_dtype=Precision.FP32,
        activation_checkpointing=True,
        total_steps=10,
        # Pin: these tests exercise specific schedules; "auto" (the config
        # default) would resolve accum=4 > pipe=2 to 1f1b and silently
        # change what the gpipe tests cover (see test_auto_schedule_*).
        pipeline_schedule="gpipe",
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def _run(cfg, n_steps=3):
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    out = []
    for i in range(n_steps):
        state, m = prog.step(state, prog.synthetic_batch(seed=i))
        out.append((float(m["loss"]), float(m["grad_norm"])))
    return prog, out


def test_pipeline_matches_accumulation_exactly():
    """Same dp extent (data*fsdp=4), pipe=2 vs pipe=1: identical synthetic
    batches, so losses and grad norms must agree to float32 tolerance."""
    _, pipe = _run(_cfg(MeshConfig(data=2, fsdp=2, pipe=2)))
    _, ref = _run(_cfg(MeshConfig(data=2, fsdp=2, model=2)))
    np.testing.assert_allclose(
        [l for l, _ in pipe], [l for l, _ in ref], rtol=2e-5
    )
    np.testing.assert_allclose(
        [g for _, g in pipe], [g for _, g in ref], rtol=2e-4
    )


def test_pipeline_with_tensor_parallel_and_fsdp():
    prog, out = _run(_cfg(MeshConfig(data=1, fsdp=2, pipe=2, model=2)), n_steps=4)
    losses = [l for l, _ in out]
    assert all(np.isfinite(losses))
    # Layer params are sharded over pipe: check the stage dim placement.
    import jax.sharding as jsh

    q_sharding = prog.state_shardings["params"]["layers"]["q"]["kernel"]
    assert q_sharding.spec[0] == "pipe"


def test_pipeline_with_ring_attention():
    """pipe=2 × sequence=2: the stage vmap composes over the ring shard_map."""
    _, out = _run(_cfg(MeshConfig(data=1, fsdp=2, pipe=2, sequence=2)), n_steps=2)
    assert all(np.isfinite(l) for l, _ in out)


def test_pipeline_moe_expert_parallel():
    _, out = _run(
        _cfg(MeshConfig(data=1, fsdp=2, pipe=2, model=2), model_name="moe-tiny"),
        n_steps=2,
    )
    assert all(np.isfinite(l) for l, _ in out)


def test_pipeline_loss_decreases():
    cfg = _cfg(
        MeshConfig(data=2, fsdp=2, pipe=2),
        learning_rate=1e-2,
        warmup_steps=1,
        total_steps=8,
    )
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    batch = prog.synthetic_batch(seed=0)  # fixed batch → should overfit
    first = last = None
    for _ in range(8):
        state, m = prog.step(state, batch)
        last = float(m["loss"])
        first = first if first is not None else last
    assert last < first - 0.5, f"loss did not decrease: {first} -> {last}"


def test_pipeline_rejects_indivisible_layers():
    with pytest.raises(ValueError, match="divisible"):
        build_train_program(
            _cfg(MeshConfig(data=2, fsdp=1, pipe=4), model_name="gpt-tiny")
        )  # gpt-tiny has 2 layers, pipe=4


def test_stage_layer_stack_shapes():
    from tpu_engine.parallel.pipeline import stage_layer_stack

    cfg = tfm.MODEL_CONFIGS["gpt-tiny"]
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    staged = stage_layer_stack(params["layers"], 2, cfg.n_layers)
    q = staged["q"]["kernel"]
    assert q.shape[:2] == (2, cfg.n_layers // 2)
    with pytest.raises(ValueError, match="divisible"):
        stage_layer_stack(params["layers"], 3, cfg.n_layers)


def test_pipeline_gpt2_arch():
    """GPT-2 blocks (biases, LayerNorm, learned positions) stream through
    the GPipe schedule identically to the accumulation path."""
    pipe = _run(_cfg(MeshConfig(data=2, fsdp=2, pipe=2), model_name="gpt2-tiny"))[1]
    ref = _run(_cfg(MeshConfig(data=2, fsdp=2, model=2), model_name="gpt2-tiny"))[1]
    np.testing.assert_allclose([l for l, _ in pipe], [l for l, _ in ref], rtol=2e-5)


# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow


# -- 1F1B schedule + flash under PP (round 3, VERDICT r2 item 5) -------------


def test_1f1b_matches_gpipe_and_accumulation_exactly():
    """The 1F1B schedule is the same math as GPipe on a different timetable:
    losses and grad norms must agree with BOTH the GPipe pipeline and the
    non-pipelined accumulation path across multiple optimizer steps."""
    _, fb = _run(_cfg(MeshConfig(data=2, fsdp=2, pipe=2),
                      pipeline_schedule="1f1b"))
    _, gp = _run(_cfg(MeshConfig(data=2, fsdp=2, pipe=2)))
    _, ref = _run(_cfg(MeshConfig(data=2, fsdp=2, model=2)))
    np.testing.assert_allclose([l for l, _ in fb], [l for l, _ in gp], rtol=1e-6)
    np.testing.assert_allclose([g for _, g in fb], [g for _, g in gp], rtol=2e-5)
    np.testing.assert_allclose([l for l, _ in fb], [l for l, _ in ref], rtol=2e-5)
    np.testing.assert_allclose([g for _, g in fb], [g for _, g in ref], rtol=2e-4)


def test_1f1b_trains_and_loss_decreases():
    cfg = _cfg(MeshConfig(data=1, fsdp=2, model=2, pipe=2),
               pipeline_schedule="1f1b", learning_rate=1e-2, warmup_steps=1)
    prog = build_train_program(cfg)
    state = prog.init(jax.random.PRNGKey(0))
    batch = prog.synthetic_batch(seed=0)  # fixed batch → loss must drop
    losses = []
    for _ in range(6):
        state, m = prog.step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.05, losses


def test_1f1b_moe_aux_gradients_match_gpipe():
    """MoE under 1F1B: the router aux-loss cotangent is threaded manually
    (aux_cotangent); grads must match GPipe's autodiff."""
    _, fb = _run(_cfg(MeshConfig(data=1, fsdp=2, model=2, pipe=2),
                      model_name="moe-tiny", pipeline_schedule="1f1b"),
                 n_steps=2)
    _, gp = _run(_cfg(MeshConfig(data=1, fsdp=2, model=2, pipe=2),
                      model_name="moe-tiny"), n_steps=2)
    np.testing.assert_allclose([l for l, _ in fb], [l for l, _ in gp], rtol=1e-5)
    np.testing.assert_allclose([g for _, g in fb], [g for _, g in gp], rtol=5e-5)


def test_flash_attention_under_pipeline():
    """The Pallas kernel (interpret off-TPU) under the pipe-vmapped stage:
    spmd_axis_name threads the pipe axis into the kernel's shard_map specs.
    Numerics must match the XLA-attention pipeline."""
    _, fl = _run(_cfg(MeshConfig(data=1, fsdp=2, model=2, pipe=2),
                      seq_len=128, attention_impl="flash",
                      precision=Precision.BF16), n_steps=2)
    _, xl = _run(_cfg(MeshConfig(data=1, fsdp=2, model=2, pipe=2),
                      seq_len=128, attention_impl="xla",
                      precision=Precision.BF16), n_steps=2)
    np.testing.assert_allclose([l for l, _ in fl], [l for l, _ in xl],
                               rtol=2e-3)


def test_auto_schedule_selection():
    """pipeline_schedule="auto" (the default) resolves at build time:
    zb — the zero-bubble schedule, which strictly dominates 1f1b — exactly
    when the microbatch count exceeds the stage count (the regime where
    the O(P) activation residency frees real memory — measured in
    benchmarks/RESULTS.md §Pipeline), gpipe otherwise, and gpipe whenever
    the manual-vjp schedules lack a requested feature. (Config-only
    resolution is covered fast in test_pipeline_zb.py; this asserts the
    built program agrees.)"""
    mesh = MeshConfig(data=2, fsdp=2, pipe=2)
    # M=4 > P=2 → zb.
    assert build_train_program(
        _cfg(mesh, pipeline_schedule="auto")
    ).pipeline_schedule == "zb"
    # M=2 <= P=2 → gpipe (warmup/drain overhead, no memory win).
    assert build_train_program(
        _cfg(mesh, pipeline_schedule="auto", gradient_accumulation_steps=2)
    ).pipeline_schedule == "gpipe"
    # No pipe axis → schedule is irrelevant; resolves to gpipe.
    assert build_train_program(
        _cfg(MeshConfig(data=2, fsdp=2, model=2), pipeline_schedule="auto")
    ).pipeline_schedule == "gpipe"
    # Features the manual-vjp schedule lacks force gpipe instead of
    # erroring (explicit "1f1b" still errors — tests below).
    assert build_train_program(
        _cfg(mesh, pipeline_schedule="auto", loss_chunk_size=32)
    ).pipeline_schedule == "gpipe"
    assert build_train_program(
        _cfg(mesh, pipeline_schedule="auto", precision=Precision.BF16,
             param_dtype=Precision.FP32, grad_allreduce_dtype="bf16")
    ).pipeline_schedule == "gpipe"
    # Explicit choices are honoured verbatim.
    assert build_train_program(
        _cfg(mesh, pipeline_schedule="1f1b")
    ).pipeline_schedule == "1f1b"
    assert build_train_program(_cfg(mesh)).pipeline_schedule == "gpipe"


def test_1f1b_rejects_loss_chunking():
    with pytest.raises(ValueError, match="loss_chunk_size"):
        build_train_program(_cfg(MeshConfig(data=2, fsdp=2, pipe=2),
                                 pipeline_schedule="1f1b",
                                 loss_chunk_size=32))


def test_1f1b_rejects_reduced_comm_dtype():
    with pytest.raises(ValueError, match="grad_allreduce_dtype"):
        build_train_program(_cfg(MeshConfig(data=2, fsdp=2, pipe=2),
                                 pipeline_schedule="1f1b",
                                 precision=Precision.BF16,
                                 param_dtype=Precision.FP32,
                                 grad_allreduce_dtype="bf16"))
