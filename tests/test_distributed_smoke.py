"""Two-process jax.distributed rendezvous smoke test (VERDICT round-1
weak #5): drives ``initialize_distributed`` + ``build_mesh`` across REAL
process boundaries on CPU — the same coordinator path the GKE JobSet
(infra/tpu-jobset.yaml) relies on, exercised without a cluster.
"""

import socket
import subprocess
import sys

import pytest

pytestmark = pytest.mark.slow

_CHILD = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_cpu_collectives_implementation", "gloo")
import numpy as np

from tpu_engine.mesh_runtime import MeshConfig, build_mesh, initialize_distributed

pid = int(sys.argv[1])
coord = sys.argv[2]
ok = initialize_distributed(
    coordinator_address=coord, num_processes=2, process_id=pid
)
assert ok, "initialize_distributed returned False with explicit coordinator"
assert jax.process_count() == 2, jax.process_count()
assert jax.local_device_count() == 2
assert jax.device_count() == 4

from jax.sharding import NamedSharding, PartitionSpec as P

mesh = build_mesh(MeshConfig(data=-1))
assert mesh.devices.shape[0] == 4  # data axis absorbed all four devices

# A global array assembled from per-process shards, reduced with a real
# cross-process collective.
sharding = NamedSharding(mesh, P(("data", "fsdp", "pipe", "sequence", "model")))
global_data = np.arange(8, dtype=np.float32)
arr = jax.make_array_from_callback(
    global_data.shape, sharding, lambda idx: global_data[idx]
)
total = jax.jit(lambda a: a.sum(), out_shardings=NamedSharding(mesh, P()))(arr)
assert float(total) == float(global_data.sum()), float(total)

# The real thing: a FULL sharded train step (ZeRO-3 over data+fsdp spanning
# both processes) — the exact path a GKE JobSet worker runs.
from tpu_engine.mesh_runtime import MeshRuntime
from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
from tpu_engine.train import build_train_program

cfg = TPUTrainConfig(
    model_name="gpt-tiny", sharding_stage=ShardingStage.FULL_PARTITIONING,
    mesh=MeshConfig(data=2, fsdp=2), micro_batch_size=1, seq_len=32,
    precision=Precision.FP32, activation_checkpointing=False,
)
prog = build_train_program(cfg, runtime=MeshRuntime(cfg.mesh))
state = prog.init(jax.random.PRNGKey(0))
batch = prog.synthetic_batch(0)
state, metrics = prog.step(state, batch)
loss = float(jax.device_get(metrics["loss"]))
assert 5.0 < loss < 8.0, loss  # ~ln(512) on synthetic tokens
print(f"child {pid} loss {loss:.4f}", flush=True)

# File-backed input across process boundaries: each process reads ONLY its
# row block (sharded reads, VERDICT r2 weak #5), and the assembled global
# batch drives a real step on both processes.
from tpu_engine.data import TokenFileDataset, make_data_fn

token_path = sys.argv[3]
ds = TokenFileDataset(token_path, seq_len=32)
fn = make_data_fn(prog, ds, seed=11)
fbatch = fn(0)
assert fbatch.shape == prog.global_batch_shape()
state, metrics = prog.step(state, fbatch)
floss = float(jax.device_get(metrics["loss"]))
print(f"child {pid} fileloss {floss:.4f}", flush=True)
ds.close()

# Multi-host disk-tier optimizer spill (round 5 — DeepSpeed's NVMe tier
# works multi-node; so does this one): each process spills only the
# master SHARDS its devices hold under spill_dir/proc{k}, the host AdamW
# walks them with zero cross-host communication, and the updated blocks
# stitch back into the global sharded params. Parity: losses must match
# the in-memory optax chain step for step.
import glob
spill_dir = sys.argv[4]
dcfg = cfg.model_copy(update={
    "optimizer_offload": "disk", "optimizer_spill_dir": spill_dir,
})
ref_prog = build_train_program(cfg, runtime=MeshRuntime(cfg.mesh))
ref_state = ref_prog.init(jax.random.PRNGKey(7))
disk_prog = build_train_program(dcfg, runtime=MeshRuntime(dcfg.mesh))
disk_state = disk_prog.init(jax.random.PRNGKey(7))
for i in range(2):
    b = ref_prog.synthetic_batch(i)
    ref_state, ref_m = ref_prog.step(ref_state, b)
    disk_state, disk_m = disk_prog.step(disk_state, b)
    rl = float(jax.device_get(ref_m["loss"]))
    dl = float(jax.device_get(disk_m["loss"]))
    assert abs(rl - dl) < 1e-4, (i, rl, dl)
assert disk_prog.disk_store.step_on_disk == 2
my_slabs = glob.glob(os.path.join(spill_dir, f"proc{pid}", "*.master.f32"))
assert my_slabs, f"process {pid} spilled no master slabs"
# Loss LAST on the line: the parent's parity check compares the final
# token across processes.
print(f"child {pid} slabs {len(my_slabs)} diskloss {dl:.4f}", flush=True)

# Cross-host attach consensus: tear ONE host's spill (drop its meta) and
# rebuild — BOTH hosts must reseed fresh (a warm host stitching its old
# moments against a fresh host's zeroed ones would silently mix
# trajectories). The allgather in train._all_hosts is what enforces it.
if pid == 0:
    os.remove(os.path.join(spill_dir, "proc0", "disk_adamw.json"))
disk_prog2 = build_train_program(dcfg, runtime=MeshRuntime(dcfg.mesh))
disk_state2 = disk_prog2.init(jax.random.PRNGKey(7))
st2 = disk_prog2.disk_store
assert not st2.attached, f"pid {pid}: attached warm despite peer's torn spill"
assert st2.moment_steps == 0
print(f"child {pid} consensus ok", flush=True)
print(f"child {pid} ok", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_rendezvous_and_collective(tmp_path):
    coord = f"127.0.0.1:{_free_port()}"
    env_base = {
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
        "JAX_PLATFORMS": "cpu",
    }
    import os

    import numpy as np

    from tpu_engine.data import write_token_file

    token_path = str(tmp_path / "toks.bin")
    write_token_file((np.arange(4096) % 512).astype(np.uint16), token_path)

    spill_dir = str(tmp_path / "spill")
    procs = []
    for pid in (0, 1):
        env = dict(os.environ)
        env.update(env_base)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _CHILD, str(pid), coord, token_path,
                 spill_dir],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
                env=env, cwd=os.path.dirname(os.path.dirname(__file__)),
            )
        )
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=360)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("distributed smoke test timed out (rendezvous hang?)")
        outs.append(out)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"child {pid} failed:\n{out[-3000:]}"
        assert f"child {pid} ok" in out
    # Both processes computed the same global loss (one SPMD program) —
    # for the synthetic step, the file-backed sharded-read step, AND the
    # multi-host disk-tier step.
    for tag in (" loss ", " fileloss ", " diskloss "):
        losses = {
            line.split()[-1]
            for out in outs
            for line in out.splitlines()
            if tag in line
        }
        assert len(losses) == 1, (tag, losses)
