"""Fleet-historian invariants: rollup-tier conservation against the raw
ring, the range-query engine (aggs, tier selection, approx degradation),
bounded memory under a 10k-tick scrape sim, virtual-clock determinism
(explicit timestamps never consult the wall clock), incident stitching
across every chaos fault kind, and the twin chaos-replay fidelity gate.

Everything runs on a virtual clock — no sleeps, no wall-clock reads."""

import pytest

from tpu_engine.faults import FaultKind
from tpu_engine.historian import (
    DEFAULT_TIERS,
    IncidentCorrelator,
    MetricHistorian,
    percentile,
)


def _forbidden_clock() -> float:
    raise AssertionError("historian consulted the wall clock")


def _fill(hist, name, pairs, labels=None):
    for ts, v in pairs:
        hist.record(name, v, ts=ts, labels=labels)


# ---------------------------------------------------------------------------
# Rollup conservation: every tier is an exact fold of the raw samples.
# ---------------------------------------------------------------------------


def test_rollup_tiers_conserve_raw_samples():
    hist = MetricHistorian(raw_capacity=4096, clock=_forbidden_clock)
    samples = [(i * 0.7, float((i * 37) % 101) - 50.0) for i in range(500)]
    _fill(hist, "m", samples)
    for width, _max_buckets in DEFAULT_TIERS:
        buckets = hist.buckets("m", width)
        assert buckets, f"tier {width} retained nothing"
        assert sum(b["count"] for b in buckets) == len(samples)
        assert sum(b["sum"] for b in buckets) == pytest.approx(
            sum(v for _, v in samples)
        )
        assert min(b["min"] for b in buckets) == min(v for _, v in samples)
        assert max(b["max"] for b in buckets) == max(v for _, v in samples)
        for b in buckets:
            inside = [
                v for ts, v in samples
                if b["t0"] <= ts < b["t0"] + b["width_s"]
            ]
            assert b["count"] == len(inside)
            assert b["sum"] == pytest.approx(sum(inside))
            assert b["min"] == min(inside)
            assert b["max"] == max(inside)
            assert b["first"] == inside[0]
            assert b["last"] == inside[-1]


def test_coarser_tier_is_fold_of_finer_tier():
    hist = MetricHistorian(clock=_forbidden_clock)
    _fill(hist, "m", [(i * 1.3, float(i % 17)) for i in range(400)])
    fine = hist.buckets("m", 10.0)
    coarse = hist.buckets("m", 60.0)
    for cb in coarse:
        members = [
            fb for fb in fine
            if cb["t0"] <= fb["t0"] < cb["t0"] + 60.0
        ]
        assert cb["count"] == sum(fb["count"] for fb in members)
        assert cb["sum"] == pytest.approx(sum(fb["sum"] for fb in members))
        assert cb["min"] == min(fb["min"] for fb in members)
        assert cb["max"] == max(fb["max"] for fb in members)


# ---------------------------------------------------------------------------
# Query engine
# ---------------------------------------------------------------------------


def test_query_raw_aggregates():
    hist = MetricHistorian(clock=_forbidden_clock)
    _fill(hist, "m", [(float(i), float(i)) for i in range(10)])
    q = hist.query("m", t0=2.0, t1=7.0, agg="avg", tier="raw")
    assert q["tier"] == "raw" and not q["approx"]
    assert q["count"] == 6
    assert q["value"] == pytest.approx(4.5)
    assert q["aggregates"] == {
        "count": 6, "sum": 27.0, "avg": 4.5, "min": 2.0, "max": 7.0,
        "last": 7.0,
    }
    assert q["points"] == [[float(i), float(i)] for i in range(2, 8)]
    assert hist.query("m", t0=0.0, t1=9.0, agg="sum")["value"] == 45.0
    assert hist.query("m", t0=0.0, t1=9.0, agg="count")["value"] == 10
    assert hist.query("m", t0=0.0, t1=9.0, agg="last")["value"] == 9.0


def test_query_rate_and_p99():
    hist = MetricHistorian(clock=_forbidden_clock)
    _fill(hist, "c", [(0.0, 0.0), (10.0, 50.0)])
    assert hist.query("c", t0=0.0, t1=10.0, agg="rate")["value"] == 5.0
    # Single point: no rate.
    _fill(hist, "one", [(0.0, 1.0)])
    assert hist.query("one", t0=0.0, t1=1.0, agg="rate")["value"] is None
    _fill(hist, "p", [(0.0, 0.0), (1.0, 100.0)])
    assert hist.query("p", t0=0.0, t1=1.0, agg="p99")["value"] == (
        pytest.approx(99.0)
    )
    assert percentile([0.0, 100.0], 0.5) == 50.0


def test_query_defaults_trailing_window_and_unknowns_raise():
    hist = MetricHistorian(clock=_forbidden_clock)
    _fill(hist, "m", [(1000.0, 1.0), (1500.0, 2.0), (2000.0, 3.0)])
    # t1 defaults to the series' last_ts, t0 to t1 - 600 — no clock read.
    q = hist.query("m")
    assert (q["t0"], q["t1"]) == (1400.0, 2000.0)
    assert q["count"] == 2
    with pytest.raises(ValueError):
        hist.query("m", agg="median")
    with pytest.raises(ValueError):
        hist.query("m", tier="5m")
    missing = hist.query("nope")
    assert missing["value"] is None and missing["count"] == 0


def test_query_auto_falls_back_to_rollup_when_ring_wraps():
    hist = MetricHistorian(raw_capacity=16, clock=_forbidden_clock)
    _fill(hist, "m", [(float(i), float(i)) for i in range(200)])
    # Ring wrapped: raw no longer covers t0=0, auto serves a rollup tier.
    q = hist.query("m", t0=0.0, t1=199.0, agg="avg", tier="auto")
    assert q["tier"] in ("10s", "1m") and q["approx"]
    assert q["count"] > 16  # rollups retained what the ring dropped
    assert q["value"] == pytest.approx(sum(range(200)) / 200)
    # p99 degrades to the bucket max (upper bound) and is marked approx.
    p = hist.query("m", t0=0.0, t1=150.0, agg="p99", tier="1m")
    assert p["approx"] and p["value"] >= 149.0
    # An explicit raw query still answers from what the ring kept.
    r = hist.query("m", t0=0.0, t1=199.0, tier="raw")
    assert r["count"] == 16 and not r["approx"]


def test_labelled_series_are_distinct_and_exported():
    hist = MetricHistorian(clock=_forbidden_clock)
    _fill(hist, "m", [(0.0, 1.0)], labels={"host": 0})
    _fill(hist, "m", [(0.0, 9.0)], labels={"host": 1})
    assert hist.query("m", t0=0.0, t1=1.0, labels={"host": "1"})["value"] == 9.0
    assert len(hist.series_list()) == 2
    trace = hist.export_chrome_counters(["m"])
    names = {ev["name"] for ev in trace["traceEvents"]}
    assert names == {"m{host=0}", "m{host=1}"}
    assert all(ev["ph"] == "C" for ev in trace["traceEvents"])


# ---------------------------------------------------------------------------
# Bounded memory: a 10k-tick scrape sim must plateau, not grow.
# ---------------------------------------------------------------------------


def test_memory_bounded_under_10k_tick_sim():
    hist = MetricHistorian(
        raw_capacity=64,
        tiers=((10.0, 32), (60.0, 16)),
        max_series=8,
        clock=_forbidden_clock,
    )
    hist.add_collector(
        lambda now: {f"sim_{i}": (now % 97.0) + i for i in range(4)}
    )
    steady = None
    for i in range(10_000):
        hist.tick(now=i * 5.0)
        if i == 8_999:
            steady = hist.stats()
    final = hist.stats()
    assert final["ticks_total"] == 10_000
    assert final["samples_total"] == 40_000
    assert final["series"] == 4
    assert final["raw_samples"] <= 4 * 64
    assert final["rollup_buckets"]["10s"] <= 4 * 32
    assert final["rollup_buckets"]["1m"] <= 4 * 16
    assert final["bucket_evictions_total"] > 0
    # Steady state: the footprint between tick 9k and 10k is identical —
    # retention evicts exactly what ingestion adds.
    assert final["estimated_bytes"] == steady["estimated_bytes"]
    assert final["raw_samples"] == steady["raw_samples"]
    assert final["rollup_buckets"] == steady["rollup_buckets"]


def test_series_registry_evicts_least_recently_written():
    hist = MetricHistorian(max_series=4, clock=_forbidden_clock)
    for i in range(10):
        hist.record("m", 1.0, ts=float(i), labels={"i": i})
    st = hist.stats()
    assert st["series"] == 4 and st["series_evicted_total"] == 6
    kept = {s["labels"]["i"] for s in hist.series_list()}
    assert kept == {"6", "7", "8", "9"}


def test_collector_failure_is_counted_not_raised():
    hist = MetricHistorian(clock=_forbidden_clock)
    def _boom(now):
        raise RuntimeError("collector exploded")
    hist.add_collector(_boom)
    hist.add_collector(lambda now: {"ok": 1.0})
    assert hist.tick(now=0.0) == 1
    assert hist.stats()["collector_errors_total"] == 1


# ---------------------------------------------------------------------------
# Virtual-clock determinism
# ---------------------------------------------------------------------------


def test_identical_replays_are_bit_identical():
    def build():
        h = MetricHistorian(clock=_forbidden_clock)
        c = IncidentCorrelator(clock=_forbidden_clock, stale_after_s=1e9)
        for i in range(300):
            h.record("step_time_s", 0.1 + (i % 7) * 0.01, ts=i * 0.5)
        c.ingest(records=_chain_records("chip-unhealthy", 3, 10.0, 0), now=50.0)
        return h, c
    h1, c1 = build()
    h2, c2 = build()
    for agg in ("avg", "min", "max", "last", "sum", "count", "rate", "p99"):
        assert h1.query("step_time_s", t0=0.0, t1=150.0, agg=agg) == (
            h2.query("step_time_s", t0=0.0, t1=150.0, agg=agg)
        )
    assert h1.buckets("step_time_s", 10.0) == h2.buckets("step_time_s", 10.0)
    assert c1.incidents(limit=0) == c2.incidents(limit=0)
    assert c1.stats() == c2.stats()


def test_ingest_counter_events_rebuilds_series_at_recorded_timestamps():
    hist = MetricHistorian(clock=_forbidden_clock)
    events = [
        {"kind": "counter", "name": "goodput", "ts": float(t),
         "attrs": {"fraction": t / 10.0, "note": "skip-me"}}
        for t in range(10)
    ]
    assert hist.ingest_counter_events(events) == 10
    q = hist.query("goodput.fraction", t0=0.0, t1=9.0, tier="raw")
    assert q["count"] == 10 and q["aggregates"]["last"] == 0.9
    # Non-counter and malformed records are ignored.
    assert hist.ingest_counter_events([{"kind": "span"}, {"kind": "counter"}]) == 0


# ---------------------------------------------------------------------------
# Incident stitching
# ---------------------------------------------------------------------------


def _chain_records(kind_value, device, base_ts, seq):
    """One self-heal chain as raw flight-recorder JSONL: FaultEvent detect,
    parented scheduler requeue, parented supervisor resume."""
    tid = f"trace-{seq}"
    return [
        {"record": "event", "event_id": f"f-{seq}", "trace_id": tid,
         "parent_id": None, "name": kind_value, "kind": "fault",
         "ts": base_ts, "attrs": {"device": device, "kind": kind_value}},
        {"record": "event", "event_id": f"a-{seq}", "trace_id": tid,
         "parent_id": f"f-{seq}", "name": "requeue", "kind": "scheduler",
         "ts": base_ts + 1.0, "attrs": {"submission_id": f"sub-{seq}"}},
        {"record": "event", "event_id": f"r-{seq}", "trace_id": tid,
         "parent_id": f"a-{seq}", "name": "resume", "kind": "supervisor",
         "ts": base_ts + 2.0, "attrs": {}},
    ]


def test_every_fault_kind_stitches_into_one_resolved_incident():
    corr = IncidentCorrelator(clock=_forbidden_clock, stale_after_s=1e9)
    kinds = [k.value for k in FaultKind]
    records = []
    for seq, kind in enumerate(kinds):
        records.extend(_chain_records(kind, seq, seq * 100.0, seq))
    assert corr.ingest(records=records, now=len(kinds) * 100.0) == 3 * len(kinds)
    st = corr.stats()
    assert st["opened_by_trigger"] == {"fault": len(kinds)}
    assert st["resolved_total"] == len(kinds)
    assert st["open"] == 0 and st["ignored_total"] == 0
    incs = corr.incidents(limit=0)
    assert len(incs) == len(kinds)
    by_name = {i["timeline"][0]["name"]: i for i in incs}
    assert set(by_name) == set(kinds)
    for seq, kind in enumerate(kinds):
        inc = by_name[kind]
        assert inc["state"] == "resolved"
        assert [e["role"] for e in inc["timeline"]] == (
            ["detect", "action", "resolution"]
        )
        assert inc["device_index"] == seq
        assert inc["submission_id"] == f"sub-{seq}"
        assert inc["duration_s"] == pytest.approx(2.0)


def test_detect_double_record_merges_span_and_event():
    """The live path records a fault twice — a detect span and the
    FaultEvent mirror at the same instant, same device. One incident."""
    corr = IncidentCorrelator(clock=_forbidden_clock, stale_after_s=1e9)
    records = [
        {"record": "span", "span_id": "s1", "trace_id": "t", "parent_id": None,
         "name": "chip-unhealthy", "kind": "fault", "t0": 100.0, "t1": 100.1,
         "attrs": {"device": 3}},
        {"record": "event", "event_id": "e1", "trace_id": "t",
         "parent_id": None, "name": "chip-unhealthy", "kind": "fault",
         "ts": 100.05, "attrs": {"device": 3}},
    ]
    corr.ingest(records=records, now=101.0)
    assert corr.stats()["opened_by_trigger"] == {"fault": 1}
    assert len(corr.incidents(limit=0)) == 1


def test_slo_alert_escalations_merge_and_resolve():
    corr = IncidentCorrelator(clock=_forbidden_clock, stale_after_s=1e9)
    def alert(eid, ts, transition):
        return {"record": "event", "event_id": eid, "trace_id": "t",
                "parent_id": None, "name": "slo_burn", "kind": "slo_alert",
                "ts": ts, "attrs": {"slo": "goodput",
                                    "transition": transition}}
    corr.ingest(
        records=[alert("a", 0.0, "page"), alert("b", 30.0, "escalate"),
                 alert("c", 60.0, "resolve")],
        now=61.0,
    )
    st = corr.stats()
    assert st["opened_by_trigger"] == {"slo_alert": 1}
    assert st["resolved_total"] == 1
    (inc,) = corr.incidents(limit=0)
    assert inc["slo"] == "goodput" and inc["state"] == "resolved"
    assert len(inc["timeline"]) == 3


def test_ingest_is_idempotent_and_stale_incidents_expire():
    corr = IncidentCorrelator(clock=_forbidden_clock, stale_after_s=900.0)
    records = _chain_records("host-slow", 1, 0.0, 0)[:2]  # no resolution
    corr.ingest(records=records, now=10.0)
    corr.ingest(records=records, now=10.0)  # dedup by record id
    st = corr.stats()
    assert st["opened_by_trigger"] == {"fault": 1}
    assert st["correlated_total"] == 2
    (inc,) = corr.incidents(limit=0)
    assert inc["state"] == "mitigating"
    # Idle past stale_after_s: moved to unresolved, no longer open.
    corr.ingest(records=[], now=2000.0)
    (inc,) = corr.incidents(limit=0)
    assert inc["state"] == "unresolved"
    assert corr.stats()["open"] == 0


def test_incident_metric_snippets_come_from_the_historian():
    hist = MetricHistorian(clock=_forbidden_clock)
    _fill(hist, "step_time_s", [(float(t), 0.1) for t in range(20)])
    corr = IncidentCorrelator(clock=_forbidden_clock, stale_after_s=1e9)
    corr.ingest(records=_chain_records("chip-unhealthy", 0, 5.0, 0), now=10.0)
    (inc,) = corr.incidents(
        limit=0, historian=hist, snippet_series=["step_time_s"]
    )
    snip = inc["metric_snippets"]["step_time_s"]
    assert snip["aggregates"]["count"] == 20  # 60s pad covers all samples
    assert snip["points"]


# ---------------------------------------------------------------------------
# Chaos replay fidelity gate (the twin lane the bench sentinel pins)
# ---------------------------------------------------------------------------


def test_historian_chaos_replay_lane_gates():
    from tpu_engine.twin import historian_lane

    lane = historian_lane(seed=0)
    assert lane["ok"], lane["gates"]
    assert lane["max_series_error_pct"] < 1.0
    assert lane["gates"]["every_fault_one_incident"]
    assert lane["gates"]["causal_chains"]
    assert lane["gates"]["replay_incidents_match"]
    assert lane["fault_incidents"] > 0
    assert lane["resolved_incidents"] >= lane["fault_incidents"]
