"""Generation must not stall training: the ragged and speculative decode
loops snapshot params once and run with the state lock released
(round-1 review finding — ``supervisor.py``)."""

import threading

import jax
import pytest

from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
from tpu_engine.supervisor import TrainingJob
from tpu_engine.train import build_train_program


def _make_job():
    cfg = TPUTrainConfig(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=1,
        seq_len=32,
        precision=Precision.FP32,
        activation_checkpointing=False,
        total_steps=10,
    )
    prog = build_train_program(cfg)
    job = TrainingJob("lock-test", cfg, program=prog)
    job._state = prog.init(jax.random.PRNGKey(0))
    return job, prog


def test_ragged_generation_releases_lock(monkeypatch):
    """While a (slow, blocked) ragged generation is mid-decode, the state
    lock must be free for the training thread to take."""
    job, prog = _make_job()

    started = threading.Event()
    release = threading.Event()
    import importlib

    # The package __init__ rebinds the attribute "generate" to the function;
    # import the submodule explicitly to patch it.
    gen_mod = importlib.import_module("tpu_engine.generate")
    real_generate = gen_mod.generate

    def slow_generate(*args, **kw):
        started.set()
        # Generous: the driver thread compiles a train step before
        # releasing, which can exceed 30 s on a loaded host (e.g. a
        # parallel pytest-xdist run oversubscribing the CPUs).
        assert release.wait(timeout=180), "test driver never released"
        return real_generate(*args, **kw)

    monkeypatch.setattr(gen_mod, "generate", slow_generate)

    result: dict = {}

    def run():
        result["rows"] = job.generate_samples_ragged(
            [[1, 2, 3], [4, 5]], max_new_tokens=2
        )

    t = threading.Thread(target=run)
    t.start()
    try:
        assert started.wait(timeout=30), "generation never started"
        # Mid-decode: the training thread must be able to take the lock
        # (and thus dispatch train steps).
        got_lock = job._state_lock.acquire(timeout=10)
        assert got_lock, "state lock held across the ragged decode loop"
        # A full train step completes while the generation is still blocked.
        job._state, metrics = prog.step(job._state, prog.synthetic_batch(0))
        assert float(jax.device_get(metrics["loss"])) > 0
        job._state_lock.release()
    finally:
        release.set()
        t.join(timeout=60)
    # The generation still finished correctly after training advanced
    # (snapshot buffers were never donated away by the train step).
    assert [r[:3] for r in result["rows"]][0] == [1, 2, 3]
    assert len(result["rows"][0]) == 5 and len(result["rows"][1]) == 4


def test_ragged_generation_consistent_after_training_advances():
    """The snapshot decouples decode weights from the live (donated) train
    state: rows decoded after a concurrent train step match a decode taken
    entirely before it."""
    job, prog = _make_job()
    before = job.generate_samples_ragged([[1, 2, 3, 4]], max_new_tokens=4, seed=7)

    # Interleave: snapshot, then advance training, then decode.
    params = job._params_snapshot()
    job._state, _ = prog.step(job._state, prog.synthetic_batch(1))

    import jax.numpy as jnp

    from tpu_engine.generate import generate

    out = generate(
        params,
        jnp.asarray([[1, 2, 3, 4]], jnp.int32),
        prog.model_config,
        max_new_tokens=4,
        rng=jax.random.PRNGKey(7),
        temperature=0.0,
        compute_dtype=prog.config.compute_dtype(),
    )
    after = [[int(t) for t in jax.device_get(out)[0]]]
    assert before == after


# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow
