"""Pallas flash attention: forward + backward vs XLA reference (interpret
mode on the CPU test mesh exercises the real kernel logic)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_engine.ops.flash_attention import mha
from tpu_engine.ops._flash_pallas import FlashUnsupported, _pick_block, flash_mha


def _rand_qkv(key, B=2, S=128, H=4, KV=4, D=64, dtype=jnp.float32):
    kq, kk, kv = jax.random.split(key, 3)
    return (
        jax.random.normal(kq, (B, S, H, D), dtype),
        jax.random.normal(kk, (B, S, KV, D), dtype),
        jax.random.normal(kv, (B, S, KV, D), dtype),
    )


def test_block_picker():
    assert _pick_block(4096) == 1024
    assert _pick_block(1024) == 512
    assert _pick_block(128) == 64
    assert _pick_block(192) == 64
    assert _pick_block(64) == 64  # single-block path (block == seq)
    assert _pick_block(100) == 0


@pytest.mark.parametrize("S", [64, 128, 256])
def test_flash_forward_matches_xla(S):
    q, k, v = _rand_qkv(jax.random.PRNGKey(0), S=S)
    ref = mha(q, k, v, force_xla=True)
    out = flash_mha(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_gqa_forward():
    q, k, v = _rand_qkv(jax.random.PRNGKey(1), H=8, KV=2)
    ref = mha(q, k, v, force_xla=True)
    out = flash_mha(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


# S=2048 exercises the backward's bb=min(block, 512) re-tiling (block=1024)
# and the >2-block DMA-clamp index maps; smaller B/H keep interpret mode fast.
@pytest.mark.parametrize("S,B,H", [(128, 2, 4), (512, 2, 4), (2048, 1, 2)])
def test_flash_backward_matches_xla(S, B, H):
    q, k, v = _rand_qkv(jax.random.PRNGKey(2), S=S, B=B, H=H, KV=H)

    def loss_flash(q, k, v):
        return jnp.sum(flash_mha(q, k, v, interpret=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha(q, k, v, force_xla=True) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4)


def test_flash_backward_bf16():
    """bf16 is the training dtype: gradients must come back bf16 and agree
    with the XLA path at bf16 tolerances."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), S=128, dtype=jnp.bfloat16)

    def loss_flash(q, k, v):
        return jnp.sum(flash_mha(q, k, v, interpret=True).astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha(q, k, v, force_xla=True).astype(jnp.float32) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        assert a.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(a, dtype=np.float32), np.asarray(b, dtype=np.float32),
            atol=0.15, rtol=0.1)


def test_unsupported_shapes_raise_and_dispatcher_falls_back():
    q, k, v = _rand_qkv(jax.random.PRNGKey(3), S=100)
    with pytest.raises(FlashUnsupported):
        flash_mha(q, k, v, interpret=True)
    # mha() dispatch silently falls back to XLA for the same shape.
    out = mha(q, k, v)
    ref = mha(q, k, v, force_xla=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_flash_under_jit_bf16():
    q, k, v = _rand_qkv(jax.random.PRNGKey(4), S=128, dtype=jnp.bfloat16)
    out = jax.jit(lambda q, k, v: flash_mha(q, k, v, interpret=True))(q, k, v)
    ref = mha(q, k, v, force_xla=True)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2, rtol=3e-2
    )


# ---------------------------------------------------------------------------
# Sliding-window attention
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("S,W", [(128, 32), (128, 64), (256, 100), (256, 65)])
def test_flash_window_forward_matches_xla(S, W):
    """Windowed flash vs the XLA mask, incl. non-block-aligned windows."""
    q, k, v = _rand_qkv(jax.random.PRNGKey(4), S=S)
    ref = mha(q, k, v, force_xla=True, window=W)
    out = flash_mha(q, k, v, interpret=True, window=W)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_flash_window_ge_seq_is_plain_causal():
    q, k, v = _rand_qkv(jax.random.PRNGKey(5), S=128)
    full = flash_mha(q, k, v, interpret=True)
    windowed = flash_mha(q, k, v, interpret=True, window=128)
    np.testing.assert_allclose(np.asarray(windowed), np.asarray(full), atol=0, rtol=0)


@pytest.mark.parametrize("S,W", [(128, 32), (256, 100)])
def test_flash_window_backward_matches_xla(S, W):
    q, k, v = _rand_qkv(jax.random.PRNGKey(6), S=S)

    def loss_flash(q, k, v):
        return jnp.sum(flash_mha(q, k, v, interpret=True, window=W) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(mha(q, k, v, force_xla=True, window=W) ** 2)

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4, rtol=5e-4)


def test_xla_window_mask_semantics():
    """Each query sees exactly the trailing W keys (inclusive of itself)."""
    S, W = 8, 3
    q = jnp.zeros((1, S, 1, 64), jnp.float32)
    # v rows are one-hot position markers; uniform scores => output averages
    # exactly the visible rows.
    k = jnp.zeros((1, S, 1, 64), jnp.float32)
    v = jnp.eye(S, 64)[None, :, None, :]
    out = mha(q, k, v, force_xla=True, window=W)[0, :, 0, :]
    for t in range(S):
        lo = max(0, t - W + 1)
        expect = np.zeros(64)
        expect[lo:t + 1] = 1.0 / (t - lo + 1)
        np.testing.assert_allclose(np.asarray(out[t]), expect, atol=1e-6)


def test_window_validation():
    q, k, v = _rand_qkv(jax.random.PRNGKey(7), S=64)
    with pytest.raises(ValueError, match="causal"):
        mha(q, k, v, causal=False, window=16)
    with pytest.raises(ValueError, match=">= 0"):
        mha(q, k, v, force_xla=True, window=-1)


def test_window_narrows_inner_grid():
    """The windowed kernels shrink the grid itself — O(S·W) programs, not
    O(S²) programs with skipped bodies."""
    from tpu_engine.ops._flash_pallas import _n_kv_blocks, _n_q_blocks

    # mistral-7b shapes: S=32768, block 512 (bwd), W=4096
    assert _n_kv_blocks(64, 512, 4096) == 9   # vs 64 unwindowed
    assert _n_q_blocks(64, 512, 4096) == 9
    # window inside one block
    assert _n_kv_blocks(8, 64, 1) == 1
    assert _n_kv_blocks(8, 64, 64) == 2
    # no window: full inner dim
    assert _n_kv_blocks(8, 64, 0) == 8 and _n_q_blocks(8, 64, 0) == 8


def test_flash_under_shard_map_matches_xla_on_mesh():
    """Mosaic calls cannot be GSPMD-partitioned: on a multi-device mesh the
    train program wraps the flash kernel in shard_map (batch over
    data/fsdp, heads over model). The full sharded train step must match
    the XLA-attention step bit-for-bit-close."""
    import jax

    from tpu_engine.mesh_runtime import MeshConfig
    from tpu_engine.sharding import ShardingStage, TPUTrainConfig
    from tpu_engine.train import build_train_program

    def step_loss(impl):
        cfg = TPUTrainConfig(
            model_name="gpt-tiny",
            sharding_stage=ShardingStage.FULL_PARTITIONING,
            mesh=MeshConfig(data=2, fsdp=2, model=2),
            micro_batch_size=2, seq_len=128, precision="fp32",
            attention_impl=impl, activation_checkpointing=True,
        )
        prog = build_train_program(cfg)
        state = prog.init(jax.random.PRNGKey(0))
        state, m = prog.step(state, prog.synthetic_batch(0))
        return float(m["loss"]), float(m["grad_norm"])

    flash = step_loss("flash")
    xla = step_loss("xla")
    assert flash == pytest.approx(xla, rel=1e-5)


# Compile-heavy module: excluded from the fast core run (pytest -m "not slow").
pytestmark = pytest.mark.slow
