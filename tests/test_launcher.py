"""Launcher: plan generation, dry-run, real in-process launch, registry."""

import jax

from tpu_engine.launcher import TPULauncher
from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
from tpu_engine.supervisor import JobStatus


def tiny_config(**kw):
    base = dict(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=1,
        gradient_accumulation_steps=1,
        seq_len=32,
        precision=Precision.FP32,
        total_steps=5,
        activation_checkpointing=False,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def test_generate_plan_contents():
    plan = TPULauncher().generate_plan(tiny_config())
    assert plan["mesh"]["shape"] == {"data": 2, "fsdp": 4, "pipe": 1, "sequence": 1, "model": 1}
    assert plan["sharding"]["stage"] == 3
    assert plan["sharding"]["semantics"]["params"] == "sharded over fsdp"
    assert plan["batch"]["effective_batch_size"] == 8
    assert plan["optimizer"]["name"] == "adamw"
    assert plan["precision"]["loss_scaling"].startswith("none")
    rep = plan["sharding"]["representative_tensors"]
    assert "fsdp" in rep["attention_qkv [embed, heads]"]["params"]


def test_plan_stage_semantics_change_with_stage():
    plan1 = TPULauncher().generate_plan(tiny_config(sharding_stage=ShardingStage.OPTIMIZER_STATE))
    sem = plan1["sharding"]["semantics"]
    assert sem["params"] == "replicated"
    assert sem["gradients"] == "all-reduced"
    assert sem["optimizer_state"] == "sharded over fsdp"


def test_dry_run_does_not_start_a_job():
    launcher = TPULauncher()
    res = launcher.launch(tiny_config(), dry_run=True)
    assert res.status == "dry_run"
    assert res.plan and res.job_id.startswith("tpu_gpt-tiny_")
    assert launcher.list_jobs() == []


def test_unknown_model_fails_cleanly():
    res = TPULauncher().launch(tiny_config(model_name="nope-9b"), dry_run=False)
    assert res.status == "failed"
    assert "unknown model" in res.error


def test_real_launch_runs_to_completion():
    launcher = TPULauncher()
    res = launcher.launch(tiny_config(total_steps=4), dry_run=False, block=True)
    assert res.status == "launched"
    job = launcher.get_job(res.job_id)
    assert job is not None
    assert job.status == JobStatus.COMPLETED, job.error
    assert job.current_step == 4
    jobs = launcher.list_jobs()
    assert len(jobs) == 1 and jobs[0]["job_id"] == res.job_id


def test_presets_exposed():
    p = TPULauncher.presets()
    assert {"125m", "7b", "13b", "70b"} <= set(p)


def test_concurrent_job_cap_queues_instead_of_refusing():
    import time

    from tpu_engine import TPULauncher, TPUTrainConfig
    from tpu_engine.mesh_runtime import MeshConfig

    cfg = TPUTrainConfig(
        model_name="gpt-tiny", mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=1, seq_len=32, precision="fp32", total_steps=200,
        activation_checkpointing=False, warmup_steps=1,
    )
    launcher = TPULauncher()  # default cap: 1 — enforced by the scheduler
    first = launcher.launch(cfg, dry_run=False, block=False)
    assert first.status == "launched"
    job = launcher.get_job(first.job_id)
    deadline = time.time() + 120
    while (
        job.status.value not in ("running", "completed", "failed")
        and time.time() < deadline
    ):
        time.sleep(0.2)
    assert job.status.value == "running", job.describe()
    # Over-cap launch queues with a position — not a bare refusal.
    second = launcher.launch(cfg, dry_run=False, block=False)
    assert second.status == "queued"
    assert second.queue_position == 1
    assert second.submission_id is not None
    # Dry runs are never blocked by the cap.
    assert launcher.launch(cfg, dry_run=True).status == "dry_run"
    # A running job cannot be deleted from the registry.
    import pytest

    with pytest.raises(ValueError, match="stop it"):
        launcher.delete_job(first.job_id)
    # Cancel the queued submission by its job_id (not admitted → no thread).
    assert launcher.stop_job(second.job_id)
    assert launcher.scheduler.get(second.submission_id).state.value == "cancelled"
    job.stop()
    job.join(timeout=120)
    # Capacity freed → a new launch is admitted immediately.
    third = launcher.launch(cfg, dry_run=False, max_steps=1, block=True)
    assert third.status == "launched"
    assert launcher.get_job(third.job_id).status.value == "completed"
    launcher.scheduler.shutdown()
