"""Launcher: plan generation, dry-run, real in-process launch, registry."""

import jax

from tpu_engine.launcher import TPULauncher
from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.sharding import Precision, ShardingStage, TPUTrainConfig
from tpu_engine.supervisor import JobStatus


def tiny_config(**kw):
    base = dict(
        model_name="gpt-tiny",
        sharding_stage=ShardingStage.FULL_PARTITIONING,
        mesh=MeshConfig(data=2, fsdp=4),
        micro_batch_size=1,
        gradient_accumulation_steps=1,
        seq_len=32,
        precision=Precision.FP32,
        total_steps=5,
        activation_checkpointing=False,
    )
    base.update(kw)
    return TPUTrainConfig(**base)


def test_generate_plan_contents():
    plan = TPULauncher().generate_plan(tiny_config())
    assert plan["mesh"]["shape"] == {"data": 2, "fsdp": 4, "pipe": 1, "sequence": 1, "model": 1}
    assert plan["sharding"]["stage"] == 3
    assert plan["sharding"]["semantics"]["params"] == "sharded over fsdp"
    assert plan["batch"]["effective_batch_size"] == 8
    assert plan["optimizer"]["name"] == "adamw"
    assert plan["precision"]["loss_scaling"].startswith("none")
    rep = plan["sharding"]["representative_tensors"]
    assert "fsdp" in rep["attention_qkv [embed, heads]"]["params"]


def test_plan_stage_semantics_change_with_stage():
    plan1 = TPULauncher().generate_plan(tiny_config(sharding_stage=ShardingStage.OPTIMIZER_STATE))
    sem = plan1["sharding"]["semantics"]
    assert sem["params"] == "replicated"
    assert sem["gradients"] == "all-reduced"
    assert sem["optimizer_state"] == "sharded over fsdp"


def test_dry_run_does_not_start_a_job():
    launcher = TPULauncher()
    res = launcher.launch(tiny_config(), dry_run=True)
    assert res.status == "dry_run"
    assert res.plan and res.job_id.startswith("tpu_gpt-tiny_")
    assert launcher.list_jobs() == []


def test_unknown_model_fails_cleanly():
    res = TPULauncher().launch(tiny_config(model_name="nope-9b"), dry_run=False)
    assert res.status == "failed"
    assert "unknown model" in res.error


def test_real_launch_runs_to_completion():
    launcher = TPULauncher()
    res = launcher.launch(tiny_config(total_steps=4), dry_run=False, block=True)
    assert res.status == "launched"
    job = launcher.get_job(res.job_id)
    assert job is not None
    assert job.status == JobStatus.COMPLETED, job.error
    assert job.current_step == 4
    jobs = launcher.list_jobs()
    assert len(jobs) == 1 and jobs[0]["job_id"] == res.job_id


def test_presets_exposed():
    p = TPULauncher.presets()
    assert {"125m", "7b", "13b", "70b"} <= set(p)
