"""Flight-recorder HTTP surface: the filterable span query, the
Chrome-trace export, and the structured 409 the profiling trace-start
returns when a capture is already active."""

import asyncio
import threading

import httpx
import pytest
from aiohttp import web

from backend.main import create_app
from tpu_engine import tracing


@pytest.fixture(scope="module", autouse=True)
def _fresh_recorder():
    """Serve a fresh recorder: earlier suites leave wall-clock traces on the
    process-wide one, which would push this module's virtual-timestamped
    seeds (t0=100.0) out of the newest-first ``traces()`` listing."""
    prev = tracing.get_recorder()
    tracing.set_recorder(tracing.FlightRecorder())
    yield
    tracing.set_recorder(prev)


@pytest.fixture(scope="module")
def client():
    loop = asyncio.new_event_loop()
    started = threading.Event()
    state = {}

    def run():
        asyncio.set_event_loop(loop)
        runner = web.AppRunner(create_app())
        loop.run_until_complete(runner.setup())
        site = web.TCPSite(runner, "127.0.0.1", 0)
        loop.run_until_complete(site.start())
        state["port"] = runner.addresses[0][1]
        started.set()
        loop.run_forever()
        loop.run_until_complete(runner.cleanup())

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(timeout=30)
    with httpx.Client(base_url=f"http://127.0.0.1:{state['port']}", timeout=60) as c:
        yield c
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=10)


def _seed_trace():
    """Record a small causal chain on the process recorder the app serves."""
    rec = tracing.get_recorder()
    root = rec.start_span("job:endpoint-test", kind="job", t0=100.0)
    child = rec.start_span("attempt", kind="attempt", parent=root, t0=101.0)
    child.end(t1=102.0)
    root.end(t1=103.0)
    rec.event("requeue", kind="scheduler", trace_id=root.trace_id, ts=101.5)
    return root.trace_id


def test_trace_query_endpoint(client):
    tid = _seed_trace()
    r = client.get("/api/v1/trace")
    assert r.status_code == 200
    body = r.json()
    assert {"stats", "traces", "spans", "events"} <= set(body)
    assert body["stats"]["spans_total"] >= 2
    assert any(t["trace_id"] == tid for t in body["traces"])
    # Filters narrow to one trace / one kind.
    f = client.get("/api/v1/trace", params={"trace_id": tid, "kind": "attempt"})
    spans = f.json()["spans"]
    assert len(spans) == 1 and spans[0]["name"] == "attempt"
    assert all(e["trace_id"] == tid for e in f.json()["events"])
    # Bad limit → 400, not a 500.
    assert client.get("/api/v1/trace", params={"limit": "x"}).status_code == 400


def test_trace_export_endpoint(client):
    tid = _seed_trace()
    r = client.get(f"/api/v1/trace/{tid}.json")
    assert r.status_code == 200
    assert "attachment" in r.headers.get("Content-Disposition", "")
    doc = r.json()
    evs = doc["traceEvents"]
    assert evs and all("ph" in e and "ts" in e and "pid" in e for e in evs)
    body = [e["ts"] for e in evs if e["ph"] != "M"]
    assert body == sorted(body)
    assert doc["otherData"]["trace_id"] == tid
    # Unknown trace → 404 with a detail body.
    miss = client.get("/api/v1/trace/nope.json")
    assert miss.status_code == 404 and "detail" in miss.json()


def test_trace_start_conflict_is_structured(client, tmp_path_factory):
    """Double-start returns 409 with the holder's dir and age, not a bare
    string — the caller can decide to wait, stop, or pick another box."""
    log_dir = str(tmp_path_factory.mktemp("trace"))
    r = client.post("/api/v1/profile/trace/start", json={"log_dir": log_dir})
    assert r.status_code == 200
    try:
        dup = client.post("/api/v1/profile/trace/start", json={})
        assert dup.status_code == 409
        body = dup.json()
        assert "trace already active" in body["detail"]
        active = body["active"]
        assert active["log_dir"] == log_dir
        assert active["started_at"] > 0
        assert active["elapsed_s"] >= 0
    finally:
        assert client.post("/api/v1/profile/trace/stop").status_code == 200
