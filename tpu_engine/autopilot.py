"""Explainable fleet autopilot: one audited control loop from incident to
action.

The sensor planes (flight recorder, goodput/SLO alerter, historian,
incident correlator) tell a human *what* happened; until now the three
control ticks — scheduler poll, serving autoscaler, precompile worker —
actuated independently with no shared record of *why*. ``FleetAutopilot``
subsumes them into one deterministic, virtual-clock-compatible
:meth:`FleetAutopilot.tick` and makes every actuation (and every
deliberate non-actuation) a first-class, queryable artifact:

- **Inputs are trends, never instants.** Each policy rule consults
  historian *range queries* (aggregate over ``trend_window_s``), recorder
  blame events over the same window, open incident ids, and host-health
  gauges — and every one of those inputs is copied into the decision.
- **DecisionRecords.** One bounded, id-stable record per consult: the
  rule, the target, the query inputs, the hysteresis/cooldown state, the
  chosen action or the structured suppression reason, and the outcome.
  Records are mirrored as ``kind="autopilot"`` spans on the flight
  recorder, which the :class:`~tpu_engine.historian.IncidentCorrelator`
  ingests as the incident's *action* leg (``action_source`` distinguishes
  ``autopilot`` from ``autopilot-dryrun`` from ``human``).
- **Blast-radius guards.** A rule fires only after ``sustain_consults``
  consecutive breaching consults (hysteresis), outside the per-target
  ``cooldown_s``, and under ``max_actions_per_window`` across the whole
  loop — each guard trip is itself a recorded suppression.
- **Dry-run (shadow) mode.** The full decision stream with zero
  actuations: mode lives on the autopilot, never inside the serialized
  record, so a shadow run is byte-identical to an armed run over the
  same inputs.

``GET /api/v1/autopilot/decisions`` serves the record stream
(``backend/routers/autopilot.py``); ``/metrics`` exports the
``tpu_engine_autopilot_*`` families; the twin's
:func:`tpu_engine.twin.autopilot_lane` A/Bs chaos goodput with the loop
on vs off, and ``benchmarks/chaos.py`` exit-gates on it.
"""

from __future__ import annotations

import dataclasses
import itertools
import json
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from tpu_engine import historian as historian_mod
from tpu_engine import tracing as tracing_mod

log = logging.getLogger("tpu_engine.autopilot")

__all__ = [
    "RULES",
    "OUTCOMES",
    "SUPPRESSION_REASONS",
    "ACTION_SOURCES",
    "AutopilotConfig",
    "DecisionRecord",
    "FleetAutopilot",
    "get_autopilot",
    "set_autopilot",
]

# Evaluated in this order every tick — the order is part of the contract
# (blast-radius budget is consumed first-come) and must stay stable.
RULES = ("replan_slow_job", "rescale_serving", "drain_host", "kick_precompile")
OUTCOMES = ("fired", "suppressed")
# Checked in this order; the first failing guard names the suppression.
SUPPRESSION_REASONS = (
    "trend-not-sustained", "cooldown-active", "blast-radius", "no-actuator",
)
ACTION_SOURCES = ("human", "autopilot", "autopilot-dryrun")


@dataclasses.dataclass
class AutopilotConfig:
    """Policy constants. Mode (armed vs dry-run) deliberately lives on the
    :class:`FleetAutopilot`, not here — records must not encode it."""

    # Input windows: rules aggregate over trend_window_s; the slow-step
    # baseline (when no nominal is configured) comes from the longer one.
    trend_window_s: float = 120.0
    baseline_window_s: float = 480.0
    # Hysteresis / blast radius.
    sustain_consults: int = 3
    cooldown_s: float = 120.0                 # per (rule, target)
    max_actions_per_window: int = 2           # across ALL rules
    action_window_s: float = 300.0
    max_decisions: int = 512                  # retained record ring
    # replan_slow_job: avg step time over the window vs a nominal (explicit,
    # or the min over the baseline window when None).
    step_time_series: str = "step_time_s"
    step_time_labels: Optional[Dict[str, str]] = None
    nominal_step_time_s: Optional[float] = None
    slow_step_factor: float = 1.25
    # rescale_serving: windowed p99-ok ratio under the floor means the SLO
    # is burning — scale ahead of the page.
    serving_ok_series: str = "slo_serving_p99_ok"
    serving_p99_series: str = "slo_serving_p99_ms"
    serving_labels: Optional[Dict[str, str]] = None
    serving_ok_floor: float = 0.9
    serving_scale_step: int = 1
    # drain_host: the recorder keeps blaming one device AND its retained
    # health trend sits under the floor (or has no healthy evidence).
    fault_blame_threshold: int = 3
    host_health_series: str = "hetero_host_health"
    host_health_floor: float = 0.9
    # kick_precompile: queued work is sitting idle (the autopilot records
    # the depth gauge itself each tick, then queries its own trend).
    precompile_series: str = "precompile_queue_depth"
    # Per-rule sustain overrides (kick_precompile reacts in one consult —
    # pumping a queue is cheap and self-correcting).
    rule_sustain: Dict[str, int] = dataclasses.field(
        default_factory=lambda: {"kick_precompile": 1}
    )


@dataclasses.dataclass
class DecisionRecord:
    """One consult, fired or suppressed. ``to_json()`` is byte-stable:
    two runs fed identical inputs serialize identically regardless of
    armed/dry-run mode (mode is recorded only on the mirrored span and
    the incident timeline, as ``action_source``)."""

    decision_id: str
    ts: float
    rule: str
    target: str
    # {"queries": [...], "incidents": [...], "gauges": {...}, "evidence": {...}}
    inputs: Dict[str, Any]
    # {"streak", "required", "cooldown_remaining_s",
    #  "actions_in_window", "max_actions_per_window"}
    hysteresis: Dict[str, Any]
    action: Optional[Dict[str, Any]]
    suppressed_reason: Optional[str]
    outcome: str  # "fired" | "suppressed"

    def to_dict(self) -> Dict[str, Any]:
        return {
            "decision_id": self.decision_id,
            "ts": self.ts,
            "rule": self.rule,
            "target": self.target,
            "inputs": self.inputs,
            "hysteresis": self.hysteresis,
            "action": self.action,
            "suppressed_reason": self.suppressed_reason,
            "outcome": self.outcome,
        }

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True, separators=(",", ":"))


def _default_ids() -> Callable[[], str]:
    counter = itertools.count(1)
    return lambda: f"apd-{next(counter):06d}"


class FleetAutopilot:
    """The unified control loop. All collaborators are injectable; the
    historian/correlator/recorder default to the process singletons *at
    tick time*, so tests that swap singletons see the swap."""

    def __init__(
        self,
        config: Optional[AutopilotConfig] = None,
        *,
        dry_run: bool = True,
        historian: Optional["historian_mod.MetricHistorian"] = None,
        correlator: Optional["historian_mod.IncidentCorrelator"] = None,
        recorder: Optional["tracing_mod.FlightRecorder"] = None,
        scheduler: Any = None,
        serving_fleet: Any = None,
        precompiler: Any = None,
        actuators: Optional[Dict[str, Callable[[DecisionRecord], Any]]] = None,
        gauges_fn: Optional[Callable[[], Dict[str, float]]] = None,
        clock: Callable[[], float] = time.time,
        id_factory: Optional[Callable[[], str]] = None,
        trace_id: str = "fleet",
    ):
        self.config = config or AutopilotConfig()
        self.dry_run = bool(dry_run)
        self._historian = historian
        self._correlator = correlator
        self._recorder = recorder
        self.scheduler = scheduler
        self.serving_fleet = serving_fleet
        self.precompiler = precompiler
        self.actuators = dict(actuators or {})
        self.gauges_fn = gauges_fn
        self.clock = clock
        self.id_factory = id_factory or _default_ids()
        self.trace_id = trace_id
        self._lock = threading.RLock()
        self._records: deque[DecisionRecord] = deque(
            maxlen=max(int(self.config.max_decisions), 1)
        )
        # Guard state. All of it evolves identically in dry-run — that is
        # what makes the shadow stream byte-identical to an armed one.
        self._streak: Dict[tuple, int] = {}
        self._last_action: Dict[tuple, float] = {}
        self._action_times: deque[float] = deque()
        # Health counters.
        self.ticks_total = 0
        self.decisions_total = 0
        self.fired_total = 0
        self.suppressed_total = 0
        self.suppressed_by_reason: Dict[str, int] = {
            r: 0 for r in SUPPRESSION_REASONS
        }
        self.decisions_by_rule: Dict[str, int] = {r: 0 for r in RULES}
        self.actuations_total = 0
        self.actuations_by_rule: Dict[str, int] = {r: 0 for r in RULES}
        self.actuation_errors_total = 0
        self.decisions_dropped_total = 0
        self.subsumed_errors_total = 0
        self.last_tick_ts: Optional[float] = None

    # -- plane resolution ------------------------------------------------------

    def _hist(self) -> "historian_mod.MetricHistorian":
        return self._historian or historian_mod.get_historian()

    def _corr(self) -> "historian_mod.IncidentCorrelator":
        return self._correlator or historian_mod.get_correlator()

    def _rec(self) -> "tracing_mod.FlightRecorder":
        return self._recorder or tracing_mod.get_recorder()

    # -- the tick --------------------------------------------------------------

    def tick(self, now: Optional[float] = None) -> List[DecisionRecord]:
        """One deterministic control pass: run the subsumed plane ticks,
        roll the historian forward, refresh incidents, evaluate every
        policy rule, and emit exactly one DecisionRecord per consult."""
        with self._lock:
            now = float(self.clock() if now is None else now)
            hist, corr, rec = self._hist(), self._corr(), self._rec()
            self._subsumed_ticks(now, hist)
            # Satellite contract: headless fleets (no /metrics scraper)
            # still roll up and expire series through this tick.
            try:
                hist.tick(now=now)
            except Exception:
                self.subsumed_errors_total += 1
            try:
                corr.ingest(recorder=rec, now=now)
            except Exception:
                self.subsumed_errors_total += 1
            incidents = self._open_incident_ids(corr)
            gauges = self._gauges()
            records: List[DecisionRecord] = []
            consulted: set = set()
            for rule in RULES:
                try:
                    consults = self._consults_for(rule, now, hist, rec)
                except Exception:
                    self.subsumed_errors_total += 1
                    consults = []
                for consult in consults:
                    key = (rule, consult["target"])
                    consulted.add(key)
                    record = self._decide(now, rule, consult, incidents, gauges)
                    records.append(record)
                    self._admit(record)
                    self._mirror(rec, record)
                    if record.outcome == "fired" and not self.dry_run:
                        self._actuate(rule, record)
            # Hysteresis demands *consecutive* breaches: any target whose
            # signal went quiet this tick starts over.
            for key in [k for k in self._streak if k not in consulted]:
                del self._streak[key]
            # Ingest again so this tick's decision spans attach to their
            # incidents as the action leg immediately, not a tick late.
            try:
                corr.ingest(recorder=rec, now=now)
            except Exception:
                self.subsumed_errors_total += 1
            self.ticks_total += 1
            self.last_tick_ts = now
            return records

    def _subsumed_ticks(self, now: float, hist: Any) -> None:
        """The three control loops this tick replaces. Each is best-effort:
        one failing plane must not starve the others or the policy pass."""
        if self.scheduler is not None:
            try:
                self.scheduler.poll()
            except Exception:
                self.subsumed_errors_total += 1
        if self.serving_fleet is not None:
            try:
                self.serving_fleet.tick(now)
            except Exception:
                self.subsumed_errors_total += 1
        if self.precompiler is not None:
            # The worker's queue depth becomes a historian series so the
            # kick_precompile rule consults a trend, not an instant.
            try:
                depth = float(self.precompiler.stats().get("queue_depth", 0))
                hist.record(self.config.precompile_series, depth, ts=now)
            except Exception:
                self.subsumed_errors_total += 1

    # -- inputs ----------------------------------------------------------------

    def _query(
        self,
        hist: Any,
        series: str,
        labels: Optional[Dict[str, str]],
        now: float,
        window_s: float,
        agg: str,
    ) -> Dict[str, Any]:
        q = hist.query(
            series, t0=now - window_s, t1=now, agg=agg, labels=labels
        )
        value = q.get("value")
        return {
            "series": series,
            "labels": {str(k): str(v) for k, v in (labels or {}).items()},
            "agg": agg,
            "window_s": round(float(window_s), 6),
            "value": None if value is None else round(float(value), 6),
            "count": int(q.get("count") or 0),
        }

    def _open_incident_ids(self, corr: Any) -> List[str]:
        try:
            return [ref["incident_id"] for ref in corr.open_refs(limit=8)]
        except Exception:
            return []

    def _gauges(self) -> Dict[str, float]:
        if self.gauges_fn is None:
            return {}
        try:
            return {
                str(k): round(float(v), 6)
                for k, v in sorted(self.gauges_fn().items())
            }
        except Exception:
            return {}

    # -- rules -----------------------------------------------------------------

    def _consults_for(
        self, rule: str, now: float, hist: Any, rec: Any
    ) -> List[Dict[str, Any]]:
        if rule == "replan_slow_job":
            return self._rule_replan(now, hist)
        if rule == "rescale_serving":
            return self._rule_rescale(now, hist)
        if rule == "drain_host":
            return self._rule_drain(now, hist, rec)
        return self._rule_precompile(now, hist)

    def _rule_replan(self, now: float, hist: Any) -> List[Dict[str, Any]]:
        cfg = self.config
        q = self._query(
            hist, cfg.step_time_series, cfg.step_time_labels, now,
            cfg.trend_window_s, "avg",
        )
        if not q["count"] or q["value"] is None:
            return []
        queries = [q]
        nominal = cfg.nominal_step_time_s
        if nominal is None:
            base = self._query(
                hist, cfg.step_time_series, cfg.step_time_labels, now,
                cfg.baseline_window_s, "min",
            )
            queries.append(base)
            nominal = base["value"]
        if not nominal or q["value"] < cfg.slow_step_factor * nominal:
            return []
        return [{
            "target": "training",
            "queries": queries,
            "action": {
                "kind": "replan",
                "params": {
                    "observed_step_s": q["value"],
                    "nominal_step_s": round(float(nominal), 6),
                },
            },
        }]

    def _rule_rescale(self, now: float, hist: Any) -> List[Dict[str, Any]]:
        cfg = self.config
        labels = cfg.serving_labels
        if labels is None:
            if self.serving_fleet is None:
                return []
            try:
                from tpu_engine import goodput as goodput_mod

                labels = goodput_mod.get_alerter().series_labels
            except Exception:
                return []
        q_ok = self._query(
            hist, cfg.serving_ok_series, labels, now, cfg.trend_window_s, "avg"
        )
        if not q_ok["count"] or q_ok["value"] is None:
            return []
        q_p99 = self._query(
            hist, cfg.serving_p99_series, labels, now, cfg.trend_window_s, "avg"
        )
        if q_ok["value"] >= cfg.serving_ok_floor:
            return []
        return [{
            "target": "serving",
            "queries": [q_ok, q_p99],
            "action": {
                "kind": "rescale",
                "params": {
                    "delta": int(cfg.serving_scale_step),
                    "p99_ok_ratio": q_ok["value"],
                    "p99_ms": q_p99["value"],
                },
            },
        }]

    def _rule_drain(
        self, now: float, hist: Any, rec: Any
    ) -> List[Dict[str, Any]]:
        """Drain a host the recorder keeps blaming — fault/anomaly events
        over the window, corroborated by the retained health trend."""
        cfg = self.config
        blame: Dict[int, int] = {}
        for kind in ("fault", "anomaly"):
            for ev in rec.events(kind=kind, limit=0):
                ts = ev.get("ts")
                if ts is None or ts < now - cfg.trend_window_s or ts > now:
                    continue
                idx = (ev.get("attrs") or {}).get("device_index")
                if idx is None:
                    continue
                blame[int(idx)] = blame.get(int(idx), 0) + 1
        consults: List[Dict[str, Any]] = []
        for idx in sorted(blame):
            if blame[idx] < cfg.fault_blame_threshold:
                continue
            q_health = self._query(
                hist, cfg.host_health_series, {"host": str(idx)}, now,
                cfg.trend_window_s, "avg",
            )
            healthy = (
                q_health["count"]
                and q_health["value"] is not None
                and q_health["value"] >= cfg.host_health_floor
            )
            if healthy:
                continue
            consults.append({
                "target": f"host-{idx}",
                "queries": [q_health],
                "evidence": {"blame_events": blame[idx]},
                "attrs": {"device_index": idx},
                "action": {
                    "kind": "drain",
                    "params": {
                        "device_index": idx,
                        "blame_events": blame[idx],
                    },
                },
            })
        return consults

    def _rule_precompile(self, now: float, hist: Any) -> List[Dict[str, Any]]:
        cfg = self.config
        if self.precompiler is None and "kick_precompile" not in self.actuators:
            return []
        q_avg = self._query(
            hist, cfg.precompile_series, None, now, cfg.trend_window_s, "avg"
        )
        q_last = self._query(
            hist, cfg.precompile_series, None, now, cfg.trend_window_s, "last"
        )
        if not q_last["count"] or not q_last["value"]:
            return []
        return [{
            "target": "precompile",
            "queries": [q_avg, q_last],
            "action": {
                "kind": "kick_precompile",
                "params": {"queue_depth": q_last["value"]},
            },
        }]

    # -- decision + guards -----------------------------------------------------

    def _decide(
        self,
        now: float,
        rule: str,
        consult: Dict[str, Any],
        incidents: List[str],
        gauges: Dict[str, float],
    ) -> DecisionRecord:
        cfg = self.config
        key = (rule, consult["target"])
        required = max(int(cfg.rule_sustain.get(rule, cfg.sustain_consults)), 1)
        streak = self._streak.get(key, 0) + 1
        self._streak[key] = streak
        while self._action_times and self._action_times[0] <= now - cfg.action_window_s:
            self._action_times.popleft()
        last = self._last_action.get(key)
        cooldown_remaining = (
            max(0.0, last + cfg.cooldown_s - now) if last is not None else 0.0
        )
        actions_in_window = len(self._action_times)
        reason: Optional[str] = None
        if streak < required:
            reason = "trend-not-sustained"
        elif cooldown_remaining > 0:
            reason = "cooldown-active"
        elif actions_in_window >= cfg.max_actions_per_window:
            reason = "blast-radius"
        elif self._resolve_actuator(rule) is None:
            reason = "no-actuator"
        outcome = "suppressed" if reason else "fired"
        inputs: Dict[str, Any] = {
            "queries": consult.get("queries", []),
            "incidents": list(incidents),
            "gauges": gauges,
        }
        if consult.get("evidence"):
            inputs["evidence"] = consult["evidence"]
        record = DecisionRecord(
            decision_id=self.id_factory(),
            ts=round(now, 6),
            rule=rule,
            target=consult["target"],
            inputs=inputs,
            hysteresis={
                "streak": streak,
                "required": required,
                "cooldown_remaining_s": round(cooldown_remaining, 6),
                "actions_in_window": actions_in_window,
                "max_actions_per_window": cfg.max_actions_per_window,
            },
            action=consult["action"] if outcome == "fired" else None,
            suppressed_reason=reason,
            outcome=outcome,
        )
        if outcome == "fired":
            # Guard state moves on "fired" in BOTH modes — a shadow run
            # must trace the exact decisions an armed run would make.
            self._streak[key] = 0
            self._last_action[key] = now
            self._action_times.append(now)
        record._span_attrs = dict(consult.get("attrs") or {})  # type: ignore[attr-defined]
        return record

    def _admit(self, record: DecisionRecord) -> None:
        if len(self._records) == self._records.maxlen:
            self.decisions_dropped_total += 1
        self._records.append(record)
        self.decisions_total += 1
        self.decisions_by_rule[record.rule] += 1
        if record.outcome == "fired":
            self.fired_total += 1
        else:
            self.suppressed_total += 1
            if record.suppressed_reason in self.suppressed_by_reason:
                self.suppressed_by_reason[record.suppressed_reason] += 1

    def action_source(self) -> str:
        return "autopilot-dryrun" if self.dry_run else "autopilot"

    def _mirror(self, rec: Any, record: DecisionRecord) -> None:
        attrs = {
            "decision_id": record.decision_id,
            "rule": record.rule,
            "target": record.target,
            "outcome": record.outcome,
            "suppressed_reason": record.suppressed_reason,
            "action": (record.action or {}).get("kind"),
            "action_source": self.action_source(),
            "incident_ids": list(record.inputs.get("incidents", []))[:8],
        }
        attrs.update(getattr(record, "_span_attrs", {}))
        try:
            rec.record_span(
                f"autopilot:{record.rule}",
                kind="autopilot",
                trace_id=self.trace_id,
                t0=record.ts,
                t1=record.ts,
                attrs=attrs,
            )
        except Exception:
            self.subsumed_errors_total += 1

    # -- actuation -------------------------------------------------------------

    def _resolve_actuator(
        self, rule: str
    ) -> Optional[Callable[[DecisionRecord], Any]]:
        if rule in self.actuators:
            return self.actuators[rule]
        if rule == "drain_host" and self.scheduler is not None:
            fn = getattr(self.scheduler, "quarantine_device", None)
            if fn is not None:
                return lambda r: fn(
                    int(r.action["params"]["device_index"]), owner="autopilot"
                )
        if rule == "replan_slow_job" and self.scheduler is not None:
            fn = getattr(self.scheduler, "request_replan", None)
            if fn is not None:
                return lambda r: fn()
        if rule == "rescale_serving" and self.serving_fleet is not None:
            fleet = self.serving_fleet
            return lambda r: fleet.scale_to(
                int(getattr(fleet, "desired_replicas", 0))
                + int(r.action["params"]["delta"])
            )
        if rule == "kick_precompile" and self.precompiler is not None:
            fn = getattr(self.precompiler, "pump", None)
            if fn is not None:
                return lambda r: fn()
        return None

    def _actuate(self, rule: str, record: DecisionRecord) -> None:
        actuator = self._resolve_actuator(rule)
        if actuator is None:  # pragma: no cover — guarded by "no-actuator"
            return
        try:
            actuator(record)
            self.actuations_total += 1
            self.actuations_by_rule[rule] += 1
        except Exception as e:  # noqa: BLE001 — the loop must survive a plane
            self.actuation_errors_total += 1
            log.warning("autopilot: %s actuation failed — %s", rule, e)

    # -- queries ---------------------------------------------------------------

    def decisions(
        self,
        limit: int = 50,
        rule: Optional[str] = None,
        outcome: Optional[str] = None,
        target: Optional[str] = None,
    ) -> List[Dict[str, Any]]:
        """Retained DecisionRecords, newest first, optionally filtered."""
        with self._lock:
            out: List[Dict[str, Any]] = []
            for record in reversed(self._records):
                if rule is not None and record.rule != rule:
                    continue
                if outcome is not None and record.outcome != outcome:
                    continue
                if target is not None and record.target != target:
                    continue
                out.append(record.to_dict())
                if limit and len(out) >= limit:
                    break
            return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "armed": not self.dry_run,
                "dry_run": self.dry_run,
                "ticks_total": self.ticks_total,
                "decisions_total": self.decisions_total,
                "fired_total": self.fired_total,
                "suppressed_total": self.suppressed_total,
                "suppressed_by_reason": dict(self.suppressed_by_reason),
                "decisions_by_rule": dict(self.decisions_by_rule),
                "actuations_total": self.actuations_total,
                "actuations_by_rule": dict(self.actuations_by_rule),
                "actuation_errors_total": self.actuation_errors_total,
                "decisions_retained": len(self._records),
                "decisions_dropped_total": self.decisions_dropped_total,
                "subsumed_errors_total": self.subsumed_errors_total,
                "last_tick_ts": self.last_tick_ts,
            }

    def set_dry_run(self, dry_run: bool) -> None:
        """Flip shadow mode. Guard state carries over — arming after a
        shadow soak keeps the learned streaks and cooldowns."""
        with self._lock:
            self.dry_run = bool(dry_run)

    # -- durability (control-plane journal snapshot section) -----------------

    def export_state(self) -> Dict[str, Any]:
        """Serialized guard state (streaks, per-rule cooldown clocks,
        rate-limit window) for the control-plane journal. Tuple keys are
        flattened to ``[key_parts, value]`` pairs for JSON."""
        with self._lock:
            return {
                "dry_run": self.dry_run,
                "streak": [
                    [list(k), int(v)] for k, v in sorted(self._streak.items())
                ],
                "last_action": [
                    [list(k), float(v)]
                    for k, v in sorted(self._last_action.items())
                ],
                "action_times": [float(t) for t in self._action_times],
            }

    def load_state(self, state: Dict[str, Any]) -> None:
        """Inverse of :meth:`export_state`; a restarted autopilot keeps
        its hysteresis so cooldowns survive a control-plane crash instead
        of refiring immediately. Tolerant of missing keys."""
        if not isinstance(state, dict):
            return
        with self._lock:
            if "dry_run" in state:
                self.dry_run = bool(state["dry_run"])
            self._streak = {
                tuple(k): int(v) for k, v in state.get("streak") or []
            }
            self._last_action = {
                tuple(k): float(v) for k, v in state.get("last_action") or []
            }
            self._action_times = deque(
                float(t) for t in state.get("action_times") or []
            )


# -- process-wide autopilot (the backend/router default) -----------------------

_autopilot: Optional[FleetAutopilot] = None
_autopilot_lock = threading.Lock()


def get_autopilot() -> FleetAutopilot:
    """The process autopilot: created on first use in dry-run (shadow)
    mode with no planes wired beyond the process singletons — arming and
    actuator wiring are deliberate, explicit steps."""
    global _autopilot
    with _autopilot_lock:
        if _autopilot is None:
            _autopilot = FleetAutopilot(dry_run=True)
        return _autopilot


def set_autopilot(autopilot: Optional[FleetAutopilot]) -> None:
    global _autopilot
    with _autopilot_lock:
        _autopilot = autopilot
