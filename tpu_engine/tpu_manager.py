"""TPU fleet telemetry and health management.

Capability parity with the reference's GPU fleet manager
(``ai_engine/gpu_manager.py``): device table, health classification with
warning/critical thresholds, fleet aggregation + alert rollup, best-device
selection, a mock fleet for tests, and injectable raw telemetry — but sourced
from the JAX runtime / libtpu rather than an ``nvidia-smi`` subprocess parse
(reference ``gpu_manager.py:100-117``).

TPU-honest schema notes (SURVEY.md §7 hard part e): there is no fan speed and
no per-process memory attribution on TPU; instead we report HBM usage from
``device.memory_stats()``, with duty cycle / TensorCore utilization /
throttle score / ICI link health laid over from the live telemetry stack
(``tpu_engine.telemetry``: libtpu SDK monitoring + engine-derived duty
cycle), and temperature / power when an injected or external source provides
them. Health thresholds mirror the reference's semantics
(``gpu_manager.py:92-98``): temp 80/90 °C, memory 85/95 %, utilization 95 %,
power 0.9× limit — plus the TPU-native throttle-score thresholds (the
hardware's own thermal/power-protection signal).
"""

from __future__ import annotations

import json
import time
from enum import Enum
from typing import Any, Optional, Sequence

import jax
from pydantic import BaseModel, Field

# Default HBM per chip when the runtime doesn't report a limit (GiB).
_DEFAULT_HBM_GIB = {
    "TPU v4": 32.0,
    "TPU v5 lite": 16.0,
    "TPU v5e": 16.0,
    "TPU v5": 16.0,
    "TPU v5p": 95.0,
    "TPU v6 lite": 32.0,
    "TPU v6e": 32.0,
}


class TPUHealthStatus(str, Enum):
    """Mirrors reference ``GPUHealthStatus`` (``gpu_manager.py:20-25``)."""

    HEALTHY = "healthy"
    WARNING = "warning"
    CRITICAL = "critical"
    UNKNOWN = "unknown"


class TPUJobRef(BaseModel):
    """A supervised job holding this chip — the TPU analogue of the
    reference's per-GPU process table (``gpu_manager.py:27-33``, populated
    ``:174-184``). The entries are the control plane's OWN jobs, registered
    by their supervisors (``tpu_engine.telemetry.register_job_devices``);
    FOREIGN holders are surfaced separately via :class:`TPUProcessRef`."""

    job_id: str
    status: str
    process_index: int = 0


class TPUProcessRef(BaseModel):
    """An OS process holding this chip — including ones this control plane
    never launched. Reference parity: ``GPUProcess`` (``gpu_manager.py:
    27-33``: pid, name, memory). Source: ``tpu-info``'s TPU Chips table PID
    column (the runtime exposes no per-process memory attribution, so
    ``memory_mb`` has no TPU-honest value and is omitted). ``foreign`` is
    True when the pid is not this control-plane process — a chip held by a
    job nobody here supervises."""

    pid: int
    name: Optional[str] = None
    foreign: bool = False


def _process_ref(pid: int) -> "TPUProcessRef":
    """Resolve a chip-holder pid into a process ref. The name comes from
    /proc/<pid>/comm when the pid is on this host (tpu-info runs host-local,
    so it always is); a vanished pid keeps name=None."""
    import os

    name = None
    try:
        with open(f"/proc/{pid}/comm") as f:
            name = f.read().strip() or None
    except OSError:
        pass
    return TPUProcessRef(pid=pid, name=name, foreign=pid != os.getpid())


class TPUDevice(BaseModel):
    """One TPU chip/core. Reference analogue: ``GPUDevice`` (``gpu_manager.py:35-62``)."""

    index: int
    name: str = "TPU"
    device_kind: str = "unknown"
    platform: str = "tpu"
    process_index: int = 0
    coords: Optional[tuple[int, ...]] = None
    core_on_chip: Optional[int] = None

    hbm_total_gb: float = 0.0
    hbm_used_gb: float = 0.0
    hbm_utilization_pct: float = 0.0

    duty_cycle_pct: Optional[float] = None  # % of time the chip was executing
    tensorcore_util_pct: Optional[float] = None  # MXU utilization (per-core mean)
    # libtpu throttle score: 0 = not throttled, 1-10 = throttled by 10-100%.
    # TPU metrics expose *throttling* rather than raw die temperature — this
    # is the hardware-honest signal behind the reference's temp/power alerts.
    throttle_score: Optional[int] = None
    # INJECTION-ONLY fields: no TPU telemetry source reports die temperature
    # or power (the libtpu SDK has no such metrics — throttle_score is the
    # thermal signal), so on the LIVE path these stay null. They exist, with
    # their reference-parity health thresholds, for injected snapshots
    # (``metrics=``/``parse_metrics_json`` — external collectors, tests,
    # the mock fleet).
    temperature_c: Optional[float] = None
    power_draw_w: Optional[float] = None
    power_limit_w: Optional[float] = None

    health_status: TPUHealthStatus = TPUHealthStatus.UNKNOWN
    alerts: list[str] = Field(default_factory=list)
    # Supervised jobs whose mesh holds this chip (live snapshots only;
    # injected/mock fleets have no job registry to consult).
    jobs: list[TPUJobRef] = Field(default_factory=list)
    # OS processes holding the chip per `tpu-info`'s chips table —
    # including FOREIGN holders the control plane didn't launch
    # (reference ``gpu_manager.py:174-184``).
    processes: list[TPUProcessRef] = Field(default_factory=list)

    @property
    def hbm_free_gb(self) -> float:
        return max(self.hbm_total_gb - self.hbm_used_gb, 0.0)

    @property
    def is_available(self) -> bool:
        """Schedulable: <80% HBM used, duty cycle <90% (if known), not critical.

        Same semantics as reference ``GPUDevice.is_available``
        (``gpu_manager.py:57-62`` — the code, not its stale docstring; see
        SURVEY.md §5 quirks).
        """
        if self.health_status == TPUHealthStatus.CRITICAL:
            return False
        if self.hbm_utilization_pct >= 80.0:
            return False
        if self.duty_cycle_pct is not None and self.duty_cycle_pct >= 90.0:
            return False
        return True


class TPUFleetStatus(BaseModel):
    """Fleet aggregate. Reference analogue: ``GPUFleetStatus`` (``gpu_manager.py:65-77``)."""

    timestamp: float = Field(default_factory=time.time)
    total_devices: int = 0
    available_devices: int = 0
    total_hbm_gb: float = 0.0
    used_hbm_gb: float = 0.0
    average_duty_cycle_pct: Optional[float] = None
    average_temperature_c: Optional[float] = None
    devices: list[TPUDevice] = Field(default_factory=list)
    fleet_alerts: list[str] = Field(default_factory=list)
    # Live telemetry sources that contributed to this snapshot, priority
    # order (e.g. ["libtpu_sdk", "derived"]); empty for injected/mock fleets.
    telemetry_sources: list[str] = Field(default_factory=list)
    # (location, score) per ICI link when the libtpu source reports them.
    ici_links: list[tuple[str, int]] = Field(default_factory=list)
    # Derived-duty freshness (tpu_engine.telemetry.DerivedDutySource
    # .staleness()): last-sample age + silently-expired scope count, so a
    # dead telemetry feed is visible instead of quietly UNKNOWN.
    telemetry_staleness: Optional[dict[str, Any]] = None


class TPUManager:
    """Fleet manager over the JAX runtime (reference ``GPUManager``, ``gpu_manager.py:80``).

    Telemetry sources, in priority order:

    1. injected snapshot (``metrics=`` argument or :meth:`parse_metrics_json`)
       — the test seam, parity with ``parse_xml(xml_str=...)`` /
       ``parse_csv(csv_str=...)`` (``gpu_manager.py:119-130,219-232``);
    2. the live JAX runtime: ``jax.devices()`` + ``device.memory_stats()``.
    """

    # Health thresholds — reference ``gpu_manager.py:92-98``.
    TEMP_WARNING_C = 80.0
    TEMP_CRITICAL_C = 90.0
    HBM_WARNING_PCT = 85.0
    HBM_CRITICAL_PCT = 95.0
    DUTY_WARNING_PCT = 95.0
    POWER_WARNING_RATIO = 0.9
    # libtpu throttle score (0-10): >=1 warning, >=6 critical (throttled by
    # 60%+ — the chip is protecting itself; treat like a temp-critical GPU).
    THROTTLE_CRITICAL_SCORE = 6

    def __init__(self, devices: Optional[Sequence[jax.Device]] = None):
        self._devices = devices  # None = resolve lazily from jax.devices()

    # -- telemetry ingestion -------------------------------------------------

    def _runtime_devices(self) -> list[jax.Device]:
        return list(self._devices if self._devices is not None else jax.devices())

    def _device_from_runtime(self, i: int, d: jax.Device) -> TPUDevice:
        kind = getattr(d, "device_kind", "unknown")
        hbm_total = 0.0
        hbm_used = 0.0
        stats: Optional[dict[str, Any]]
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats:
            limit = stats.get("bytes_limit") or stats.get("bytes_reservable_limit") or 0
            used = stats.get("bytes_in_use", 0)
            hbm_total = limit / 2**30
            hbm_used = used / 2**30
        if hbm_total <= 0.0:
            # Longest prefix wins: "TPU v5p" must not fall into "TPU v5"'s bucket.
            for prefix in sorted(_DEFAULT_HBM_GIB, key=len, reverse=True):
                if kind.startswith(prefix):
                    hbm_total = _DEFAULT_HBM_GIB[prefix]
                    break
        util = (hbm_used / hbm_total * 100.0) if hbm_total > 0 else 0.0
        coords = getattr(d, "coords", None)
        dev = TPUDevice(
            index=i,
            name=f"{kind} #{d.id}",
            device_kind=kind,
            platform=d.platform,
            process_index=d.process_index,
            coords=tuple(int(c) for c in coords) if coords is not None else None,
            core_on_chip=getattr(d, "core_on_chip", None),
            hbm_total_gb=round(hbm_total, 3),
            hbm_used_gb=round(hbm_used, 3),
            hbm_utilization_pct=round(util, 2),
        )
        self._assess_health(dev)
        return dev

    def parse_metrics(self, metrics: Sequence[dict[str, Any]]) -> list[TPUDevice]:
        """Build the device table from an injected telemetry snapshot.

        Each entry may carry: index, device_kind, hbm_total_gb, hbm_used_gb,
        duty_cycle_pct, temperature_c, power_draw_w, power_limit_w, coords,
        process_index. Unknown keys are ignored.
        """
        out: list[TPUDevice] = []
        for i, m in enumerate(metrics):
            total = float(m.get("hbm_total_gb", 0.0))
            used = float(m.get("hbm_used_gb", 0.0))
            util = m.get("hbm_utilization_pct")
            if util is None:
                util = (used / total * 100.0) if total > 0 else 0.0
            dev = TPUDevice(
                index=int(m.get("index", i)),
                name=m.get("name", f"{m.get('device_kind', 'TPU')} #{m.get('index', i)}"),
                device_kind=m.get("device_kind", "unknown"),
                platform=m.get("platform", "tpu"),
                process_index=int(m.get("process_index", 0)),
                coords=tuple(m["coords"]) if m.get("coords") is not None else None,
                core_on_chip=m.get("core_on_chip"),
                hbm_total_gb=total,
                hbm_used_gb=used,
                hbm_utilization_pct=round(float(util), 2),
                duty_cycle_pct=m.get("duty_cycle_pct"),
                tensorcore_util_pct=m.get("tensorcore_util_pct"),
                throttle_score=m.get("throttle_score"),
                temperature_c=m.get("temperature_c"),
                power_draw_w=m.get("power_draw_w"),
                power_limit_w=m.get("power_limit_w"),
            )
            self._assess_health(dev)
            out.append(dev)
        return out

    def parse_metrics_json(self, raw: str) -> list[TPUDevice]:
        """Injectable raw-telemetry seam: JSON list of per-chip metric dicts
        (the ``tpu-info``/libtpu analogue of canned nvidia-smi XML/CSV)."""
        data = json.loads(raw)
        if isinstance(data, dict):
            data = data.get("devices", [])
        return self.parse_metrics(data)

    # -- health --------------------------------------------------------------

    @staticmethod
    def _sanitize_telemetry(dev: TPUDevice) -> list[str]:
        """Discard non-finite (NaN/inf) telemetry before classification.

        Corrupt telemetry (a flaky collector, or an injected `telemetry-nan`
        fault) must not poison the fleet aggregates — a single NaN
        ``hbm_used_gb`` would turn the fleet-wide HBM sums NaN and wreck the
        scheduler's admission math. Optional fields revert to None (unknown),
        HBM fields to 0.0; the affected field names are returned so the
        caller can alert on them.
        """
        import math

        def bad(v: Any) -> bool:
            return isinstance(v, float) and not math.isfinite(v)

        dropped: list[str] = []
        for field in (
            "duty_cycle_pct",
            "tensorcore_util_pct",
            "temperature_c",
            "power_draw_w",
            "power_limit_w",
        ):
            if bad(getattr(dev, field)):
                setattr(dev, field, None)
                dropped.append(field)
        for field in ("hbm_total_gb", "hbm_used_gb", "hbm_utilization_pct"):
            if bad(getattr(dev, field)):
                setattr(dev, field, 0.0)
                dropped.append(field)
        if "hbm_used_gb" in dropped or "hbm_total_gb" in dropped:
            dev.hbm_utilization_pct = (
                round(dev.hbm_used_gb / dev.hbm_total_gb * 100.0, 2)
                if dev.hbm_total_gb > 0
                else 0.0
            )
        return dropped

    def _assess_health(self, dev: TPUDevice) -> None:
        """Classify health; mirrors reference ``_assess_health`` (``gpu_manager.py:348-379``)."""
        dropped = self._sanitize_telemetry(dev)
        alerts: list[str] = []
        status = TPUHealthStatus.HEALTHY

        if dev.temperature_c is not None:
            if dev.temperature_c >= self.TEMP_CRITICAL_C:
                alerts.append(f"CRITICAL: temperature {dev.temperature_c:.0f}C >= {self.TEMP_CRITICAL_C:.0f}C")
                status = TPUHealthStatus.CRITICAL
            elif dev.temperature_c >= self.TEMP_WARNING_C:
                alerts.append(f"WARNING: temperature {dev.temperature_c:.0f}C >= {self.TEMP_WARNING_C:.0f}C")
                status = TPUHealthStatus.WARNING

        if dev.hbm_total_gb > 0:
            if dev.hbm_utilization_pct >= self.HBM_CRITICAL_PCT:
                alerts.append(f"CRITICAL: HBM {dev.hbm_utilization_pct:.1f}% >= {self.HBM_CRITICAL_PCT:.0f}%")
                status = TPUHealthStatus.CRITICAL
            elif dev.hbm_utilization_pct >= self.HBM_WARNING_PCT:
                alerts.append(f"WARNING: HBM {dev.hbm_utilization_pct:.1f}% >= {self.HBM_WARNING_PCT:.0f}%")
                if status != TPUHealthStatus.CRITICAL:
                    status = TPUHealthStatus.WARNING

        if dev.duty_cycle_pct is not None and dev.duty_cycle_pct >= self.DUTY_WARNING_PCT:
            alerts.append(f"WARNING: duty cycle {dev.duty_cycle_pct:.1f}% >= {self.DUTY_WARNING_PCT:.0f}%")
            if status == TPUHealthStatus.HEALTHY:
                status = TPUHealthStatus.WARNING

        if dev.throttle_score is not None and dev.throttle_score >= 1:
            # The chip's own thermal/power protection kicking in — the TPU
            # analogue of the reference's temperature/power alerts.
            if dev.throttle_score >= self.THROTTLE_CRITICAL_SCORE:
                alerts.append(
                    f"CRITICAL: throttled by {dev.throttle_score * 10}% "
                    f"(score {dev.throttle_score}/10)"
                )
                status = TPUHealthStatus.CRITICAL
            else:
                alerts.append(
                    f"WARNING: throttled by {dev.throttle_score * 10}% "
                    f"(score {dev.throttle_score}/10)"
                )
                if status == TPUHealthStatus.HEALTHY:
                    status = TPUHealthStatus.WARNING

        if (
            dev.power_draw_w is not None
            and dev.power_limit_w is not None
            and dev.power_limit_w > 0
            and dev.power_draw_w >= self.POWER_WARNING_RATIO * dev.power_limit_w
        ):
            alerts.append(
                f"WARNING: power draw {dev.power_draw_w:.0f}W >= "
                f"{self.POWER_WARNING_RATIO:.0%} of limit {dev.power_limit_w:.0f}W"
            )
            if status == TPUHealthStatus.HEALTHY:
                status = TPUHealthStatus.WARNING

        if dropped:
            alerts.append(
                "WARNING: non-finite telemetry discarded for " + ", ".join(dropped)
            )
            # A chip whose telemetry is corrupt is not *known* healthy —
            # but it's not known bad either, so it stays schedulable
            # (is_available treats UNKNOWN as eligible) while the alert flags it.
            if status == TPUHealthStatus.HEALTHY:
                status = TPUHealthStatus.UNKNOWN

        dev.alerts = alerts
        dev.health_status = status

    def _apply_fault_overlay(self, devices: list[TPUDevice], injector: Any) -> None:
        """Lay active injected chip faults over a fleet snapshot.

        `chip-unhealthy` forces CRITICAL (the chip drops out of
        ``is_available`` and the scheduler's eligible set); `telemetry-nan`
        poisons the chip's metrics with NaN and re-assesses, which drives
        the exact sanitization path corrupt real telemetry would.
        """
        overlay = injector.chip_overlay()
        if not overlay:
            return
        from tpu_engine.faults import FaultKind

        by_index = {d.index: d for d in devices}
        for idx, kind in overlay.items():
            dev = by_index.get(idx)
            if dev is None:
                continue
            if kind is FaultKind.TELEMETRY_NAN:
                dev.duty_cycle_pct = float("nan")
                dev.hbm_used_gb = float("nan")
                self._assess_health(dev)
            elif kind is FaultKind.CHIP_UNHEALTHY:
                self._assess_health(dev)
                dev.alerts = [*dev.alerts, "CRITICAL: injected fault: chip-unhealthy"]
                dev.health_status = TPUHealthStatus.CRITICAL

    # -- fleet ---------------------------------------------------------------

    def get_fleet_status(
        self,
        metrics: Optional[Sequence[dict[str, Any]]] = None,
        metrics_json: Optional[str] = None,
    ) -> TPUFleetStatus:
        """Aggregate fleet view (reference ``get_fleet_status``, ``gpu_manager.py:275-321``)."""
        telemetry_sources: list[str] = []
        ici_links: list[tuple[str, int]] = []
        if metrics_json is not None:
            devices = self.parse_metrics_json(metrics_json)
        elif metrics is not None:
            devices = self.parse_metrics(metrics)
        else:
            try:
                runtime_devs = self._runtime_devices()
                devices = [
                    self._device_from_runtime(i, d) for i, d in enumerate(runtime_devs)
                ]
            except Exception as e:  # runtime unavailable
                return TPUFleetStatus(
                    fleet_alerts=[f"TPU runtime unavailable: {type(e).__name__}: {e}"]
                )
            # Live path: lay the telemetry-source overlay (libtpu SDK
            # monitoring, engine-derived duty cycle — tpu_engine.telemetry)
            # over the runtime's memory_stats view, then re-classify health
            # with the merged fields. This is what makes duty/throttle
            # alerts fire in production, not just on injected snapshots.
            from tpu_engine import telemetry

            overlay = telemetry.sample_overlay(len(devices))
            if overlay is not None:
                telemetry_sources = overlay.sources
                ici_links = overlay.ici_links
                for dev, extra in zip(devices, overlay.per_chip):
                    for key in (
                        "duty_cycle_pct",
                        "tensorcore_util_pct",
                        "throttle_score",
                        "temperature_c",
                        "power_draw_w",
                        "power_limit_w",
                    ):
                        if getattr(dev, key) is None and extra.get(key) is not None:
                            setattr(dev, key, extra[key])
                    # HBM: the runtime's memory_stats is exact for this
                    # process; the SDK fills in only when it gave nothing.
                    if dev.hbm_used_gb == 0.0 and extra.get("hbm_used_gb"):
                        dev.hbm_used_gb = extra["hbm_used_gb"]
                        if extra.get("hbm_total_gb"):
                            dev.hbm_total_gb = extra["hbm_total_gb"]
                        if dev.hbm_total_gb > 0:
                            dev.hbm_utilization_pct = round(
                                dev.hbm_used_gb / dev.hbm_total_gb * 100.0, 2
                            )
                    # Chip-holder process from tpu-info's chips table:
                    # foreign pids (a JAX job this plane never launched)
                    # become visible here, reference ``:174-184`` parity.
                    if extra.get("holder_pid") is not None and not dev.processes:
                        dev.processes = [
                            _process_ref(int(extra["holder_pid"]))
                        ]
                    self._assess_health(dev)

            # Per-chip job attribution: lay the supervised-job claims
            # (tpu_engine.telemetry.register_job_devices) over the device
            # table, matched by runtime device id — the TPU answer to the
            # reference's per-GPU process table (``gpu_manager.py:174-184``).
            attribution = telemetry.job_attribution()
            if attribution:
                for dev, d in zip(devices, runtime_devs):
                    refs = attribution.get(int(getattr(d, "id", dev.index)))
                    if refs:
                        dev.jobs = [TPUJobRef(**r) for r in refs]

        # Fault-injection overlay (tpu_engine.faults): applied to EVERY
        # snapshot path — injected, mock, and live — so the chaos harness
        # exercises the same detection pipeline real degradation would.
        from tpu_engine import faults as faults_mod

        injector = faults_mod.get_active()
        if injector is not None:
            self._apply_fault_overlay(devices, injector)

        fleet_alerts: list[str] = []
        if ici_links:
            from tpu_engine import telemetry

            fleet_alerts.extend(telemetry.ici_link_alerts(ici_links))
        for dev in devices:
            for a in dev.alerts:
                fleet_alerts.append(f"chip {dev.index}: {a}")

        duty = [d.duty_cycle_pct for d in devices if d.duty_cycle_pct is not None]
        temps = [d.temperature_c for d in devices if d.temperature_c is not None]
        available = sum(1 for d in devices if d.is_available)
        if devices and available == 0:
            fleet_alerts.append("No TPU devices available for new work")
        if not devices:
            fleet_alerts.append("No TPU devices detected")

        from tpu_engine import telemetry as telemetry_mod

        try:
            staleness = telemetry_mod.derived_duty().staleness()
        except Exception:
            staleness = None

        return TPUFleetStatus(
            total_devices=len(devices),
            available_devices=available,
            total_hbm_gb=round(sum(d.hbm_total_gb for d in devices), 3),
            used_hbm_gb=round(sum(d.hbm_used_gb for d in devices), 3),
            average_duty_cycle_pct=round(sum(duty) / len(duty), 2) if duty else None,
            average_temperature_c=round(sum(temps) / len(temps), 2) if temps else None,
            devices=devices,
            fleet_alerts=fleet_alerts,
            telemetry_sources=telemetry_sources,
            ici_links=ici_links,
            telemetry_staleness=staleness,
        )

    def select_best_device(
        self,
        min_free_hbm_gb: float = 0.0,
        metrics: Optional[Sequence[dict[str, Any]]] = None,
        metrics_json: Optional[str] = None,
    ) -> Optional[TPUDevice]:
        """Pick the least-loaded schedulable chip.

        Reference ``select_best_gpu`` (``gpu_manager.py:323-346``): filter by
        availability + free-memory requirement, sort by (−free HBM, duty).
        """
        fleet = self.get_fleet_status(metrics=metrics, metrics_json=metrics_json)
        return self.select_from_fleet(fleet, min_free_hbm_gb=min_free_hbm_gb)

    @staticmethod
    def select_from_fleet(
        fleet: TPUFleetStatus, min_free_hbm_gb: float = 0.0
    ) -> Optional[TPUDevice]:
        """The selection policy, shared by live and mock/fallback paths."""
        candidates = [
            d for d in fleet.devices if d.is_available and d.hbm_free_gb >= min_free_hbm_gb
        ]
        if not candidates:
            return None
        candidates.sort(key=lambda d: (-d.hbm_free_gb, d.duty_cycle_pct or 0.0))
        return candidates[0]

    # -- fixtures ------------------------------------------------------------

    @staticmethod
    def get_mock_fleet() -> TPUFleetStatus:
        """Hand-built v5e-8 fleet: 7 healthy chips + 1 warning chip.

        Test/demo fixture, parity with reference ``get_mock_fleet``
        (``gpu_manager.py:400-431``).
        """
        mgr = TPUManager(devices=[])
        metrics = []
        for i in range(8):
            hot = i == 5
            metrics.append(
                {
                    "index": i,
                    "device_kind": "TPU v5e",
                    "platform": "tpu",
                    "coords": (i % 4, i // 4, 0),
                    "hbm_total_gb": 16.0,
                    "hbm_used_gb": 14.2 if hot else 6.4,
                    "duty_cycle_pct": 97.5 if hot else 62.0,
                    "temperature_c": 83.0 if hot else 54.0,
                    "power_draw_w": 170.0 if hot else 120.0,
                    "power_limit_w": 192.0,
                    "process_index": 0,
                }
            )
        fleet = mgr.get_fleet_status(metrics=metrics)
        return fleet


# ---------------------------------------------------------------------------
# CLI — `python -m tpu_engine.tpu_manager` (the tpu-info / nvidia-smi UX:
# one fleet table, live sources when available).
# ---------------------------------------------------------------------------


def _fmt(v: Any, suffix: str = "") -> str:
    return "-" if v is None else f"{v}{suffix}"


def main(argv: Optional[list[str]] = None) -> int:
    import argparse

    parser = argparse.ArgumentParser(description="TPU fleet status")
    parser.add_argument("--mock", action="store_true", help="show the mock fleet")
    parser.add_argument("--json", action="store_true", help="raw JSON instead of a table")
    args = parser.parse_args(argv)

    fleet = TPUManager.get_mock_fleet() if args.mock else TPUManager().get_fleet_status()
    if args.json:
        print(fleet.model_dump_json(indent=2))
        return 0

    src = ",".join(fleet.telemetry_sources) or "runtime"
    print(
        f"devices: {fleet.total_devices} ({fleet.available_devices} available)"
        f"   HBM: {fleet.used_hbm_gb:.1f}/{fleet.total_hbm_gb:.1f} GiB"
        f"   telemetry: {src}"
    )
    header = f"{'idx':>3} {'kind':<14} {'hbm':>13} {'duty%':>6} {'mxu%':>6} {'thr':>4} {'temp':>5} {'health':<8}"
    print(header)
    print("-" * len(header))
    for d in fleet.devices:
        print(
            f"{d.index:>3} {d.device_kind:<14} "
            f"{d.hbm_used_gb:>5.1f}/{d.hbm_total_gb:<5.1f}G "
            f"{_fmt(d.duty_cycle_pct):>6} {_fmt(d.tensorcore_util_pct):>6} "
            f"{_fmt(d.throttle_score):>4} {_fmt(d.temperature_c):>5} "
            f"{d.health_status.value:<8}"
        )
    for a in fleet.fleet_alerts:
        print(f"! {a}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
