"""Autoregressive generation: KV-cache decode + sampling on the mesh.

The reference is a training control plane with no inference path at all;
a complete framework needs one for held-out evaluation, sampling during
training, and serving smoke tests. TPU-first design:

- **Static shapes end to end.** The cache is a fixed-``max_len`` set of
  ``[L, B, M, KV, HD]`` buffers written with ``dynamic_update_slice``; the
  decode loop is a ``lax.scan`` over ``max_new_tokens`` — no data-dependent
  Python control flow, one compile per (batch, max_len) shape.
- **Same layer scan as training.** Layers are stacked ``[L, ...]`` pytrees
  (``models/transformer.py``), so decode scans the cache alongside the
  layer stack instead of unrolling Python loops per layer.
- **Sharding by propagation.** Under ``jit`` on a mesh, XLA propagates the
  param shardings (heads/experts over "model", batch over data axes) into
  the cache and attention ops; no decode-specific partition specs needed.

MoE decode note: the training forward uses capacity-bounded dispatch
(tokens over an expert's capacity are dropped — the standard static-shape
formulation, ``_moe_mlp``). Decode processes a handful of positions, so it
computes exact capacity-free top-k routing instead (every token reaches
its chosen experts). Dense models produce bit-identical logits between
:func:`forward` and prefill+decode; MoE models can differ wherever
training-time dispatch dropped a token.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from tpu_engine.models.transformer import (
    ModelConfig,
    _dense_mlp,
    _norm,
    _proj,
    _rms_norm,
    _rope,
    cast_layer_stack,
    embed_tokens,
    unembed,
)
from tpu_engine.quant import QuantWeight, dequantize_weight

_NEG_INF = -1e30


@jax.tree_util.register_dataclass
@dataclass
class KVCache:
    """Per-layer key/value cache (a pytree — crosses jit/scan boundaries).

    k/v: [L, B, slots, KV, HD]; ``pos`` [slots] holds the global position
    stored in each slot (-1 = empty); ``length`` is the number of positions
    already written (scalar int32). When ``ring`` is set (sliding-window
    models whose cache is smaller than the sequence) the buffer wraps:
    writes go to ``position % slots`` and the attention mask reads ``pos``,
    so memory and per-step attention cost are O(window), not O(sequence).
    Non-ring caches keep the classic contract: the caller never writes past
    ``slots`` positions total."""

    k: jax.Array
    v: jax.Array
    pos: jax.Array
    length: jax.Array
    ring: bool = field(default=False, metadata=dict(static=True))
    # int8-quantized cache (``init_cache(kv_quant=True)``): k/v hold int8
    # codes and these hold the per-(slot, kv-head) absmax/127 scales
    # [L, B, slots, KV, 1] — KV memory halves vs bf16 (+1/head_dim for
    # scales); dequantisation fuses into the attention reads.
    k_scale: Optional[jax.Array] = None
    v_scale: Optional[jax.Array] = None

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def ring_lanes(cfg: ModelConfig, max_len: int,
               chunk: Optional[int] = None) -> int:
    """Lane count for a KV buffer: ``max_len`` for full-context models, or
    the ring size ``min(max_len, window + chunk - 1)`` for sliding-window
    models (a chunk of T queries needs the window behind its oldest query
    resident). THE single source of this formula — the serving slot pool
    copies a single-row ring cache into its own lanes and is only correct
    because both sides size lanes identically."""
    if not cfg.sliding_window:
        return max_len
    chunk = max_len if chunk is None else chunk
    return min(max_len, cfg.sliding_window + chunk - 1)


def init_cache(
    cfg: ModelConfig, batch: int, max_len: int, dtype=jnp.bfloat16,
    max_chunk: Optional[int] = None, kv_quant: bool = False,
) -> KVCache:
    """Allocate a cache able to hold ``max_len`` positions — or, for a
    sliding-window model, a ring buffer of ``window + max_chunk - 1`` slots
    (a chunk of T queries needs the window behind its oldest query to still
    be resident). ``max_chunk`` defaults to ``max_len`` (no shrink); pass
    the real prefill length (as :func:`generate` does) to get O(window)
    memory for long generations.

    ``kv_quant=True`` stores k/v as int8 with per-(slot, kv-head) scales —
    half the cache HBM of bf16, at ~1% quantisation error (symmetric
    absmax over head_dim)."""
    slots = ring_lanes(cfg, max_len, max_chunk)
    shape = (cfg.n_layers, batch, slots, cfg.n_kv_heads, cfg.head_dim)
    store_dtype = jnp.int8 if kv_quant else dtype
    scale_shape = shape[:-1] + (1,)
    return KVCache(
        k=jnp.zeros(shape, store_dtype),
        v=jnp.zeros(shape, store_dtype),
        pos=jnp.full((slots,), -1, jnp.int32),
        length=jnp.zeros((), jnp.int32),
        ring=slots < max_len,
        k_scale=jnp.zeros(scale_shape, jnp.float32) if kv_quant else None,
        v_scale=jnp.zeros(scale_shape, jnp.float32) if kv_quant else None,
    )


def _moe_mlp_decode(h, layer_params, cfg: ModelConfig):
    """Exact top-k MoE for decode: every token reaches its chosen experts
    (no capacity buffer — see module docstring). h: [B, T, D] → [B, T, D].

    Computes all E expert MLPs for the T new positions and combines with
    the renormalised top-k gates; for decode-sized T this is a handful of
    [D, F] matmuls and keeps every shape static.
    """
    E, K = cfg.n_experts, cfg.top_k
    router_logits = jnp.einsum(
        "btd,de->bte", h, layer_params["router"]["kernel"],
        preferred_element_type=jnp.float32,
    )
    probs = jax.nn.softmax(router_logits, axis=-1)  # [B, T, E] fp32

    def kern(name):
        # Expert kernels may be int8 QuantWeights (weight-only quantized
        # serving): dequantize inline — the convert+scale is an
        # elementwise producer XLA fuses into the einsum's operand read,
        # so HBM still sees int8 bytes (the scale's output-dim broadcast
        # does not line up with these expert einsums' outputs, hence
        # operand-side application here, unlike ``_proj``).
        w = layer_params[name]["kernel"]
        if isinstance(w, QuantWeight):
            return dequantize_weight(w, h.dtype)
        return w

    gate = jnp.einsum("btd,edf->btef", h, kern("gate"))
    up = jnp.einsum("btd,edf->btef", h, kern("up"))
    expert_out = jnp.einsum(
        "btef,efd->bted", jax.nn.silu(gate) * up, kern("down")
    )  # [B, T, E, D]

    # Top-k gates, renormalised to sum to 1 (matches training's combine).
    top_vals, top_idx = lax.top_k(probs, K)  # [B, T, K]
    top_vals = top_vals / jnp.maximum(jnp.sum(top_vals, -1, keepdims=True), 1e-9)
    weights = jnp.zeros_like(probs).at[
        jnp.arange(probs.shape[0])[:, None, None],
        jnp.arange(probs.shape[1])[None, :, None],
        top_idx,
    ].set(top_vals)  # [B, T, E]
    return jnp.einsum("bte,bted->btd", weights.astype(h.dtype), expert_out)


def _quantize_rows(rows: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantisation over the trailing (head_dim) axis:
    rows [B, T, KV, HD] → (int8 codes, fp32 scales [B, T, KV, 1])."""
    scale = jnp.max(jnp.abs(rows.astype(jnp.float32)), axis=-1, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-8)
    codes = jnp.clip(jnp.round(rows.astype(jnp.float32) / scale), -127, 127)
    return codes, scale


def _decode_block(x, layer_params, k_cache, v_cache, write, slot_pos, positions,
                  cfg: ModelConfig, k_scale_c=None, v_scale_c=None):
    """One transformer block attending against the cache.

    x: [B, T, D] new activations; k_cache/v_cache: [B, M, KV, HD];
    ``write(cache_arr, rows)`` stores the chunk's rows at its slots (built
    once in :func:`forward_with_cache`); ``slot_pos`` is the global
    position held by each cache slot after this chunk's writes — [M]
    (all rows in lockstep, the generate() case) or [B, M] (per-row
    positions, the continuous-batching slot pool in
    ``tpu_engine/serving.py``).
    ``k_scale_c``/``v_scale_c`` [B, M, KV, 1] are present for int8 caches:
    new rows are quantised before the write and the cache reads dequantise
    (the convert+mul fuses into the attention dots).
    """
    B, T, D = x.shape
    H, KV, HD = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    gpt2 = cfg.arch == "gpt2"

    def proj(h, name):
        return _proj(h, layer_params[name]["kernel"],
                     bias=layer_params[name]["bias"] if gpt2 else None)

    h = _norm(x, layer_params["attn_norm"], cfg)
    q = proj(h, "q").reshape(B, T, H, HD)
    k = proj(h, "k").reshape(B, T, KV, HD)
    v = proj(h, "v").reshape(B, T, KV, HD)
    if cfg.arch == "qwen":  # per-head qk-norm, before RoPE (as in training)
        q = _rms_norm(q, layer_params["q_norm"]["scale"], cfg.norm_eps)
        k = _rms_norm(k, layer_params["k_norm"]["scale"], cfg.norm_eps)
    if not gpt2:  # gpt2 adds learned positions at embed time instead
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)

    if k_scale_c is not None:
        k_codes, k_s = _quantize_rows(k)
        v_codes, v_s = _quantize_rows(v)
        k_cache = write(k_cache, k_codes)
        v_cache = write(v_cache, v_codes)
        k_scale_c = write(k_scale_c, k_s)
        v_scale_c = write(v_scale_c, v_s)
        kc = k_cache.astype(x.dtype) * k_scale_c.astype(x.dtype)
        vc = v_cache.astype(x.dtype) * v_scale_c.astype(x.dtype)
    else:
        k_cache = write(k_cache, k)
        v_cache = write(v_cache, v)
        kc, vc = k_cache, v_cache
    if KV != H:  # GQA
        kc = jnp.repeat(kc, H // KV, axis=2)
        vc = jnp.repeat(vc, H // KV, axis=2)

    scale = 1.0 / (HD ** 0.5)
    scores = jnp.einsum(
        "bthd,bmhd->bhtm", q, kc, preferred_element_type=jnp.float32
    ) * scale
    # Slot m is visible to query t iff it holds a real position (≥ 0) that
    # is ≤ the query's global position (causal). Sliding-window models
    # additionally hide keys older than the window, matching the
    # training-time mask; ring-buffer slots overwritten by in-chunk later
    # positions are masked for earlier queries by the same comparison.
    key_pos = slot_pos if slot_pos.ndim == 2 else slot_pos[None, :]  # [B|1, M]
    kp = key_pos[:, None, :]                                         # [B|1, 1, M]
    mask = (kp >= 0) & (kp <= positions[:, :, None])
    if cfg.sliding_window:
        mask &= kp > positions[:, :, None] - cfg.sliding_window
    scores = jnp.where(mask[:, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(x.dtype)
    attn = jnp.einsum("bhtm,bmhd->bthd", probs, vc).reshape(B, T, H * HD)
    x = x + proj(attn, "o")

    h = _norm(x, layer_params["mlp_norm"], cfg)
    if cfg.is_moe:
        x = x + _moe_mlp_decode(h, layer_params, cfg)
    else:
        x = x + _dense_mlp(h, layer_params, cfg=cfg)
    return x, k_cache, v_cache, k_scale_c, v_scale_c


def forward_with_cache(
    params: dict[str, Any],
    tokens: jax.Array,
    cache: KVCache,
    cfg: ModelConfig,
    compute_dtype=jnp.bfloat16,
    want_logits: bool = True,
) -> tuple[Optional[jax.Array], KVCache]:
    """Run ``tokens`` [B, T] through the stack against (and into) ``cache``.

    Serves both phases: prefill (T = prompt length) and decode (T = 1).
    Returns (logits [B, T, V] fp32, updated cache with length += T).
    ``want_logits=False`` (static) skips the unembed entirely and returns
    ``(None, cache)`` — cache-ingestion-only callers (the speculative
    draft's prompt prefill) should not pay a T×D×V matmul per chunk.

    For non-ring caches the caller must keep ``cache.length + T <=
    cache.max_len`` (size the cache to prompt + max_new_tokens, as
    :func:`generate` does). Ring caches (sliding-window models with fewer
    slots than the sequence) wrap; a chunk of T queries needs the window
    behind its oldest query resident, so the cache must hold at least
    ``window + T - 1`` slots (checked statically below — T=1 decode needs
    the full window resident too).
    """
    B, T = tokens.shape
    M = cache.max_len
    if cfg.arch == "gpt2" and not cache.ring and M > cfg.max_seq_len:
        # The cache is sized to the full generation; a learned position
        # table shorter than that would be silently clamped by jnp.take.
        raise ValueError(
            f"generation length {M} exceeds the learned position table "
            f"(max_seq_len={cfg.max_seq_len}) of gpt2-family model {cfg.name!r}"
        )
    if cache.ring and M < cfg.sliding_window + T - 1:
        raise ValueError(
            f"chunk of {T} queries needs >= {cfg.sliding_window + T - 1} cache "
            f"slots (window {cfg.sliding_window}), cache has {M}; prefill in "
            "smaller chunks or allocate with a larger max_chunk"
        )
    positions = cache.length + jnp.broadcast_to(
        jnp.arange(T, dtype=jnp.int32)[None, :], (B, T)
    )
    new_pos = cache.length + jnp.arange(T, dtype=jnp.int32)
    if cache.ring and T > 1:
        # A multi-token chunk on a ring cache can wrap mid-chunk; write it
        # as a one-hot select — TPU's scatter emitter rejects the
        # [B, slots, ...] multi-dim scatter (and even the 1-D traced-index
        # scatter for pos), and a select fuses cleanly. Slots within a
        # chunk are distinct (M >= T via the guard above), so the einsum
        # copies exactly one row per written slot. O(T·M) int ops — paid
        # only on this wrapping path, not on contiguous prefill/decode
        # (round-1 advisor finding).
        slots = new_pos % M
        onehot = jnp.arange(M)[None, :] == slots[:, None]  # [T, M]
        written = onehot.any(axis=0)
        pos_new = jnp.where(
            written,
            (onehot.astype(jnp.int32) * new_pos[:, None]).sum(axis=0),
            cache.pos,
        )

        def write(cache_arr, rows):
            rows_m = jnp.einsum("tm,btkh->bmkh", onehot.astype(cache_arr.dtype),
                                rows.astype(cache_arr.dtype))
            return jnp.where(written[None, :, None, None], rows_m, cache_arr)
    else:
        # Contiguous, non-wrapping write (T=1 ring decode, or any non-ring
        # chunk): a cheap O(T) dynamic_update_slice at the slot offset, for
        # the cache rows and the pos vector alike.
        offset = cache.length % M if cache.ring else cache.length
        pos_new = lax.dynamic_update_slice(cache.pos, new_pos, (offset,))

        def write(cache_arr, rows):
            return lax.dynamic_update_slice(
                cache_arr, rows.astype(cache_arr.dtype), (0, offset, 0, 0)
            )

    x = embed_tokens(params, tokens, compute_dtype, positions=positions, cfg=cfg)
    layer_stack = cast_layer_stack(params, compute_dtype)

    # One scan body serves both cache precisions: the scale stacks simply
    # join the scanned arrays when present (pytree structure is static per
    # trace).
    scales = (cache.k_scale, cache.v_scale) if cache.quantized else ()

    def body(carry, xs):
        x = carry
        layer_params, k_c, v_c, *scale_cs = xs
        x, k_c, v_c, ks_c, vs_c = _decode_block(
            x, layer_params, k_c, v_c, write, pos_new, positions, cfg,
            k_scale_c=scale_cs[0] if scale_cs else None,
            v_scale_c=scale_cs[1] if scale_cs else None,
        )
        return x, (k_c, v_c) + ((ks_c, vs_c) if scale_cs else ())

    x, out = lax.scan(body, x, (layer_stack, cache.k, cache.v) + scales)
    k_new, v_new = out[0], out[1]
    ks_new, vs_new = (out[2], out[3]) if cache.quantized else (None, None)
    logits = unembed(params, x, cfg) if want_logits else None
    return logits, KVCache(k=k_new, v=v_new, pos=pos_new,
                           length=cache.length + T, ring=cache.ring,
                           k_scale=ks_new, v_scale=vs_new)


def _filtered_sample(
    logits: jax.Array,
    rng: jax.Array,
    temperature,
    top_k: Optional[int],
    top_p,
) -> jax.Array:
    """Temperature → top-k → nucleus (top-p) → categorical draw.

    ``temperature`` and ``top_p`` may be Python floats *or traced scalars*
    (the decode loop passes them as operands so sweeping them never triggers
    a recompile); ``top_k`` must be static (``lax.top_k`` needs a static k).
    ``top_p=None`` skips the nucleus sort entirely. All static shapes — the
    top-p cutoff is a mask over the sorted cumulative distribution, not a
    dynamic truncation.
    """
    logits = logits / temperature
    if top_k is not None:
        kth = lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, _NEG_INF, logits)
    if top_p is not None:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum_excl = jnp.cumsum(probs, axis=-1) - probs  # mass strictly before
        keep_sorted = cum_excl < top_p  # always keeps the top token
        kept_min = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < kept_min, _NEG_INF, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def sample_token(
    logits: jax.Array,
    rng: jax.Array,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
) -> jax.Array:
    """logits [B, V] fp32 → token ids [B] int32. ``temperature=0`` = greedy."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return _filtered_sample(logits, rng, temperature, top_k, top_p)


def generate(
    params: dict[str, Any],
    prompt: jax.Array,
    cfg: ModelConfig,
    max_new_tokens: int,
    rng: Optional[jax.Array] = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    compute_dtype=jnp.bfloat16,
    kv_quant: bool = False,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt`` [B, P] int32.

    Returns [B, P + max_new_tokens] int32. One prefill pass over the prompt,
    then a ``lax.scan`` of single-token decode steps — the whole loop is one
    XLA program. Greedy by default; pass ``rng`` + ``temperature`` (and
    optionally ``top_k`` / ``top_p``) for sampling. ``kv_quant`` stores the
    KV cache as int8 (half the decode HBM; see :func:`init_cache`).

    Recompiles only on shape / ``cfg`` / ``top_k`` / greedy-vs-sampled /
    ``kv_quant`` changes: ``temperature`` and ``top_p`` enter the compiled
    program as traced scalars, so sweeping them (e.g. through the HTTP
    sampling endpoint) reuses the cached executable.
    """
    if rng is None:
        rng = jax.random.PRNGKey(0)
    greedy = temperature == 0.0
    return _generate_jit(
        params,
        prompt,
        jnp.asarray(1.0 if greedy else temperature, jnp.float32),
        jnp.asarray(1.0 if top_p is None else top_p, jnp.float32),
        rng,
        cfg=cfg,
        max_new_tokens=max_new_tokens,
        top_k=top_k,
        use_top_p=top_p is not None,
        greedy=greedy,
        compute_dtype=compute_dtype,
        kv_quant=kv_quant,
    )


@partial(
    jax.jit,
    static_argnames=(
        "cfg", "max_new_tokens", "top_k", "use_top_p", "greedy", "compute_dtype",
        "kv_quant",
    ),
)
def _generate_jit(
    params: dict[str, Any],
    prompt: jax.Array,
    temperature: jax.Array,
    top_p: jax.Array,
    rng: jax.Array,
    *,
    cfg: ModelConfig,
    max_new_tokens: int,
    top_k: Optional[int],
    use_top_p: bool,
    greedy: bool,
    compute_dtype,
    kv_quant: bool = False,
) -> jax.Array:
    B, P = prompt.shape

    def sample(logits, key):
        if greedy:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return _filtered_sample(
            logits, key, temperature, top_k, top_p if use_top_p else None
        )

    keys = jax.random.split(rng, max_new_tokens)  # one fresh key per draw
    cache = init_cache(cfg, B, P + max_new_tokens, dtype=compute_dtype,
                       max_chunk=P, kv_quant=kv_quant)
    logits, cache = forward_with_cache(params, prompt, cache, cfg, compute_dtype)
    first = sample(logits[:, -1, :], keys[0])

    def step(carry, step_rng):
        token, cache = carry
        logits, cache = forward_with_cache(
            params, token[:, None], cache, cfg, compute_dtype
        )
        nxt = sample(logits[:, -1, :], step_rng)
        return (nxt, cache), nxt

    if max_new_tokens > 1:
        _, rest = lax.scan(step, (first, cache), keys[1:])
        generated = jnp.concatenate([first[None], rest], axis=0)  # [N, B]
    else:
        generated = first[None]
    return jnp.concatenate([prompt, generated.T.astype(jnp.int32)], axis=1)


def speculative_generate(
    params: dict[str, Any],
    draft_params: dict[str, Any],
    prompt: jax.Array,
    cfg: ModelConfig,
    draft_cfg: ModelConfig,
    max_new_tokens: int,
    gamma: int = 4,
    compute_dtype=jnp.bfloat16,
    return_stats: bool = False,
) -> jax.Array:
    """Speculative greedy decoding: a small draft model proposes ``gamma``
    tokens autoregressively, the target verifies them in ONE forward pass,
    and the longest agreeing prefix (plus the target's correction token) is
    accepted — output is identical to plain greedy decoding of the target,
    in fewer target forward passes.

    Exactness caveat: the guarantee holds whenever the target's chunked
    (T=gamma+1) and incremental (T=1) forwards agree on the argmax. That is
    bit-exact on the CPU backend (pinned in tests); on TPU, XLA's matmul
    pass structure differs with chunk size (~1e-2 logit deltas), so
    near-argmax-ties — pervasive in random-init models, rare in trained
    ones — can resolve differently than single-token greedy.

    Cache rewind is free by construction: rejected positions simply leave
    stale entries whose stored global position exceeds every later query
    (masked by the position-based attention mask) until the sequence
    re-reaches them, at which point the write lands on the same slot before
    attention runs. ``length`` is rolled back to the accepted frontier and
    nothing else needs cleaning.

    Batch 1 only (acceptance lengths diverge across rows). Returns
    [1, P + max_new_tokens] int32 — or, with ``return_stats=True``,
    ``(tokens, rounds)`` where ``rounds`` is the number of target forward
    passes taken (a perfect draft needs ceil(N / (gamma+1))).
    """
    if prompt.shape[0] != 1:
        raise ValueError("speculative_generate supports batch size 1")
    if gamma < 1:
        raise ValueError(f"gamma must be >= 1, got {gamma}")
    out, rounds = _speculative_jit(
        params, draft_params, prompt,
        cfg=cfg, draft_cfg=draft_cfg, max_new_tokens=max_new_tokens,
        gamma=gamma, compute_dtype=compute_dtype,
    )
    return (out, int(rounds)) if return_stats else out


@partial(
    jax.jit,
    static_argnames=("cfg", "draft_cfg", "max_new_tokens", "gamma", "compute_dtype"),
)
def _speculative_jit(
    params, draft_params, prompt, *,
    cfg: ModelConfig, draft_cfg: ModelConfig,
    max_new_tokens: int, gamma: int, compute_dtype,
) -> jax.Array:
    P = prompt.shape[1]
    total = P + max_new_tokens
    buf_len = total + gamma + 1  # room for one over-full final round

    cache = init_cache(cfg, 1, buf_len, dtype=compute_dtype,
                       max_chunk=max(P - 1, gamma + 1))
    dcache = init_cache(draft_cfg, 1, buf_len, dtype=compute_dtype,
                        max_chunk=max(P - 1, 1))

    out = jnp.zeros((1, buf_len), jnp.int32)
    out = lax.dynamic_update_slice(out, prompt.astype(jnp.int32), (0, 0))

    # Ingest the prompt minus its last token (the last token is re-fed each
    # round so its logits participate in verification).
    if P > 1:
        _, cache = forward_with_cache(params, prompt[:, :-1], cache, cfg,
                                      compute_dtype)
        _, dcache = forward_with_cache(draft_params, prompt[:, :-1], dcache,
                                       draft_cfg, compute_dtype)

    def round_body(state):
        out, out_len, rounds, cache, dcache = state
        t_last = lax.dynamic_slice(out, (0, out_len - 1), (1, 1))  # [1, 1]

        # Draft proposes gamma tokens, one at a time. One extra step beyond
        # gamma (its output discarded) so the draft also ingests its own
        # last proposal's K/V: on a fully-accepted round the rewind
        # advances past that position, and without the write it would stay
        # a permanent hole in the draft cache, silently halving acceptance.
        def draft_step(carry, _):
            tok, dc = carry
            logits, dc = forward_with_cache(draft_params, tok, dc, draft_cfg,
                                            compute_dtype)
            nxt = jnp.argmax(logits[:, -1, :], axis=-1)[:, None].astype(jnp.int32)
            return (nxt, dc), nxt[0, 0]

        (_, dcache), proposals = lax.scan(
            draft_step, (t_last, dcache), None, length=gamma + 1
        )
        proposals = proposals[:gamma]  # [gamma]

        # Target verifies the whole proposal chain in one forward pass.
        chain = jnp.concatenate([t_last[0], proposals])[None, :]  # [1, gamma+1]
        logits, cache = forward_with_cache(params, chain, cache, cfg,
                                           compute_dtype)
        tgt = jnp.argmax(logits[0], axis=-1).astype(jnp.int32)  # [gamma+1]

        # Longest agreeing prefix; tgt[a] is the free correction/bonus token.
        matches = proposals == tgt[:-1]
        a = jnp.sum(jnp.cumprod(matches.astype(jnp.int32)))
        out = lax.dynamic_update_slice(out, tgt[None, :], (0, out_len))
        new_len = out_len + a + 1

        # Rewind both caches to the accepted frontier (stale entries are
        # masked by position and overwritten on re-arrival).
        cache = dataclasses.replace(cache, length=new_len - 1)
        dcache = dataclasses.replace(dcache, length=new_len - 1)
        return out, new_len, rounds + 1, cache, dcache

    def cond(state):
        return state[1] < total

    out, _, rounds, _, _ = lax.while_loop(
        cond, round_body,
        (out, jnp.asarray(P, jnp.int32), jnp.zeros((), jnp.int32), cache, dcache),
    )
    return out[:, :total], rounds
