"""Heterogeneity plane: throughput-weighted data sharding for uneven gangs.

The synchronous gang assumes uniform chips: ``data.py`` splits every step's
``[accum, global_micro]`` batch into equal per-process rows, so one degraded
host (thermal throttle, flaky ICI link, mixed-generation slice) drags the
whole step to its speed — and the only remedy used to be
``elastic_shrink_plan``, which throws the slow-but-healthy host away
entirely. Poplar (arXiv 2408.12596) shows that assigning *non-uniform*
per-device batch proportional to measured throughput recovers near-ideal
goodput on heterogeneous fleets; ZeRO-Infinity-style capacity reasoning
(arXiv 2104.07857) is the constraint — uneven batch means uneven activation
HBM, so every candidate assignment must stay inside each device's envelope
(``hbm_estimate.estimate_job_hbm`` re-run at the per-process micro batch).

Three layers, smallest first:

- :class:`ThroughputTracker` — per-process relative-throughput EMA over
  profiler step timings, *seeded* by the flight recorder's sustained
  host-slow attribution (the supervisor's anomaly path and the ``faults.py``
  host-slow seam both feed it) and *decaying* back toward 1.0 every quiet
  step so transient stalls heal instead of permanently skewing the split.
- :func:`solve_row_assignment` — integer apportionment (largest-remainder)
  of the global micro batch proportional to throughput, subject to a
  minimum-rows floor and optional per-process row caps (HBM feasibility),
  preserving the declared global batch **exactly** — the sum invariant is
  property-tested, never "approximately right".
- :class:`HeteroRebalancer` — the hysteresis-guarded policy loop the
  supervisor consults: never more than one rebalance per cooldown window,
  only on sustained imbalance, only when the predicted goodput gain clears
  a floor, dry-run mode by default, and every decision (acted, dry-run, or
  skipped) is audited on the flight recorder.

Consumers: the supervisor (periodic consult + ``data_fn.reassign``), the
``FleetScheduler`` (prefers rebalance over elastic shrink for
slow-but-healthy hosts), ``PlacementPlanner`` (per-device throughput as a
cost-model input), ``GET /api/v1/hetero`` and the ``tpu_engine_hetero_*``
Prometheus families, and the ``benchmarks/chaos.py`` hetero lane
(rebalance-on vs rebalance-off vs shrink on a seeded 25%-degraded host).
"""

from __future__ import annotations

import math
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional, Sequence

from tpu_engine import tracing

# A relative throughput below this is treated as this (a host reporting
# ~zero throughput is dying, not slow — shrink/self-heal owns that case,
# and the apportionment must never divide by zero or starve the gang).
MIN_RELATIVE_THROUGHPUT = 0.05


class InfeasibleAssignment(ValueError):
    """No integer assignment satisfies the floor/cap constraints exactly."""


# -- pure apportionment -------------------------------------------------------


def uniform_assignment(total_rows: int, n: int) -> list[int]:
    """The equal split (remainder spread over the first processes) —
    the implicit assignment every gang starts from."""
    if n <= 0:
        raise ValueError(f"need at least one process, got {n}")
    base, rem = divmod(int(total_rows), n)
    return [base + (1 if i < rem else 0) for i in range(n)]


def solve_row_assignment(
    throughputs: Sequence[float],
    total_rows: int,
    *,
    min_rows: int = 1,
    max_rows: Optional[Sequence[Optional[int]]] = None,
) -> list[int]:
    """Integer per-process rows proportional to ``throughputs``.

    Largest-remainder apportionment with a per-process floor (``min_rows``)
    and optional per-process caps (``max_rows``, ``None`` = uncapped — the
    HBM-feasibility hook). The result always sums to ``total_rows`` exactly;
    when floors and caps make that impossible, :class:`InfeasibleAssignment`
    is raised rather than silently changing the declared global batch.
    Deterministic: ties break by lowest process index.
    """
    n = len(throughputs)
    if n <= 0:
        raise ValueError("throughputs must be non-empty")
    total = int(total_rows)
    if total < n * min_rows:
        raise InfeasibleAssignment(
            f"{total} rows cannot give {n} processes the {min_rows}-row floor"
        )
    caps = [
        total if (max_rows is None or max_rows[i] is None) else int(max_rows[i])
        for i in range(n)
    ]
    if any(c < min_rows for c in caps):
        raise InfeasibleAssignment(
            f"per-process row cap below the {min_rows}-row floor: {caps}"
        )
    if sum(caps) < total:
        raise InfeasibleAssignment(
            f"row caps {caps} sum to {sum(caps)} < global micro batch {total}"
        )
    w = [max(float(t), MIN_RELATIVE_THROUGHPUT) for t in throughputs]
    sw = sum(w)
    quotas = [total * wi / sw for wi in w]
    rows = [min(max(int(math.floor(q)), min_rows), caps[i]) for i, q in enumerate(quotas)]

    # Top up by largest fractional remainder (classic largest-remainder),
    # then drain by most-over-quota — both loops terminate because the
    # feasible region is non-empty (checked above) and every iteration
    # moves sum(rows) one row toward total.
    while sum(rows) < total:
        i = max(
            (i for i in range(n) if rows[i] < caps[i]),
            key=lambda i: (quotas[i] - rows[i], -i),
        )
        rows[i] += 1
    while sum(rows) > total:
        i = max(
            (i for i in range(n) if rows[i] > min_rows),
            key=lambda i: (rows[i] - quotas[i], -i),
        )
        rows[i] -= 1
    return rows


def predicted_goodput(
    assignment: Sequence[int], throughputs: Sequence[float]
) -> float:
    """Fraction of ideal gang throughput this assignment achieves.

    The synchronous step is gated by the slowest process
    (``max_i rows_i / rate_i``); the ideal is the work-conserving bound
    ``total_rows / sum(rate)``. Unit-free — per-row seconds cancel.
    """
    rates = [max(float(t), MIN_RELATIVE_THROUGHPUT) for t in throughputs]
    total = sum(int(r) for r in assignment)
    if total <= 0:
        return 0.0
    actual = max(int(r) / rate for r, rate in zip(assignment, rates))
    if actual <= 0:
        return 1.0
    return (total / sum(rates)) / actual


def hbm_max_rows_fn(
    config: Any,
    n_processes: int,
    hbm_budget_gib: float,
    *,
    estimate_fn: Optional[Callable[..., Any]] = None,
    margin_frac: float = 0.10,
) -> Callable[[int, int], Optional[int]]:
    """Per-process HBM row caps for :func:`solve_row_assignment`.

    Uneven rows mean uneven activation/logit HBM: a process holding
    ``rows`` of the uniform split's ``rows_u`` runs an effective micro
    batch of ``micro × rows / rows_u``, and the estimate must be re-run at
    that batch (ZeRO-Infinity-style capacity reasoning). Returns
    ``max_rows(process_index, rows_uniform) -> cap`` computed by binary
    search over the monotone estimate; ``None`` when the estimator cannot
    price the config (caller then skips the HBM gate, as admission did).
    """
    if estimate_fn is None:
        from tpu_engine.hbm_estimate import estimate_job_hbm

        estimate_fn = estimate_job_hbm
    budget = float(hbm_budget_gib) / (1.0 + margin_frac)
    micro = int(getattr(config, "micro_batch_size", 0) or 0)

    def _fits(rows: int, rows_u: int) -> Optional[bool]:
        eff = max(int(math.ceil(micro * rows / max(rows_u, 1))), 1)
        try:
            est = estimate_fn(config.model_copy(update={"micro_batch_size": eff}))
        except Exception:
            return None
        if est is None:
            return None
        return float(est.device_total_gib) <= budget

    def max_rows(process_index: int, rows_uniform: int) -> Optional[int]:
        if micro <= 0 or rows_uniform <= 0:
            return None
        if _fits(1, rows_uniform) is not True:
            # Even one row does not provably fit (or the estimator cannot
            # price it) — report "no cap known" rather than an impossible 0.
            return None
        lo, hi = 1, max(rows_uniform * n_processes, 1)
        if _fits(hi, rows_uniform):
            return hi
        while lo + 1 < hi:
            mid = (lo + hi) // 2
            if _fits(mid, rows_uniform):
                lo = mid
            else:
                hi = mid
        return lo

    return max_rows


# -- throughput tracking ------------------------------------------------------


class ThroughputTracker:
    """Per-process relative-throughput EMA with decay-to-healthy.

    ``1.0`` means full speed; a sustained host-slow signal pulls the slow
    process's estimate down toward ``baseline / (baseline + penalty)``;
    every quiet observed step relaxes all *unreinforced* estimates back
    toward 1.0 by ``decay`` — transient stalls heal, chronic ones persist.
    Thread-safe (the supervisor step loop and scheduler poll both read it).
    """

    def __init__(
        self,
        n_processes: int,
        *,
        alpha: float = 0.25,
        decay: float = 0.02,
    ):
        if n_processes <= 0:
            raise ValueError(f"n_processes must be positive, got {n_processes}")
        self.n_processes = int(n_processes)
        self.alpha = float(alpha)
        self.decay = float(decay)
        self._lock = threading.Lock()
        self._rel = [1.0 for _ in range(self.n_processes)]
        self._reinforced = [False for _ in range(self.n_processes)]
        self._baseline_s: Optional[float] = None
        self.steps_observed = 0
        self.slow_signals_total = 0
        self.attribution_seeds_total = 0

    def observe_step(self, duration_s: float) -> None:
        """One gang step: refresh the healthy-step baseline (EMA of the
        fastest recent steps) and decay every estimate that was not
        reinforced since the last observation."""
        dt = float(duration_s)
        if dt <= 0:
            return
        with self._lock:
            self.steps_observed += 1
            if self._baseline_s is None or dt < self._baseline_s:
                self._baseline_s = dt
            else:
                # Slow drift upward so a genuinely slower regime (bigger
                # batch after rebalance) re-baselines instead of reading
                # as a permanent anomaly.
                self._baseline_s = 0.98 * self._baseline_s + 0.02 * dt
            for i in range(self.n_processes):
                if self._reinforced[i]:
                    self._reinforced[i] = False
                else:
                    self._rel[i] += self.decay * (1.0 - self._rel[i])

    def note_host_slow(
        self,
        process_index: Optional[int],
        penalty_s: float,
        baseline_s: Optional[float] = None,
    ) -> None:
        """A host-slow signal (the ``faults.py`` seam or a real detector):
        the process ran at ``baseline / (baseline + penalty)`` speed."""
        pen = float(penalty_s)
        if pen <= 0:
            return
        with self._lock:
            base = float(baseline_s) if baseline_s else (self._baseline_s or pen)
            if base <= 0:
                return
            i = self._clamp_index(process_index)
            obs = max(base / (base + pen), MIN_RELATIVE_THROUGHPUT)
            self._rel[i] = (1 - self.alpha) * self._rel[i] + self.alpha * obs
            self._reinforced[i] = True
            self.slow_signals_total += 1

    def note_attribution(
        self,
        cause: str,
        anomaly: dict[str, Any],
        process_index: Optional[int] = None,
    ) -> None:
        """Seed from the flight recorder's step-anomaly attribution: a
        *sustained* anomaly blamed on host-slow means the gang is running
        at ``baseline_s / duration_s`` of its healthy speed."""
        if cause != "host-slow" or not anomaly.get("sustained"):
            return
        dur = float(anomaly.get("duration_s") or 0.0)
        base = float(anomaly.get("baseline_s") or 0.0)
        if dur <= base or base <= 0:
            return
        with self._lock:
            i = self._clamp_index(process_index)
            obs = max(base / dur, MIN_RELATIVE_THROUGHPUT)
            self._rel[i] = (1 - self.alpha) * self._rel[i] + self.alpha * obs
            self._reinforced[i] = True
            self.attribution_seeds_total += 1

    def _clamp_index(self, process_index: Optional[int]) -> int:
        i = 0 if process_index is None else int(process_index)
        return min(max(i, 0), self.n_processes - 1)

    def relative_throughput(self) -> list[float]:
        with self._lock:
            return list(self._rel)

    def imbalance(self) -> float:
        """max/min relative throughput — 1.0 means a uniform gang."""
        with self._lock:
            lo = min(self._rel)
            return (max(self._rel) / lo) if lo > 0 else float("inf")

    def baseline_s(self) -> Optional[float]:
        with self._lock:
            return self._baseline_s

    def stats(self) -> dict[str, Any]:
        with self._lock:
            lo = min(self._rel)
            return {
                "n_processes": self.n_processes,
                "relative_throughput": [round(r, 4) for r in self._rel],
                "imbalance_ratio": round((max(self._rel) / lo) if lo > 0 else 0.0, 4),
                "baseline_step_s": self._baseline_s,
                "steps_observed": self.steps_observed,
                "slow_signals_total": self.slow_signals_total,
                "attribution_seeds_total": self.attribution_seeds_total,
            }


# -- per-host health ----------------------------------------------------------


def host_health(
    n_hosts: int,
    relative_throughput: Optional[Sequence[float]] = None,
    quarantined_devices: Sequence[int] = (),
    devices_per_host: int = 1,
    fault_counts: Optional[dict[int, int]] = None,
) -> list[float]:
    """Composite 0–1 health score per host.

    Folds the three degradation signals this plane already measures into
    one scalar the historian can retain and the autopilot can threshold:
    the tracker's relative-throughput EMA (clamped to [0, 1] — a host
    running *faster* than the gang is healthy, not >1 healthy), a 4×
    penalty while any of the host's devices sits in scheduler
    quarantine, and a per-recent-fault penalty (40% each, floored at
    0.2 so a flapping host stays visible instead of pinning to 0).
    Pure function: callers map devices to hosts and window the fault
    counts (``backend/routers/metrics.py`` uses the flight recorder's
    recent fleet fault events).
    """
    n_hosts = max(1, int(n_hosts))
    devices_per_host = max(1, int(devices_per_host))
    rel = list(relative_throughput or [])
    quarantined_hosts = {
        int(d) // devices_per_host for d in quarantined_devices
    }
    scores = []
    for h in range(n_hosts):
        score = min(1.0, max(0.0, rel[h] if h < len(rel) else 1.0))
        if h in quarantined_hosts:
            score *= 0.25
        faults = int((fault_counts or {}).get(h, 0))
        if faults > 0:
            score *= max(0.2, 1.0 - 0.4 * faults)
        scores.append(score)
    return scores


# -- rebalance policy ---------------------------------------------------------


@dataclass
class RebalancePlan:
    """One rebalance decision — what the audit event and the caller see."""

    step: int
    ts: float
    assignment: list[int]
    previous: list[int]
    throughputs: list[float]
    imbalance: float
    goodput_before: float
    goodput_after: float
    dry_run: bool
    reason: str = "imbalance"
    hbm_capped: list[int] = field(default_factory=list)

    def describe(self) -> dict[str, Any]:
        return {
            "step": self.step,
            "ts": self.ts,
            "assignment": list(self.assignment),
            "previous": list(self.previous),
            "throughputs": [round(t, 4) for t in self.throughputs],
            "imbalance": round(self.imbalance, 4),
            "goodput_before": round(self.goodput_before, 4),
            "goodput_after": round(self.goodput_after, 4),
            "dry_run": self.dry_run,
            "reason": self.reason,
            "hbm_capped": list(self.hbm_capped),
        }


def broadcast_agree_fn() -> Callable[[Sequence[float]], list[float]]:
    """Cross-process agreement hook for :class:`HeteroRebalancer`.

    Every process adopts process 0's throughput estimates before solving,
    so — together with step-keyed consults and a step-based cooldown —
    all ranks derive the identical assignment at the identical step and
    the per-process row windows can never overlap or gap. Identity on a
    single-process runtime; degrades to the process-local estimates (with
    one warning) when the collective is unavailable.
    """
    warned = [False]

    def agree(tput: Sequence[float]) -> list[float]:
        vals = [float(t) for t in tput]
        try:
            import jax

            if jax.process_count() <= 1:
                return vals
            import numpy as np
            from jax.experimental import multihost_utils

            out = multihost_utils.broadcast_one_to_all(
                np.asarray(vals, np.float64)
            )
            return [float(x) for x in out]
        except Exception:
            if not warned[0]:
                warned[0] = True
                import logging

                logging.getLogger(__name__).warning(
                    "hetero: cross-process broadcast unavailable; falling "
                    "back to process-local throughput estimates",
                    exc_info=True,
                )
            return vals

    return agree


class HeteroRebalancer:
    """Hysteresis-guarded rebalance loop over a :class:`ThroughputTracker`.

    ``maybe_rebalance`` is safe to call every step: it acts at most once
    per cooldown window (``cooldown_s`` wall-clock, or ``cooldown_steps``
    when set — the deterministic choice for multi-process gangs), only
    after ``sustain_consults`` consecutive consults propose a different
    split (a single noisy reading never moves the gang), and only when the
    predicted goodput gain clears ``min_gain``. ``dry_run=True`` (the
    default) evaluates and audits the decision without changing the live
    assignment — the supervisor flips it per job. Every path lands an
    audit event on the flight recorder.

    Cross-process agreement is *enforced*, not assumed: on multi-process
    runtimes the owner wires ``agree_fn`` (see :func:`broadcast_agree_fn`)
    so every rank solves from rank 0's estimates, consults happen at the
    same step on every rank (the supervisor's step-keyed modulo check),
    and ``cooldown_steps`` replaces wall-clock cooldown so no rank's clock
    skew can make it skip a consult its peers acted on. Out-of-band
    consult requests (:meth:`request_consult`, the scheduler's
    rebalance-over-shrink path) are therefore only honored between step
    boundaries on single-process runtimes.
    """

    def __init__(
        self,
        tracker: ThroughputTracker,
        global_micro: int,
        *,
        min_rows: int = 1,
        cooldown_s: float = 60.0,
        cooldown_steps: Optional[int] = None,
        imbalance_trigger: float = 1.15,
        min_gain: float = 0.03,
        sustain_consults: int = 2,
        dry_run: bool = True,
        max_rows_fn: Optional[Callable[[int, int], Optional[int]]] = None,
        agree_fn: Optional[Callable[[Sequence[float]], list[float]]] = None,
        clock: Callable[[], float] = time.time,
        recorder: Optional[Any] = None,
        trace_id: Optional[str] = None,
    ):
        self.tracker = tracker
        self.global_micro = int(global_micro)
        self.min_rows = int(min_rows)
        self.cooldown_s = float(cooldown_s)
        self.cooldown_steps = (
            None if cooldown_steps is None else int(cooldown_steps)
        )
        self.imbalance_trigger = float(imbalance_trigger)
        self.min_gain = float(min_gain)
        self.sustain_consults = int(sustain_consults)
        self.dry_run = bool(dry_run)
        self.max_rows_fn = max_rows_fn
        self.agree_fn = agree_fn
        self.clock = clock
        self._recorder = recorder
        self.trace_id = trace_id or "fleet"
        self._lock = threading.Lock()
        self.assignment = uniform_assignment(self.global_micro, tracker.n_processes)
        self.last_rebalance_at: Optional[float] = None
        self.last_rebalance_step: Optional[int] = None
        self.last_plan: Optional[RebalancePlan] = None
        self._pending = 0  # consecutive consults proposing a change
        self._consult_requested = False
        self.rebalances_total = 0
        self.dry_runs_total = 0
        self.reverts_total = 0
        self.consults_total = 0
        self.skips: dict[str, int] = {
            "cooldown": 0, "balanced": 0, "sustain": 0, "gain": 0, "hbm": 0,
        }

    def _rec(self) -> Any:
        return self._recorder if self._recorder is not None else tracing.get_recorder()

    def _skip(self, reason: str) -> None:
        self.skips[reason] = self.skips.get(reason, 0) + 1

    def request_consult(self) -> None:
        """Ask the owner to serve ``maybe_rebalance`` at its next step
        boundary (the ``FleetScheduler``'s rebalance-over-shrink path).
        The scheduler thread never moves rows itself: only the
        supervisor's step loop is a safe reassignment point, and on
        multi-process runtimes only a step-keyed consult keeps the ranks
        in agreement."""
        with self._lock:
            self._consult_requested = True

    def consult_pending(self) -> bool:
        with self._lock:
            return self._consult_requested

    def _in_cooldown(self, step: int, now: float) -> bool:
        # Caller holds the lock. Step-based when configured (deterministic
        # across processes); wall-clock otherwise.
        if self.cooldown_steps is not None:
            return (
                self.last_rebalance_step is not None
                and int(step) - self.last_rebalance_step < self.cooldown_steps
            )
        return (
            self.last_rebalance_at is not None
            and now - self.last_rebalance_at < self.cooldown_s
        )

    def maybe_rebalance(
        self, step: int, now: Optional[float] = None
    ) -> Optional[RebalancePlan]:
        """One consult. Returns a :class:`RebalancePlan` when a rebalance
        (or dry-run of one) fired; ``None`` on every guarded skip."""
        now = self.clock() if now is None else float(now)
        with self._lock:
            self.consults_total += 1
            self._consult_requested = False  # this consult serves any request
            tput = self.tracker.relative_throughput()
            if self.agree_fn is not None:
                agreed = [float(t) for t in self.agree_fn(tput)]
                if len(agreed) == len(tput):
                    tput = agreed
            n = len(tput)
            rows_u = max(self.global_micro // n, 1)
            caps = None
            capped: list[int] = []
            if self.max_rows_fn is not None:
                caps = [self.max_rows_fn(i, rows_u) for i in range(n)]
                capped = [i for i, c in enumerate(caps) if c is not None and c < self.global_micro]
            try:
                proposed = solve_row_assignment(
                    tput, self.global_micro, min_rows=self.min_rows, max_rows=caps
                )
            except InfeasibleAssignment:
                self._skip("hbm")
                self._audit("hetero_rebalance_skip", step, now, {"reason": "hbm-infeasible"})
                return None
            if proposed == self.assignment:
                self._pending = 0
                self._skip("balanced")
                return None
            # Imbalance from the AGREED estimates (== the tracker's own
            # when no agree_fn): every rank must take the same branch.
            lo = min(tput)
            imb = (max(tput) / lo) if lo > 0 else float("inf")
            before = predicted_goodput(self.assignment, tput)
            after = predicted_goodput(proposed, tput)
            # Healing back toward uniform is triggered by the *gain*, not
            # the imbalance ratio (a healed gang has imbalance ≈ 1 but a
            # stale skewed split still gates on its over-loaded hosts).
            if imb < self.imbalance_trigger and after - before < self.min_gain:
                self._pending = 0
                self._skip("balanced")
                return None
            self._pending += 1
            if self._pending < self.sustain_consults:
                self._skip("sustain")
                return None
            if self._in_cooldown(step, now):
                self._skip("cooldown")
                return None
            if after - before < self.min_gain:
                self._skip("gain")
                self._audit(
                    "hetero_rebalance_skip", step, now,
                    {"reason": "gain-below-floor",
                     "goodput_before": round(before, 4),
                     "goodput_after": round(after, 4)},
                )
                return None
            plan = RebalancePlan(
                step=int(step), ts=now,
                assignment=proposed, previous=list(self.assignment),
                throughputs=tput, imbalance=imb,
                goodput_before=before, goodput_after=after,
                dry_run=self.dry_run, hbm_capped=capped,
            )
            self.last_plan = plan
            self.last_rebalance_at = now
            self.last_rebalance_step = int(step)
            self._pending = 0
            if self.dry_run:
                self.dry_runs_total += 1
            else:
                self.rebalances_total += 1
                self.assignment = list(proposed)
            self._audit("hetero_rebalance", step, now, plan.describe())
            return plan

    def _audit(self, name: str, step: int, ts: float, attrs: dict[str, Any]) -> None:
        try:
            self._rec().event(
                name, kind="hetero", trace_id=self.trace_id, ts=ts,
                attrs={"step": int(step), **attrs},
            )
        except Exception:
            pass  # audit must never take the step loop down

    def revert(self, plan: RebalancePlan) -> None:
        """Roll back a live plan the caller could not apply (the data
        layer rejected the windows, or there is no ``reassign`` seam at
        all): restore the previous assignment so
        ``hetero_assignment_rows`` and ``recovered_goodput_fraction``
        never report a split that is not actually feeding the mesh."""
        if plan.dry_run:
            return
        with self._lock:
            if self.assignment == list(plan.assignment):
                self.assignment = list(plan.previous)
            self.reverts_total += 1
        self._audit(
            "hetero_rebalance_reverted", plan.step, self.clock(),
            {"assignment": list(plan.previous),
             "rejected": list(plan.assignment)},
        )

    def recovered_goodput_fraction(self) -> float:
        """Predicted goodput of the live assignment minus the uniform
        split's, under current throughput — the "what rebalancing buys"
        gauge. 0 when uniform (or in dry-run, where nothing moved)."""
        with self._lock:
            tput = self.tracker.relative_throughput()
            uni = uniform_assignment(self.global_micro, len(tput))
            return max(
                predicted_goodput(self.assignment, tput) - predicted_goodput(uni, tput),
                0.0,
            )

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "global_micro": self.global_micro,
                "assignment": list(self.assignment),
                "dry_run": self.dry_run,
                "cooldown_s": self.cooldown_s,
                "cooldown_steps": self.cooldown_steps,
                "imbalance_trigger": self.imbalance_trigger,
                "min_gain": self.min_gain,
                "consults_total": self.consults_total,
                "rebalances_total": self.rebalances_total,
                "dry_runs_total": self.dry_runs_total,
                "reverts_total": self.reverts_total,
                "consult_requested": self._consult_requested,
                "skips": dict(self.skips),
                "last_rebalance_at": self.last_rebalance_at,
                "last_rebalance_step": self.last_rebalance_step,
                "last_plan": self.last_plan.describe() if self.last_plan else None,
                "tracker": self.tracker.stats(),
            }


# -- process-wide plane (router/metrics/scheduler default lookup) -------------

_active: Optional[HeteroRebalancer] = None
_active_lock = threading.Lock()


def set_active(rebalancer: Optional[HeteroRebalancer]) -> None:
    global _active
    with _active_lock:
        _active = rebalancer


def get_active() -> Optional[HeteroRebalancer]:
    return _active


def clear_active() -> None:
    set_active(None)
