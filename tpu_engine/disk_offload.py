"""Disk-tier optimizer-state offload — the NVMe-offload analogue.

The reference exposes ``OffloadDevice.NVME`` with pin/buffer knobs
(``deepspeed_launcher.py:29-33,197-212``): optimizer state pages to
local NVMe and a CPU-side Adam applies the update. SURVEY §2.3 noted
TPU-VMs have no NVMe *API* equivalent — but they do have local disk,
and the capability the knob buys (training a model whose optimizer
state exceeds host+device memory) ports directly:

- **master params, mu, nu live in fp32 memory-mapped files** under a
  spill directory — zero bytes of HBM, zero bytes of *resident* host
  RAM beyond the slab being updated (the page cache does the staging,
  and ``posix_fadvise`` drives it);
- **the device runs only forward/backward** on compute-dtype (bf16)
  params — the jitted step computes and clips gradients and never sees
  optimizer state at all;
- **a fused host AdamW** walks the gradient leaves one at a time:
  prefetch leaf i+1's slabs (``POSIX_FADV_WILLNEED`` — kernel
  readahead runs while leaf i updates), update leaf i in place on the
  memmap, write the new compute-dtype leaf back to device, then drop
  leaf i's pages (``POSIX_FADV_DONTNEED``) so the spill never grows
  the process's resident set.

The update math mirrors this repo's optax chain exactly
(``train.make_optimizer``: clip_by_global_norm on device →
scale_by_adam(b1, b2, eps=1e-8) → add_decayed_weights(wd, kernel-mask)
→ ``-lr`` apply), so disk-tier training is step-for-step comparable to
the in-memory path — pinned by ``tests/test_disk_offload.py``.

Persistence is a feature, not an accident: the spill directory survives
the process, so a warm restart re-attaches to the exact optimizer
moments (``attach=True`` path) — the disk tier doubles as an optimizer-
state checkpoint that costs no save step.

Multi-host scope (round 5): AdamW is elementwise, so each process
updates exactly the master SHARDS its devices hold — slab files are
keyed per shard (``path@start-stop…``), each process spills under its
own ``proc{k}/`` subdirectory, and the uploader stitches the updated
local blocks back into global sharded arrays
(``AsyncShardUploader.result``). No cross-host communication happens in
the update at all; the gradient collectives already ran on device. The
glue lives in ``train._assemble_disk_tier``; DeepSpeed's NVMe tier
works multi-node the same way (per-rank partition files).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass
from typing import Any, Optional

import jax
import numpy as np

_META = "disk_adamw.json"


def _advise(f, advice: int) -> None:
    """Best-effort fadvise on an open memmap's file descriptor."""
    try:
        size = os.fstat(f.fileno()).st_size
        os.posix_fadvise(f.fileno(), 0, size, advice)
    except (OSError, AttributeError):  # non-POSIX or closed — advisory only
        pass


@dataclass
class _Slab:
    """One parameter leaf's on-disk state: master + mu + nu memmaps."""

    path: str
    shape: tuple[int, ...]
    decay: bool
    master: np.memmap
    mu: np.memmap
    nu: np.memmap

    def files(self):
        return (self.master, self.mu, self.nu)


class DiskAdamW:
    """AdamW whose entire state lives in fp32 memmaps under ``spill_dir``.

    ``initialize(params_host)`` writes masters from a host tree and
    zeroes the moments; if a matching spill already exists (same leaf
    paths, shapes and hyperparameters) it re-attaches instead — the
    moments persist across process restarts. ``update`` applies one
    AdamW step in place, emitting each new master leaf as it lands.
    """

    def __init__(self, spill_dir: str, *, b1: float, b2: float,
                 eps: float = 1e-8, weight_decay: float = 0.0):
        self.dir = spill_dir
        self.b1, self.b2, self.eps = float(b1), float(b2), float(eps)
        self.weight_decay = float(weight_decay)
        self.slabs: dict[str, _Slab] = {}
        self.attached = False
        # The step whose update the spill last absorbed (persisted in the
        # meta file): lets a restart detect that the restored train state
        # is OLDER than the spill (a rollback) and reseed masters from it.
        self.step_on_disk: Optional[int] = None
        # Adam bias-correction counter — SEPARATE from the train step:
        # the LR schedule must keep the restored step across a reseed,
        # while the zeroed moments must bias-correct from t=1 again.
        self.moment_steps: int = 0

    # -- layout --------------------------------------------------------------

    def _meta(self) -> dict[str, Any]:
        return {
            "b1": self.b1, "b2": self.b2, "eps": self.eps,
            "weight_decay": self.weight_decay,
            "step": self.step_on_disk,
            "moment_steps": self.moment_steps,
            "leaves": {
                p: {"shape": list(s.shape), "decay": s.decay}
                for p, s in self.slabs.items()
            },
        }

    def _write_meta(self, extra: Optional[dict[str, Any]] = None) -> None:
        """Crash-atomic meta write (tmp + rename): a kill mid-write must
        never leave truncated JSON — that would fail every later attach
        instead of being refused like any other torn spill."""
        meta = self._meta()
        if extra:
            meta.update(extra)
        path = os.path.join(self.dir, _META)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(meta, f)
        os.replace(tmp, path)

    def _slab_path(self, leaf_path: str, kind: str) -> str:
        safe = leaf_path.replace("/", "__")
        return os.path.join(self.dir, f"{safe}.{kind}.f32")

    def _open_slabs(self, shapes: dict[str, tuple[int, ...]],
                    decay_mask: dict[str, bool], mode: str) -> None:
        for path, shape in shapes.items():
            self.slabs[path] = _Slab(
                path=path, shape=tuple(shape), decay=bool(decay_mask[path]),
                master=np.memmap(self._slab_path(path, "master"), np.float32,
                                 mode, shape=tuple(shape)),
                mu=np.memmap(self._slab_path(path, "mu"), np.float32, mode,
                             shape=tuple(shape)),
                nu=np.memmap(self._slab_path(path, "nu"), np.float32, mode,
                             shape=tuple(shape)),
            )

    def try_attach(self, shapes: dict[str, Any],
                   decay_mask: dict[str, bool]) -> bool:
        """Attach to an existing spill iff its meta matches this layout
        and hyperparameters AND the spill is clean (no update died
        mid-walk — a torn spill holds mixed-step state and is discarded
        rather than silently resumed). Needs only SHAPES, so a warm
        restart never materialises a throwaway random init."""
        meta_path = os.path.join(self.dir, _META)
        if not os.path.exists(meta_path):
            return False
        try:
            with open(meta_path) as f:
                have = json.load(f)
        except (json.JSONDecodeError, OSError):
            return False  # unreadable meta == untrustworthy spill
        want_leaves = {
            p: {"shape": list(s), "decay": bool(decay_mask[p])}
            for p, s in shapes.items()
        }
        if have.get("in_progress") is not None:
            return False  # torn mid-update — not trustworthy
        if have.get("leaves") != want_leaves or not all(
            have.get(k) == getattr(self, k)
            for k in ("b1", "b2", "eps", "weight_decay")
        ):
            return False
        try:
            self._open_slabs({p: tuple(s) for p, s in shapes.items()},
                             decay_mask, "r+")
        except (FileNotFoundError, ValueError, OSError):
            # Meta survived but slab files are missing/truncated (partial
            # cleanup or copy) — an untrustworthy spill falls back to
            # fresh init like every other one.
            self.slabs.clear()
            return False
        self.step_on_disk = have.get("step")
        self.moment_steps = int(have.get("moment_steps", 0))
        self.attached = True
        return True

    def initialize(self, params_host: Any,
                   decay_mask: dict[str, bool],
                   shapes: Optional[dict[str, tuple[int, ...]]] = None,
                   force_fresh: bool = False) -> bool:
        """Create (or re-attach to) the spill. ``params_host`` maps leaf
        path → fp32 ndarray, OR is a callable ``path -> ndarray`` fetched
        one leaf at a time (bounded host residency — the tier's whole
        point; pass ``shapes`` alongside). Returns True when an existing
        spill was re-attached (masters/moments kept — the caller should
        trust the DISK masters over its own init values).
        ``force_fresh`` skips the attach attempt — the multi-host
        consensus path uses it when ANOTHER host could not attach (all
        hosts must reseed together or the stitched global state mixes
        trajectories)."""
        os.makedirs(self.dir, exist_ok=True)
        fetch = params_host if callable(params_host) else params_host.get
        if shapes is None:
            if callable(params_host):
                raise ValueError("callable params_host requires shapes")
            shapes = {p: tuple(np.shape(a)) for p, a in params_host.items()}
        if force_fresh:
            self.slabs.clear()
            self.attached = False
            self.step_on_disk = None
            self.moment_steps = 0
        elif not self.slabs and self.try_attach(shapes, decay_mask):
            return True
        self.slabs.clear()
        # Fresh seed: drop slab files from any PREVIOUS layout (e.g. the
        # pre-round-5 full-leaf keying on a sharded host, or a different
        # mesh shape) — a failed attach would otherwise leave them
        # orphaned on disk forever, silently doubling spill usage.
        want = {
            self._slab_path(p, kind)
            for p in shapes for kind in ("master", "mu", "nu")
        }
        for f in os.listdir(self.dir):
            full = os.path.join(self.dir, f)
            if f.endswith(".f32") and full not in want:
                try:
                    os.remove(full)
                except OSError:
                    pass
        self._open_slabs(shapes, decay_mask, "w+")
        for path in shapes:
            slab = self.slabs[path]
            slab.master[...] = np.asarray(fetch(path), np.float32)
            slab.mu[...] = 0.0
            slab.nu[...] = 0.0
            for f in slab.files():
                f.flush()
        self.step_on_disk = None
        self._write_meta()
        self.attached = False
        return False

    def masters(self) -> dict[str, np.ndarray]:
        """Read back the fp32 master tree (copies, not memmap views).
        Materialises every leaf — callers with bounded-residency needs
        should iterate ``slabs`` and copy one master at a time."""
        return {p: np.array(s.master) for p, s in self.slabs.items()}

    def reseed_masters(self, params_host: Any,
                       step: Optional[int] = None,
                       cast_dtype: Any = None) -> None:
        """Restart the trajectory from a (restored) param tree: masters
        overwritten, moments ZEROED — exactly what loading a checkpoint
        without optimizer state does. (Keeping moments "warm" across a
        step discontinuity would apply the wrong Adam bias correction:
        ``t`` restarts small while mu/nu stay converged, inflating the
        corrected moments by up to 1/(1-b1).)

        ``params_host`` is a dict OR a callable ``path -> ndarray``
        (leaf-at-a-time, bounded residency). ``cast_dtype``: the compute
        dtype the incoming params were truncated to (e.g. bfloat16) —
        where the existing fp32 master still rounds to exactly the
        incoming value, the MASTER is kept, so a reseed from a state
        that never actually diverged (warm re-attach without a restored
        step counter) does not silently shave the masters to bf16."""
        fetch = params_host if callable(params_host) else params_host.get
        for path, slab in self.slabs.items():
            incoming = np.asarray(fetch(path), np.float32)
            if cast_dtype is not None:
                rounded = np.asarray(slab.master).astype(cast_dtype)
                keep = rounded.astype(np.float32) == incoming
                slab.master[...] = np.where(keep, slab.master, incoming)
            else:
                slab.master[...] = incoming
            slab.mu[...] = 0.0
            slab.nu[...] = 0.0
            for f in slab.files():
                f.flush()
        self.step_on_disk = step
        self.moment_steps = 0
        self._write_meta()

    # -- the update ----------------------------------------------------------

    def update(self, grads: dict[str, Any], lr: float, step: int,
               emit) -> None:
        """One AdamW step over every slab. ``grads`` maps slab key →
        device array OR a callable returning the host block (the
        shard-granular form — already clipped, fp32); ``step`` is the POST-update
        TRAIN step (bookkeeping only — bias correction uses the internal
        ``moment_steps`` counter, which survives restarts and resets with
        the moments on reseed). ``emit(path, new_master_fp32)`` receives
        each updated leaf immediately, so the caller can overlap the
        device upload of leaf i with the disk update of leaf i+1.

        Crash safety: the meta file carries an ``in_progress`` marker for
        the duration of the walk — a spill whose process died mid-update
        holds mixed-step slabs, and the marker makes the next
        ``try_attach`` refuse it instead of silently resuming."""
        import queue

        b1, b2, eps, wd = self.b1, self.b2, self.eps, self.weight_decay
        t_bias = self.moment_steps + 1
        c1 = 1.0 - b1 ** t_bias
        c2 = 1.0 - b2 ** t_bias
        self._write_meta(extra={"in_progress": step})
        order = list(self.slabs)
        # Kick kernel readahead for the first leaf's slabs, then always
        # stay one leaf ahead of the update loop.
        if order:
            for f in self.slabs[order[0]].files():
                _advise(f, os.POSIX_FADV_WILLNEED)
        # One-leaf-ahead gradient D2H: a fetcher thread pulls leaf i+1
        # off the device while the main thread's numpy update crunches
        # leaf i — the transfer and the math overlap instead of strictly
        # alternating. In the SERIAL walk regime (the default) the gets
        # contend with nothing — the device finished this step's compute
        # before the walk starts; under ``disk_update_overlap`` they
        # share the wire with step N+1's execution (see that config
        # field's measured caveat). The depth-1 queue bounds host
        # residency at THREE gradient leaves (one being updated, one
        # queued, one in the fetcher's in-flight device_get) — still
        # O(leaf), never the tree; ``abort`` poisons the fetcher if the
        # walk dies mid-update, so a failure never strands a thread
        # blocked on the queue pinning the whole device gradient tree.
        fetched: "queue.Queue" = queue.Queue(maxsize=1)
        abort = threading.Event()

        def _put(item) -> bool:
            while not abort.is_set():
                try:
                    fetched.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def _host(v) -> np.ndarray:
            # Slab keys may map to a deferred fetch (a callable pulling
            # ONE addressable shard off its device — the multi-host /
            # multi-device form) or to a whole device array.
            if callable(v):
                return np.asarray(v(), np.float32)
            return np.asarray(jax.device_get(v), np.float32)

        def _fetch() -> None:
            try:
                for p in order:
                    if not _put((p, _host(grads[p]))):
                        return
                _put(None)
            except BaseException as e:  # noqa: BLE001 — re-raised below
                _put(e)

        fetcher = threading.Thread(target=_fetch, daemon=True,
                                   name="disk-grad-fetch")
        fetcher.start()
        try:
            for i, path in enumerate(order):
                if i + 1 < len(order):
                    for f in self.slabs[order[i + 1]].files():
                        _advise(f, os.POSIX_FADV_WILLNEED)
                slab = self.slabs[path]
                item = fetched.get()
                if isinstance(item, BaseException):
                    raise item
                _, g = item
                if g.shape != slab.shape:
                    raise ValueError(
                        f"grad leaf {path} shape {g.shape} != master {slab.shape}"
                    )
                mu, nu, w = slab.mu, slab.nu, slab.master
                mu *= b1
                mu += (1.0 - b1) * g
                nu *= b2
                nu += (1.0 - b2) * np.square(g)
                u = (mu / c1) / (np.sqrt(nu / c2) + eps)
                if slab.decay and wd:
                    u += wd * w
                w -= lr * u
                emit(path, w)
                for f in slab.files():
                    f.flush()
                    _advise(f, os.POSIX_FADV_DONTNEED)
        finally:
            abort.set()
            fetcher.join()
        self.step_on_disk = step
        self.moment_steps = t_bias
        self._write_meta()  # clean meta — clears in_progress

    def spill_bytes(self) -> int:
        return sum(3 * int(np.prod(s.shape)) * 4 for s in self.slabs.values())


# ---------------------------------------------------------------------------
# Tree <-> path-keyed dict plumbing (the slab store is flat by design:
# file names come from leaf paths)
# ---------------------------------------------------------------------------


def flatten_with_paths(tree: Any) -> dict[str, Any]:
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {jax.tree_util.keystr(path): leaf for path, leaf in flat}


def unflatten_like(tree: Any, flat: dict[str, Any]) -> Any:
    paths_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    return jax.tree_util.tree_unflatten(
        treedef, [flat[jax.tree_util.keystr(p)] for p, _ in paths_leaves]
    )


def is_replicated_upload(block_shape: tuple, leaf_shape: tuple,
                         n_devices: int, n_addressable: int) -> bool:
    """Whether an uploaded block may take the one-transfer replicated
    fast path (``jax.device_put(block, sharding)``) instead of the
    per-device block-stitch.

    Spanning all ADDRESSABLE devices is necessary but not sufficient: on
    a multi-host mesh a leaf can be replicated over this host's devices
    while still globally SHARDED across hosts — its local block is then
    a fraction of the leaf, and ``device_put(block, global_sharding)``
    would quietly lay the shard out as if it were the whole array. The
    block must also BE the full leaf."""
    return (
        n_devices > 1
        and n_devices == n_addressable
        and tuple(block_shape) == tuple(leaf_shape)
    )


class AsyncShardUploader:
    """Overlaps device uploads of updated master SHARDS with the next
    leaf's disk update: ``emit`` hands the fp32 block to ONE worker
    thread (depth-1 queue) that casts + ``device_put``s it to every
    device holding that shard while the main thread walks on. The
    bounded queue is the point: at most two block copies are ever
    resident (one queued, one uploading) — an unbounded fan-out would
    buffer the whole fp32 master tree in host RAM, the very thing the
    disk tier exists to avoid. ``result()`` joins and stitches each
    leaf's per-device blocks into a global ``jax.Array`` with the leaf's
    sharding — which is what makes the tier multi-host capable: every
    process uploads only ITS shards, and the assembled global array
    spans them all.

    ``key_devices``: slab key → (leaf path, [devices holding the
    shard]); ``leaf_shapes``/``leaf_shardings``: per leaf path."""

    def __init__(self, key_devices: dict[str, tuple[str, list]],
                 leaf_shapes: dict[str, tuple], leaf_shardings: dict[str, Any],
                 dtype):
        import queue

        self._keys = key_devices
        self._shapes = leaf_shapes
        self._sh = leaf_shardings
        self._dtype = dtype
        self._blocks: dict[str, list] = {}
        self._complete: dict[str, Any] = {}
        self._err: Optional[BaseException] = None
        self._q: "queue.Queue[Optional[tuple[str, np.ndarray]]]" = \
            queue.Queue(maxsize=1)
        self._worker = threading.Thread(target=self._drain, daemon=True)
        self._worker.start()

    def _drain(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            key, arr = item
            try:
                path, devices = self._keys[key]
                block = arr.astype(self._dtype)
                sh = self._sh[path]
                if is_replicated_upload(
                    block.shape, self._shapes[path], len(devices),
                    len(sh.addressable_devices),
                ):
                    # A fully-replicated single-shard leaf: one
                    # sharding-aware transfer (the runtime broadcasts
                    # on-device) instead of one H2D copy per device.
                    self._complete[path] = jax.device_put(block, sh)
                else:
                    self._blocks.setdefault(path, []).extend(
                        jax.device_put(block, d) for d in devices
                    )
            except BaseException as e:  # noqa: BLE001 — rethrown in result()
                self._err = e

    def emit(self, key: str, master: np.ndarray) -> None:
        # A failed upload poisons the whole walk — raise HERE, not at
        # result(): letting the walk run to completion would write a
        # clean meta at step t while the uploaded state is one step
        # behind, and every later slab write is wasted work (round-4
        # advisor finding). Aborting mid-walk leaves the in_progress
        # marker, so the next attach refuses the torn spill and reseeds
        # (masters kept where they still round to the incoming params;
        # moments zeroed) — consistent, just not free.
        if self._err is not None:
            raise self._err
        # Copy now: the memmap buffer is reused/advised-away immediately.
        # Blocks when a copy is already queued — bounded residency.
        self._q.put((key, np.asarray(master, dtype=np.float32).copy()))

    def close(self) -> None:
        """Stop the worker without raising — the failure-path companion
        to ``result()`` (a caller whose disk update threw must not leak a
        worker blocked on the queue forever)."""
        if self._worker.is_alive():
            self._q.put(None)
            self._worker.join()

    def result(self) -> dict[str, Any]:
        """Join and assemble: leaf path → global sharded array."""
        self.close()
        if self._err is not None:
            raise self._err
        out = {
            path: jax.make_array_from_single_device_arrays(
                self._shapes[path], self._sh[path], blocks
            )
            for path, blocks in self._blocks.items()
        }
        out.update(self._complete)
        return out


class WalkInFlight:
    """One ``DiskAdamW.update`` running on its own thread, paired with its
    :class:`AsyncShardUploader` — the host half of delayed-parameter-update
    overlap (``disk_update_overlap``): while this walk drains, the main
    thread returns to the train loop and the DEVICE computes the next
    step's forward/backward. ``join`` returns the uploaded compute-dtype
    leaf dict (or raises the walk's error); ``discard`` joins without
    raising, for abandoning a walk after a rollback."""

    def __init__(self, store: DiskAdamW, grads_flat: dict[str, Any],
                 lr: float, step: int, uploader: "AsyncShardUploader"):
        self.step = int(step)
        self._up = uploader
        self._err: Optional[BaseException] = None

        def run() -> None:
            try:
                store.update(grads_flat, lr, self.step, self._up.emit)
            except BaseException as e:  # noqa: BLE001 — rethrown in join()
                self._err = e
            finally:
                self._up.close()

        self._t = threading.Thread(target=run, daemon=True,
                                   name=f"disk-walk-{step}")
        self._t.start()

    def join(self) -> dict[str, Any]:
        self._t.join()
        if self._err is not None:
            raise self._err
        return self._up.result()

    def discard(self) -> None:
        self._t.join()
        self._up.close()
