"""LoRA: low-rank adaptation for parameter-efficient fine-tuning.

Absent from the reference (which delegates models entirely to external
scripts); first-class here because it is the standard fine-tuning mode a
complete training framework must offer. TPU-first formulation:

- Adapters ride the same stacked ``[L, ...]`` layout as the base kernels,
  so the training scan, sharding machinery, and checkpointing all apply
  unchanged: ``A`` is ``[L, in, r]``, ``B`` is ``[L, r, out]``, and the
  merge ``W + (alpha/r)·A@B`` is one einsum per target — negligible next
  to the forward matmuls, and XLA fuses it into the surrounding program.
- The *trainable* state is the adapter tree only: gradients, optimizer
  moments, and checkpoints are all rank-sized (a 7B base with r=16
  adapters checkpoints ~40 MB instead of ~28 GB). The frozen base params
  enter the compiled step as captured constants, sharded like any stage-3
  parameter tree.
- Sharding: ``A`` inherits the target kernel's (layers, in) axes, ``B``
  its (layers, out) axes — the rank dimension is never sharded.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

from tpu_engine.models.transformer import ModelConfig

# Kernels that can take adapters; MoE expert MLPs are 4-D ([L, E, in, out])
# and are deliberately not adaptable — restrict MoE models to attention.
DENSE_TARGETS = ("q", "k", "v", "o", "gate", "up", "down")
ATTN_TARGETS = ("q", "k", "v", "o")


def target_shapes(cfg: ModelConfig) -> dict[str, tuple[int, int, int]]:
    """[L, in, out] shape of each adaptable kernel."""
    L, D, F = cfg.n_layers, cfg.d_model, cfg.d_ff
    H, KV, HD = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    shapes = {
        "q": (L, D, H * HD),
        "k": (L, D, KV * HD),
        "v": (L, D, KV * HD),
        "o": (L, H * HD, D),
    }
    if cfg.arch == "gpt2":
        shapes.update({"fc": (L, D, F), "proj": (L, F, D)})
    elif not cfg.is_moe:
        shapes.update({"gate": (L, D, F), "up": (L, D, F), "down": (L, F, D)})
    return shapes


def validate_targets(cfg: ModelConfig, targets: Sequence[str]) -> tuple[str, ...]:
    allowed = target_shapes(cfg)
    bad = [t for t in targets if t not in allowed]
    if bad:
        raise ValueError(
            f"invalid lora_targets {bad} for model {cfg.name!r}; "
            f"valid: {sorted(allowed)}"
            + (" (MoE expert MLPs cannot take adapters)" if cfg.is_moe else "")
        )
    if not targets:
        raise ValueError("lora_targets must not be empty")
    return tuple(targets)


def init_lora_params(
    rng: jax.Array,
    cfg: ModelConfig,
    rank: int,
    targets: Sequence[str],
    dtype=jnp.float32,
) -> dict[str, Any]:
    """A ~ N(0, 1/r) (per the LoRA paper), B = 0 — the adapted model starts
    exactly equal to the base model."""
    shapes = target_shapes(cfg)
    keys = jax.random.split(rng, len(targets))
    layers: dict[str, Any] = {}
    for key, t in zip(keys, targets):
        L, i, o = shapes[t]
        layers[t] = {
            "A": (jax.random.normal(key, (L, i, rank), dtype) / (rank ** 0.5)),
            "B": jnp.zeros((L, rank, o), dtype),
        }
    return {"layers": layers}


def lora_logical_axes(
    model_logical: dict[str, Any], targets: Sequence[str]
) -> dict[str, Any]:
    """Adapter logical-axis tree: A takes the target's (layers, in) axes,
    B its (layers, out) axes; the rank axis is never sharded."""
    layers: dict[str, Any] = {}
    for t in targets:
        lyr, in_ax, out_ax = model_logical["layers"][t]["kernel"]
        layers[t] = {"A": (lyr, in_ax, None), "B": (lyr, None, out_ax)}
    return {"layers": layers}


def merge_lora(
    base_params: dict[str, Any],
    lora_params: dict[str, Any],
    alpha: float,
    rank: int,
) -> dict[str, Any]:
    """Base params with ``W_t + (alpha/r)·A_t@B_t`` for each adapted target.

    Non-destructive: returns a new tree sharing every unadapted leaf.
    """
    scale = alpha / rank
    layers = dict(base_params["layers"])
    for t, ab in lora_params["layers"].items():
        w = layers[t]["kernel"]
        delta = jnp.einsum(
            "lir,lro->lio", ab["A"].astype(w.dtype), ab["B"].astype(w.dtype)
        )
        layers[t] = {"kernel": w + scale * delta}
    return {**base_params, "layers": layers}


def lora_param_count(cfg: ModelConfig, rank: int, targets: Sequence[str]) -> int:
    shapes = target_shapes(cfg)
    return sum(shapes[t][0] * rank * (shapes[t][1] + shapes[t][2]) for t in targets)
