"""Profiling & tracing: per-step wall-clock breakdown, MFU accounting, and
on-demand ``jax.profiler`` trace capture.

The reference delegates all profiling to DeepSpeed config flags
(``wall_clock_breakdown``, ``dump_state`` — ``ai_engine/deepspeed_launcher.py:79-80,
129-130``) and carries throughput as a passive, never-analysed field
(``ai_engine/loss_monitor.py:50``). Here the engine owns the numbers
(SURVEY.md §5 tracing plan):

- :class:`StepProfiler` — the in-loop wall-clock breakdown: data-wait,
  device-step, host-sync and monitor overhead per step, with rolling
  mean/p50/p95 summaries (bounded window — no unbounded growth);
- :func:`mfu` / :func:`peak_flops_per_chip` — tokens/sec/chip → model-FLOPs
  utilisation against the chip's bf16 peak (the BASELINE.json north-star
  metric);
- :class:`TraceSession` — start/stop ``jax.profiler`` traces (XPlane/
  TensorBoard format) with an optional auto-stop duration, safe to drive
  from the HTTP control plane.
"""

from __future__ import annotations

import statistics
import threading
import time
from collections import deque
from typing import Any, Optional

import jax

# Peak bf16 FLOP/s per chip by device kind (public spec sheets).
PEAK_FLOPS_BF16 = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
    "trillium": 918e12,
}


def peak_flops_per_chip(device: Optional[jax.Device] = None) -> Optional[float]:
    """Peak bf16 FLOP/s for ``device`` (default: first visible), or None if
    the chip generation isn't recognised (e.g. CPU test meshes)."""
    if device is None:
        device = jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, flops in PEAK_FLOPS_BF16.items():
        if key in kind:
            return flops
    return None


def mfu(
    flops_per_token: float,
    tokens_per_sec_per_chip: float,
    device: Optional[jax.Device] = None,
) -> Optional[float]:
    """Model-FLOPs utilisation in [0, 1], or None off known TPU chips.

    Uses *model* FLOPs (6N + attention), not hardware FLOPs: remat recompute
    is deliberately not credited, matching the standard MFU definition.

    Accounting basis under int8 quantized training (``quant_training=
    'int8'``, tpu_engine/quant_train.py): the numerator stays MODEL FLOPs
    and the denominator stays the chip's BF16 peak — quantization changes
    neither the model nor this definition. What it changes is the
    ACHIEVABLE roofline: int8×int8→int32 MXU throughput is up to 2× the
    bf16 rate, so a quantized run can legitimately report >100%
    "bf16-MFU" on matmul-bound configs. Compare quantized runs by
    step time / tokens-per-sec, and read their MFU as a fraction of the
    bf16 roofline, not of the hardware's int8 ceiling.
    """
    peak = peak_flops_per_chip(device)
    if peak is None or tokens_per_sec_per_chip <= 0:
        return None
    return flops_per_token * tokens_per_sec_per_chip / peak


def pipeline_tick_account(
    schedule: str, n_stages: int, microbatches: int
) -> Optional[dict[str, Any]]:
    """Analytic tick / busy-lane account for a pipelined run, or None off
    the pipelined path (``n_stages <= 1``).

    Thin re-export of ``tpu_engine.parallel.pipeline_zb.schedule_account``
    so profiler consumers (supervisor telemetry, bench.py) don't import the
    schedule module directly. ``busy_fraction`` is useful lane F-units over
    total lane F-units — see the schedule module for the cost model.
    """
    if n_stages <= 1:
        return None
    from tpu_engine.parallel.pipeline_zb import schedule_account

    return schedule_account(schedule, n_stages, microbatches)


class StepProfiler:
    """Rolling wall-clock breakdown of the train loop's phases.

    Phases (per step): ``data`` (batch fetch / host pipeline), ``dispatch``
    (trace-cache hit + async enqueue of the jit step), ``device`` (device
    execution + metric transfer — JAX dispatch is async, so the wall-clock
    cost of the step lands in the blocking device→host read), ``other``
    (monitor, checkpoint bookkeeping). All in seconds.
    """

    PHASES = ("data", "dispatch", "device", "other")

    def __init__(self, window: int = 100, tokens_per_step: Optional[int] = None,
                 flops_per_token: Optional[float] = None, n_devices: int = 1,
                 pipeline_account: Optional[dict[str, Any]] = None):
        self.window = window
        self.tokens_per_step = tokens_per_step
        self.flops_per_token = flops_per_token
        self.n_devices = max(n_devices, 1)
        # Analytic schedule account for pipelined runs (from
        # pipeline_tick_account): enables bubble-adjusted MFU — raw MFU
        # divided by the schedule's busy-lane fraction, i.e. utilisation of
        # the lanes the schedule actually keeps busy. Without it RESULTS.md
        # under-reports pipelined MFU: the bubble is a schedule property,
        # not a kernel-efficiency loss.
        self.pipeline_account = pipeline_account
        self._phases: dict[str, deque[float]] = {p: deque(maxlen=window) for p in self.PHASES}
        self._totals: deque[float] = deque(maxlen=window)
        self._steps_seen = 0
        self._lock = threading.Lock()
        self._t_phase: Optional[float] = None
        self._t_step_start: Optional[float] = None
        self._current: dict[str, float] = {}

    # -- recording ----------------------------------------------------------

    def begin_step(self) -> None:
        now = time.perf_counter()
        self._t_step_start = now
        self._t_phase = now
        self._current = {}

    def mark(self, phase: str) -> None:
        """Close the currently-running phase as ``phase``."""
        now = time.perf_counter()
        if self._t_phase is not None:
            self._current[phase] = self._current.get(phase, 0.0) + (now - self._t_phase)
        self._t_phase = now

    def end_step(self) -> float:
        """Close the step; un-attributed time lands in ``other``. Returns
        total step wall-clock seconds."""
        now = time.perf_counter()
        total = (now - self._t_step_start) if self._t_step_start is not None else 0.0
        attributed = sum(self._current.values())
        self._current["other"] = self._current.get("other", 0.0) + max(total - attributed, 0.0)
        with self._lock:
            for p in self.PHASES:
                self._phases[p].append(self._current.get(p, 0.0))
            self._totals.append(total)
            self._steps_seen += 1
        self._t_phase = None
        self._t_step_start = None
        return total

    def last_step_phases(self) -> dict[str, float]:
        """Phase seconds of the most recently ended step (empty before any).
        Feeds the derived duty-cycle telemetry source."""
        return dict(self._current)

    # -- views --------------------------------------------------------------

    @staticmethod
    def _stats(xs: list[float]) -> dict[str, float]:
        if not xs:
            return {"mean_ms": 0.0, "p50_ms": 0.0, "p95_ms": 0.0}
        xs_sorted = sorted(xs)
        p95 = xs_sorted[min(int(0.95 * (len(xs_sorted) - 1)), len(xs_sorted) - 1)]
        return {
            "mean_ms": statistics.fmean(xs) * 1e3,
            "p50_ms": statistics.median(xs_sorted) * 1e3,
            "p95_ms": p95 * 1e3,
        }

    def summary(self) -> dict[str, Any]:
        with self._lock:
            totals = list(self._totals)
            phases = {p: list(v) for p, v in self._phases.items()}
            steps_seen = self._steps_seen
        out: dict[str, Any] = {
            "steps_seen": steps_seen,
            "window": len(totals),
            "total": self._stats(totals),
            "phases": {p: self._stats(v) for p, v in phases.items()},
        }
        mean_total = statistics.fmean(totals) if totals else 0.0
        if totals and mean_total > 0:
            for p, v in phases.items():
                out["phases"][p]["fraction"] = round(statistics.fmean(v) / mean_total, 4)
        if self.tokens_per_step and mean_total > 0:
            tps = self.tokens_per_step / mean_total
            out["tokens_per_sec"] = round(tps, 1)
            out["tokens_per_sec_per_chip"] = round(tps / self.n_devices, 1)
            if self.flops_per_token:
                u = mfu(self.flops_per_token, tps / self.n_devices)
                out["mfu"] = round(u, 4) if u is not None else None
        if self.pipeline_account is not None:
            acct = self.pipeline_account
            busy = acct.get("busy_fraction", 1.0) or 1.0
            out["pipeline"] = {
                "schedule": acct.get("schedule"),
                "n_stages": acct.get("n_stages"),
                "microbatches": acct.get("microbatches"),
                "ticks": acct.get("ticks"),
                "busy_fraction": round(busy, 4),
                "bubble_fraction": round(acct.get("bubble_fraction", 0.0), 4),
            }
            if out.get("mfu") is not None:
                out["mfu_bubble_adjusted"] = round(out["mfu"] / busy, 4)
        return out


class TraceActiveError(RuntimeError):
    """Raised on double-start; carries the active capture's coordinates so
    callers (the ``/api/v1/profile/start`` route, the anomaly auto-trace
    hook) can report a structured conflict instead of a bare string."""

    def __init__(self, log_dir: str, started_at: float):
        self.log_dir = log_dir
        self.started_at = started_at
        super().__init__(f"trace already active (dir={log_dir})")

    def describe(self) -> dict[str, Any]:
        return {
            "log_dir": self.log_dir,
            "started_at": self.started_at,
            "elapsed_s": round(time.time() - self.started_at, 3),
        }


class TraceSession:
    """On-demand ``jax.profiler`` trace capture (one at a time per process).

    Produces XPlane traces viewable in TensorBoard / Perfetto. Drive from
    code or the ``/api/v1/profile`` routes.
    """

    def __init__(self):
        # RLock: start() reports via status() while still holding the lock.
        self._lock = threading.RLock()
        self._active_dir: Optional[str] = None
        self._started_at: Optional[float] = None
        self._auto_timer: Optional[threading.Timer] = None

    @property
    def active(self) -> bool:
        return self._active_dir is not None

    def start(self, log_dir: str, duration_s: Optional[float] = None) -> dict[str, Any]:
        with self._lock:
            if self._active_dir is not None:
                raise TraceActiveError(
                    self._active_dir, self._started_at or time.time()
                )
            jax.profiler.start_trace(log_dir)
            self._active_dir = log_dir
            self._started_at = time.time()
            if duration_s is not None and duration_s > 0:
                self._auto_timer = threading.Timer(duration_s, self._auto_stop)
                self._auto_timer.daemon = True
                self._auto_timer.start()
            return self.status()

    def _auto_stop(self) -> None:
        try:
            self.stop()
        except Exception:
            pass

    def stop(self) -> dict[str, Any]:
        with self._lock:
            if self._active_dir is None:
                raise RuntimeError("no active trace")
            if self._auto_timer is not None:
                self._auto_timer.cancel()
                self._auto_timer = None
            jax.profiler.stop_trace()
            info = {
                "log_dir": self._active_dir,
                "duration_s": round(time.time() - (self._started_at or time.time()), 3),
                "active": False,
            }
            self._active_dir = None
            self._started_at = None
            return info

    def status(self) -> dict[str, Any]:
        with self._lock:
            if self._active_dir is None:
                return {"active": False}
            return {
                "active": True,
                "log_dir": self._active_dir,
                "elapsed_s": round(time.time() - (self._started_at or time.time()), 3),
            }
