"""Placement planner: auto-layout search at admission, one shared cost model.

Users hand-pick ``(data, fsdp, model, pipe, schedule, quant, comm)`` per
submission even though every cost-model ingredient already exists in-tree:
the per-layout memory plane (:func:`tpu_engine.hbm_estimate.estimate_job_hbm`),
the analytic pipeline lane account
(:func:`tpu_engine.parallel.pipeline_zb.schedule_account`) and the
ZeRO++-style per-leaf byte model
(:func:`tpu_engine.comm_compress.expected_volume_factors`). The planner
composes them into one search (the Placement-Semantics recipe,
arXiv:2601.02311; the comm-volume accounting follows ZeRO++,
arXiv:2306.10209):

1. **enumerate** — every factorization of the gang across the
   ``data × fsdp × pipe × model`` mesh axes, crossed with sharding stage,
   pipeline schedule and (opt-in) quant / comm-compression toggles;
2. **prune** — each candidate is constructed as a real
   :class:`~tpu_engine.sharding.TPUTrainConfig` (so the config interaction
   matrix fires) and then pushed through a mirror of
   ``build_train_program``'s build-time checks — the planner can never
   emit a layout the builder would reject;
3. **filter** — per-device HBM via ``estimate_job_hbm`` against live
   fleet headroom minus the scheduler's per-device reservation ledger;
4. **rank** — predicted step time = max(roofline compute ÷
   ``schedule_account`` busy fraction, streamed fsdp/data collectives)
   + the exposed interconnect term (tensor-parallel all-reduces, pipe
   boundary permutes, DCN hops) from the comm byte model over
   intra-slice (ICI) vs cross-slice (DCN) bandwidth.

The prediction is a *ranking* model: absolute seconds assume a nominal
TPU roofline and are meaningless on the CPU test backend, but every term
that differs between layouts (bubble fraction, gather/reduce bytes,
per-shard batch) is modelled, so the order survives — validated by
``benchmarks/placement_plan.py`` (measured CPU-mesh sweep + llama-7b AOT).

Wiring: ``FleetScheduler.submit(..., mesh="auto")`` admits the
predicted-fastest feasible plan, ``TPULauncher`` dry runs and
``POST /api/v1/scheduler/plan`` return the ranked table, and
``tpu_engine_placement_*`` Prometheus families expose the counters.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Callable, Iterable, Optional, Sequence

import jax.numpy as jnp
from pydantic import BaseModel, ConfigDict, Field

from tpu_engine.hbm_estimate import HBMEstimate, estimate_job_hbm, gang_size
from tpu_engine.models import transformer as tfm
from tpu_engine.parallel.pipeline_zb import schedule_account
from tpu_engine.sharding import (
    OffloadDevice,
    Precision,
    ShardingStage,
    TPUTrainConfig,
    dtype_of,
    resolve_pipeline_schedule,
)

log = logging.getLogger(__name__)

# Nominal per-chip roofline / link constants. Absolute values only scale
# the prediction; RANKING depends on the ratios, which hold across TPU
# generations (ICI is ~1 order of magnitude faster than DCN). The compute
# fallback is the v5e bf16 peak so predictions are well-defined on the
# CPU test backend, where profiler.peak_flops_per_chip returns None.
NOMINAL_PEAK_FLOPS = 197e12  # v5e bf16 MXU peak (profiler.PEAK_FLOPS_BF16)
NOMINAL_ICI_BYTES_S = 4.5e10  # per-chip one-way intra-slice bandwidth
NOMINAL_DCN_BYTES_S = 6.25e9  # per-host cross-slice (data-center) bandwidth
ASSUMED_MFU = 0.45  # roofline derate; cancels in ranking


class PlacementPlan(BaseModel):
    """One validated candidate layout with its cost-model verdict."""

    model_config = ConfigDict(arbitrary_types_allowed=True)

    mesh: dict[str, int]
    gang: int
    sharding_stage: int
    pipeline_schedule: str  # resolved concrete schedule ("gpipe"/"1f1b"/"zb")
    micro_batch_size: int
    gradient_accumulation_steps: int
    quant_training: str = "none"
    comm_compress: bool = False
    predicted_compute_s: float
    predicted_bubble_fraction: float
    predicted_comm_s: float  # total collective seconds (streamed + exposed)
    predicted_exposed_comm_s: float = 0.0  # critical-path share of the above
    predicted_step_time_s: float
    # Compile-cache verdict (None/0 when the planner has no index): is this
    # exact layout warm in the persistent XLA cache, and what cold-compile
    # cost does admission pay when it is not (per-layout EMA of measured
    # cold compiles — see tpu_engine/compile_index.py).
    compile_warm: Optional[bool] = None
    expected_compile_s: float = 0.0
    # Reshard verdict (0/None without a resume topology): one-time cost of
    # remapping the saved checkpoint onto THIS plan's factorization
    # (tpu_engine/reshard.py cost model) — 0 for a same-topology resume.
    predicted_reshard_s: float = 0.0
    reshard_same_topology: Optional[bool] = None
    # Mean relative throughput the cost model assumed for this gang (1.0 =
    # every chip at nominal speed; < 1 when the heterogeneity plane reports
    # degraded hosts — see tpu_engine/hetero.py). Observability only: the
    # compute term was already divided by it.
    assumed_rel_throughput: float = 1.0
    hbm_estimate: Optional[HBMEstimate] = None
    feasible: bool = True
    skip_reason: Optional[str] = None
    # The fully-validated config this plan runs as — excluded from dumps
    # (the API table stays compact); the scheduler admits exactly this.
    config: Optional[TPUTrainConfig] = Field(default=None, exclude=True, repr=False)

    @property
    def label(self) -> str:
        axes = "x".join(
            f"{k}{v}" for k, v in self.mesh.items()
            if v > 1 and k != "dcn_data"
        ) or "data1"
        tags = [self.pipeline_schedule] if self.mesh.get("pipe", 1) > 1 else []
        if self.quant_training != "none":
            tags.append(self.quant_training)
        if self.comm_compress:
            tags.append("commq")
        return "·".join([axes, f"s{self.sharding_stage}", *tags])


class PlannerResult(BaseModel):
    """Ranked outcome of one planning pass."""

    plans: list[PlacementPlan]  # feasible, predicted-fastest first
    infeasible: list[PlacementPlan]  # HBM/headroom rejected (with reasons)
    pruned: list[dict[str, str]]  # invalid layouts: {"layout", "reason"}
    evaluated: int
    skip_reason: Optional[str] = None  # e.g. "no_estimate:<model>"
    search_s: float = 0.0  # wall seconds the enumerate+rank pass took

    @property
    def best(self) -> Optional[PlacementPlan]:
        return self.plans[0] if self.plans else None

    def table(self, top_k: int = 10) -> list[dict[str, Any]]:
        """Compact ranked rows for the API / launcher plan."""
        rows = []
        for rank, p in enumerate(self.plans[:top_k], start=1):
            rows.append({
                "rank": rank,
                "layout": p.label,
                "mesh": p.mesh,
                "gang": p.gang,
                "sharding_stage": p.sharding_stage,
                "pipeline_schedule": p.pipeline_schedule,
                "micro_batch_size": p.micro_batch_size,
                "gradient_accumulation_steps": p.gradient_accumulation_steps,
                "predicted_step_time_s": round(p.predicted_step_time_s, 6),
                "predicted_bubble_fraction": round(p.predicted_bubble_fraction, 4),
                "predicted_comm_s": round(p.predicted_comm_s, 6),
                "compile_warm": p.compile_warm,
                "expected_compile_s": round(p.expected_compile_s, 3),
                "predicted_reshard_s": round(p.predicted_reshard_s, 3),
                "hbm_gib_per_device": (
                    round(p.hbm_estimate.device_total_gib, 3)
                    if p.hbm_estimate else None
                ),
            })
        return rows


def _divisors(n: int) -> list[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def _mirror_build_checks(cfg: TPUTrainConfig, model_cfg: tfm.ModelConfig) -> None:
    """Re-raise (as ValueError) every ``build_train_program`` build-time
    interaction the config validators do not already cover, so an
    enumerated plan can never fail at job construction. Mirrors
    ``tpu_engine/train.py`` — the checks there are the source of truth;
    this copy exists so the planner prunes instead of admitting a dud."""
    m = cfg.mesh
    pipe, model_ax, seq_ax = m.pipe, m.model, m.sequence
    schedule = resolve_pipeline_schedule(cfg)
    if pipe > 1 and model_cfg.n_layers % pipe != 0:
        raise ValueError(
            f"n_layers={model_cfg.n_layers} not divisible by pipe={pipe}"
        )
    moe_impl = cfg.moe_impl or model_cfg.moe_impl
    if cfg.moe_impl is not None and not model_cfg.is_moe:
        raise ValueError(f"moe_impl={cfg.moe_impl!r} on dense model")
    if model_cfg.is_moe and moe_impl == "ragged" and model_ax > 1:
        raise ValueError("moe_impl='ragged' cannot shard the expert dim")
    if (
        cfg.quant_training == "int8"
        and model_cfg.is_moe
        and moe_impl == "ragged"
        and "moe" in cfg.quant_train_targets
    ):
        raise ValueError("quant int8 cannot quantize ragged MoE")
    window = (
        cfg.sliding_window
        if cfg.sliding_window is not None
        else model_cfg.sliding_window
    )
    if window and cfg.attention_impl in ("ring", "ulysses"):
        raise ValueError("sliding_window with context-parallel attention")
    if cfg.attention_impl == "ulysses":
        local_heads = model_cfg.n_heads // model_ax
        if local_heads % seq_ax != 0:
            raise ValueError(
                f"ulysses: {local_heads} local heads not divisible by "
                f"sequence axis {seq_ax}"
            )
    if model_ax > 1 and (
        model_cfg.n_heads % model_ax
        or model_cfg.n_kv_heads % model_ax
        or model_cfg.d_ff % model_ax
        or model_cfg.vocab_size % model_ax
    ):
        raise ValueError(
            f"model axis {model_ax} does not divide heads/kv/ffn/vocab"
        )
    if cfg.loss_chunk_size:
        if cfg.seq_len % cfg.loss_chunk_size:
            raise ValueError("loss_chunk_size must divide seq_len")
        if schedule in ("1f1b", "zb") and pipe > 1:
            raise ValueError(f"loss_chunk_size with schedule {schedule!r}")
    use_lora = cfg.lora_rank is not None
    if use_lora and pipe > 1:
        raise ValueError("LoRA with pipeline parallelism")
    offload_params = cfg.param_offload == OffloadDevice.HOST
    if offload_params and (use_lora or pipe > 1):
        raise ValueError("param_offload=host with LoRA/pipeline")
    if cfg.optimizer_offload == OffloadDevice.DISK and pipe > 1:
        raise ValueError("optimizer_offload='disk' with pipeline")
    reduced_comm = (
        cfg.grad_allreduce_dtype is not None
        and cfg.grad_allreduce_dtype != Precision.FP32
    )
    if reduced_comm and pipe > 1 and schedule in ("1f1b", "zb"):
        raise ValueError(f"grad_allreduce_dtype with schedule {schedule!r}")
    if reduced_comm and offload_params:
        raise ValueError("grad_allreduce_dtype with param_offload=host")


class PlacementPlanner:
    """Enumerate → prune → HBM-filter → rank layouts for one submission.

    Thread-safe counters only; the search itself is pure. One instance
    lives on the :class:`~tpu_engine.scheduler.FleetScheduler` so admission,
    grow-back, the launcher plan and the HTTP endpoint share a single
    counter plane (``tpu_engine_placement_*``).
    """

    def __init__(
        self,
        estimate_fn: Callable[..., Optional[HBMEstimate]] = estimate_job_hbm,
        peak_flops: Optional[float] = None,
        ici_bytes_s: float = NOMINAL_ICI_BYTES_S,
        dcn_bytes_s: float = NOMINAL_DCN_BYTES_S,
        consider_quant: bool = False,
        consider_comm_compress: bool = False,
        stages: tuple[ShardingStage, ...] = (
            ShardingStage.FULL_PARTITIONING,
            ShardingStage.GRADIENT_PARTITIONING,
        ),
        max_gang_enumeration: int = 16,
        hbm_margin_frac: float = 0.35,
        compile_index: Optional[Any] = None,
        prefer_warm_max_slowdown_pct: float = 5.0,
        throughput_fn: Optional[Callable[[], Sequence[float]]] = None,
        calibration_path: Optional[str] = None,
        calibration_alpha: float = 0.3,
    ):
        if peak_flops is None:
            try:
                from tpu_engine.profiler import peak_flops_per_chip

                peak_flops = peak_flops_per_chip()
            except Exception:
                peak_flops = None
        self.peak_flops = peak_flops or NOMINAL_PEAK_FLOPS
        self.estimate_fn = estimate_fn
        self.ici_bytes_s = ici_bytes_s
        self.dcn_bytes_s = dcn_bytes_s
        # Quant / comm-compression variants are opt-in: both are measured
        # wins only on real MXU / real DCN (benchmarks/RESULTS.md — int8
        # matmul is 0.71x on CPU), so enumerating them by default would
        # mispredict every CPU-backend ranking.
        self.consider_quant = consider_quant
        self.consider_comm_compress = consider_comm_compress
        self.stages = stages
        self.max_gang_enumeration = max_gang_enumeration
        # estimate_job_hbm is analytic: it cannot see XLA's scheduling
        # temporaries, so a plan near the top of free HBM still OOMs at
        # compile. Measured on llama-7b via placement_plan.py --aot: flat
        # layouts land ~8% over the estimate (15.18 est -> 16.38 real),
        # pipelined ones 30-40% over (13.79 -> 17.82; 13.70 -> 18.99) —
        # the in-flight microbatch stash is the hardest term to project.
        # 35% covers the measured band; the AOT plane is the backstop for
        # anything beyond it. The gate charges every estimate this
        # fraction on top before comparing to headroom.
        self.hbm_margin_frac = hbm_margin_frac
        # Compile-cache awareness: with an index attached, every candidate
        # is annotated warm/cold and the ranking tie-breaks toward warm
        # layouts — a warm plan may be preferred over a cold one predicted
        # up to ``prefer_warm_max_slowdown_pct`` percent faster (the cold
        # plan's one-time compile usually dwarfs that step-time edge).
        self.compile_index = compile_index
        self.prefer_warm_max_slowdown_pct = prefer_warm_max_slowdown_pct
        # Reshard awareness: when ``plan(saved_topology=...)`` names the
        # factorization a resume candidate's checkpoints were saved under,
        # a same-topology plan within this band of the fastest feasible
        # one outranks every topology-changing plan — the remap is a
        # one-time cost, so only a real step-time edge justifies it.
        self.prefer_same_topology_max_slowdown_pct = prefer_warm_max_slowdown_pct
        # Heterogeneity input: a callable returning per-device relative
        # throughputs (1.0 = nominal). The compute term is divided by the
        # gang's mean, so a 25%-degraded host raises the predicted step
        # time of any plan forced to gate on it. Default None keeps every
        # existing prediction byte-identical.
        self.throughput_fn = throughput_fn

        self._lock = threading.Lock()
        self.plans_evaluated_total = 0
        self.plans_pruned_total = 0
        self.plans_hbm_rejected_total = 0
        self.plans_chosen_total = 0
        self.no_estimate_refusals_total = 0
        self.warm_tiebreaks_total = 0
        self.topology_rejected_total = 0
        self.reshard_tiebreaks_total = 0
        self.prune_reasons: dict[str, int] = {}
        self.last_feasible = 0
        self.last_chosen_predicted_s: Optional[float] = None
        self._observations: list[tuple[float, float]] = []  # (predicted, observed)

        # Predicted-vs-observed calibration, persisted alongside the
        # compile-index sidecar so restarts don't forget what admission
        # learned (same atomic tmp+rename discipline as compile_index.py).
        self.calibration_alpha = calibration_alpha
        self.calibration_persist_errors_total = 0
        self.calibration_load_errors_total = 0
        self._calibration_path: Optional[str] = None
        self._calib_ema_rel_error: Optional[float] = None
        self._calib_observations_total = 0
        self._calib_last: Optional[tuple[float, float]] = None
        if calibration_path is not None:
            self.attach_calibration(calibration_path)

    # -- enumeration ---------------------------------------------------------

    def enumerate(
        self,
        config: TPUTrainConfig,
        gang: int,
        *,
        consider_quant: Optional[bool] = None,
        consider_comm_compress: Optional[bool] = None,
        stages: Optional[Iterable[ShardingStage]] = None,
    ) -> tuple[list[PlacementPlan], list[dict[str, str]]]:
        """All valid layouts of ``config`` on exactly ``gang`` devices.

        Returns ``(plans, pruned)``: every plan carries a fully-validated
        ``TPUTrainConfig`` (the interaction matrix and the mirrored build
        checks both passed); ``pruned`` records the rejected layouts with
        their reason — known-invalid combos (1f1b × quant_training,
        comm-compression × pipe, ...) land there, never in ``plans``.

        The search keeps tokens/step constant: the submitted global batch
        (``micro × accum × data × fsdp`` at the configured mesh) is
        re-split per layout — per-shard batch must divide evenly, micro
        shrinks to the largest divisor ≤ the requested micro, and the
        remainder becomes gradient accumulation (the pipeline stream).
        """
        model_cfg = tfm.MODEL_CONFIGS.get(config.model_name)
        if model_cfg is None:
            raise ValueError(f"no_estimate:{config.model_name}")
        cq = self.consider_quant if consider_quant is None else consider_quant
        cc = (
            self.consider_comm_compress
            if consider_comm_compress is None
            else consider_comm_compress
        )
        stage_opts = tuple(stages) if stages is not None else self.stages

        base = config.model_dump()
        base_mesh = config.mesh
        # Requested global batch at the configured mesh (data=-1 resolved
        # against the same gang).
        base_data, base_fsdp, _, _, _ = base_mesh.resolved_shape(
            gang_size(config, gang)
        )
        global_batch = (
            config.micro_batch_size
            * config.gradient_accumulation_steps
            * base_data
            * base_fsdp
        )
        seq_ax = base_mesh.sequence  # held fixed: same factor in every plan
        dcn = base_mesh.dcn_data

        plans: list[PlacementPlan] = []
        pruned: list[dict[str, str]] = []

        def _prune(layout: str, reason: str) -> None:
            pruned.append({"layout": layout, "reason": reason})

        if gang % seq_ax:
            _prune(f"gang{gang}", f"gang not divisible by sequence axis {seq_ax}")
            self._account(evaluated=1, pruned_n=1, reasons=[r["reason"] for r in pruned])
            return plans, pruned

        spatial = gang // seq_ax
        n_evaluated = 0
        for model_ax in _divisors(spatial):
            for pipe in _divisors(spatial // model_ax):
                for fsdp in _divisors(spatial // (model_ax * pipe)):
                    data = spatial // (model_ax * pipe * fsdp)
                    name = f"d{data}·f{fsdp}·p{pipe}·t{model_ax}"
                    if data % dcn:
                        n_evaluated += 1
                        _prune(name, f"data axis {data} not divisible by dcn_data {dcn}")
                        continue
                    dp = data * fsdp
                    if global_batch % dp:
                        n_evaluated += 1
                        _prune(name, f"global batch {global_batch} not divisible by dp {dp}")
                        continue
                    per_shard = global_batch // dp
                    micro = max(
                        d for d in _divisors(per_shard)
                        if d <= config.micro_batch_size
                    )
                    accum = per_shard // micro
                    schedules = (
                        ("gpipe", "1f1b", "zb") if pipe > 1 else ("auto",)
                    )
                    stage_list = (
                        stage_opts if fsdp > 1
                        else (ShardingStage.FULL_PARTITIONING,)
                    )
                    quant_opts = ("none", "int8") if cq else ("none",)
                    comm_opts = (False, True) if cc else (False,)
                    for stage in stage_list:
                        for schedule in schedules:
                            for quant in quant_opts:
                                for comm in comm_opts:
                                    n_evaluated += 1
                                    tag = name + f"·s{int(stage)}·{schedule}" + (
                                        f"·{quant}" if quant != "none" else ""
                                    ) + ("·commq" if comm else "")
                                    cand = dict(base)
                                    cand["mesh"] = {
                                        "data": data, "fsdp": fsdp,
                                        "pipe": pipe, "sequence": seq_ax,
                                        "model": model_ax, "dcn_data": dcn,
                                    }
                                    cand["sharding_stage"] = stage
                                    cand["pipeline_schedule"] = schedule
                                    cand["micro_batch_size"] = micro
                                    cand["gradient_accumulation_steps"] = accum
                                    cand["quant_training"] = quant
                                    if comm:
                                        cand["comm_quant_weights"] = True
                                        cand["comm_quant_grads"] = True
                                    try:
                                        # A fresh construction — never
                                        # model_copy, which skips the
                                        # validator interaction matrix.
                                        cfg = TPUTrainConfig(**cand)
                                        _mirror_build_checks(cfg, model_cfg)
                                    except ValueError as e:
                                        msg = str(e)
                                        errors = getattr(e, "errors", None)
                                        if callable(errors):
                                            try:  # pydantic: the real message
                                                msg = errors()[0].get("msg", msg)
                                            except Exception:
                                                pass
                                        _prune(tag, msg.splitlines()[0][:160])
                                        continue
                                    plans.append(self._predict(cfg, model_cfg, gang))
        self._account(
            evaluated=n_evaluated,
            pruned_n=len(pruned),
            reasons=[r["reason"] for r in pruned],
        )
        return plans, pruned

    # -- cost model ----------------------------------------------------------

    def _gang_rel_throughput(self, gang: int) -> float:
        """Mean relative throughput of the ``gang`` fastest known devices.

        The planner places on the best available chips, so the cost model
        charges the mean of the top-``gang`` per-device estimates; unknown
        devices (fewer estimates than gang) count as nominal 1.0. Clamped
        to (0, 1]: chips never beat nominal, and a dead estimate must not
        zero the divisor. Any failure in the callable degrades to 1.0 —
        heterogeneity awareness must never block prediction.
        """
        if self.throughput_fn is None:
            return 1.0
        try:
            rates = [float(r) for r in self.throughput_fn()]
        except Exception:
            log.debug("throughput_fn consult failed", exc_info=True)
            return 1.0
        if not rates:
            return 1.0
        top = sorted(rates, reverse=True)[:gang]
        top += [1.0] * max(gang - len(top), 0)
        mean = sum(top) / len(top)
        return min(max(mean, 1e-3), 1.0)

    def _predict(
        self, cfg: TPUTrainConfig, model_cfg: tfm.ModelConfig, gang: int
    ) -> PlacementPlan:
        """Predicted step time for one validated candidate.

        compute: roofline seconds for the step's global tokens, divided by
        the schedule's busy fraction (bubble lanes burn chip time);
        comm: analytic bytes per device per step over ICI/DCN —
        stage-3 weight all-gathers per microbatch (÷ the qwZ wire factor
        when compressed), gradient reduce-scatter/all-reduce over
        fsdp/data (the data plane rides DCN when dcn_data > 1, ÷ the qgZ
        factor when compressed), per-layer tensor-parallel activation
        all-reduces, and pipeline boundary permutes.

        The fsdp/data collectives are *streamed*: XLA's latency-hiding
        scheduler overlaps weight gathers and gradient reduces with the
        per-layer matmuls (that is what makes FSDP work at all), so they
        are charged as ``max(compute, streamed_comm)`` rather than added.
        Tensor-parallel activation all-reduces sit between sequential
        matmuls, pipeline boundary permutes between stages, and DCN hops
        behind a long latency — those stay on the critical path. Charging
        everything serially over-ranks deep-pipe layouts (their comm is
        boundary-only) against fsdp layouts whose gathers are actually
        free; the ``--aot`` plane caught exactly that inversion.
        """
        m = cfg.mesh
        # Resolve elastic axes (data=-1) against the gang — the raw mesh
        # would give a negative token count.
        data, fsdp, pipe, seq_axis, model_ax = m.resolved_shape(gang)
        seq = cfg.seq_len
        micro = cfg.micro_batch_size
        accum = cfg.gradient_accumulation_steps
        schedule = resolve_pipeline_schedule(cfg)

        tokens = data * fsdp * micro * accum * seq
        flops = tfm.train_flops_per_token(model_cfg, seq) * tokens
        compute_s = flops / (gang * self.peak_flops * ASSUMED_MFU)
        acct = schedule_account(schedule, pipe, accum)
        busy = acct["busy_fraction"] or 1.0
        compute_s /= busy
        # Heterogeneity: a synchronous gang runs at its mean effective rate
        # only if rows are rebalanced; without input (rel=1.0) nothing
        # changes. The divide keeps ranking stable — every candidate on the
        # same gang is scaled identically, but cross-gang comparisons (grow
        # targets) see the slow chips.
        rel = self._gang_rel_throughput(gang)
        compute_s /= rel

        compute_b = jnp.dtype(cfg.compute_dtype()).itemsize
        grad_b = (
            jnp.dtype(dtype_of(cfg.grad_allreduce_dtype)).itemsize
            if cfg.grad_allreduce_dtype is not None else 4
        )
        n_params = tfm.param_count(model_cfg)
        # Params owned by this device's fsdp group (model/pipe shard first).
        p_group = n_params / (model_ax * pipe)
        ici_stream_bytes = 0.0  # overlaps with compute (fsdp/data plane)
        ici_exposed_bytes = 0.0  # critical path (tp all-reduce, pipe p2p)
        dcn_bytes = 0.0

        if fsdp > 1 and cfg.sharding_stage >= ShardingStage.FULL_PARTITIONING:
            # ZeRO-3 weight all-gather, forward + backward re-gather, once
            # per accumulation microbatch.
            gather = p_group * compute_b * (fsdp - 1) / fsdp * 2 * accum
            if cfg.comm_quant_weights:
                from tpu_engine.comm_compress import expected_volume_factors

                gather /= expected_volume_factors(
                    cfg.comm_quant_block_size
                )["weight_gather"]
            ici_stream_bytes += gather

        g_bytes = p_group * grad_b
        if fsdp > 1:
            if cfg.sharding_stage >= ShardingStage.GRADIENT_PARTITIONING:
                ici_stream_bytes += g_bytes * (fsdp - 1) / fsdp  # reduce-scatter
                g_bytes /= fsdp  # the data-plane reduce moves the shard
            else:
                ici_stream_bytes += 2 * g_bytes * (fsdp - 1) / fsdp  # all-reduce
        if data > 1:
            reduce = 2 * g_bytes * (data - 1) / data
            if m.dcn_data > 1:
                if cfg.comm_quant_grads:
                    from tpu_engine.comm_compress import expected_volume_factors

                    reduce /= expected_volume_factors(
                        cfg.comm_quant_block_size
                    )["grad_cross_slice"]
                dcn_bytes += reduce
            else:
                ici_stream_bytes += reduce
        if model_ax > 1:
            # Two activation all-reduces per layer per direction (attention
            # out + MLP out), sized [micro, seq, d_model].
            act = micro * seq * model_cfg.d_model * compute_b
            ici_exposed_bytes += (
                8.0 * act * (model_ax - 1) / model_ax
                * (model_cfg.n_layers / pipe) * accum
            )
        if pipe > 1:
            act = micro * seq * model_cfg.d_model * compute_b
            ici_exposed_bytes += 2.0 * act * accum  # boundary ppermute fwd+bwd

        stream_s = ici_stream_bytes / self.ici_bytes_s
        exposed_s = (
            ici_exposed_bytes / self.ici_bytes_s
            + dcn_bytes / self.dcn_bytes_s
        )
        comm_s = stream_s + exposed_s
        plan = PlacementPlan(
            mesh={
                "data": data, "fsdp": fsdp, "pipe": pipe,
                "sequence": seq_axis, "model": model_ax,
                "dcn_data": m.dcn_data,
            },
            gang=gang,
            sharding_stage=int(cfg.sharding_stage),
            pipeline_schedule=schedule,
            micro_batch_size=micro,
            gradient_accumulation_steps=accum,
            quant_training=cfg.quant_training,
            comm_compress=bool(cfg.comm_quant_weights or cfg.comm_quant_grads),
            predicted_compute_s=compute_s,
            predicted_bubble_fraction=acct["bubble_fraction"],
            predicted_comm_s=comm_s,
            predicted_exposed_comm_s=exposed_s,
            predicted_step_time_s=max(compute_s, stream_s) + exposed_s,
            assumed_rel_throughput=rel,
            config=cfg,
        )
        if self.compile_index is not None:
            try:
                key = self.compile_index.key_for_plan(plan)
                plan.compile_warm = self.compile_index.is_warm(key)
                plan.expected_compile_s = self.compile_index.expected_compile_s(key)
            except Exception:  # the index must never block prediction
                log.debug("compile index consult failed", exc_info=True)
        return plan

    def predict(
        self,
        config: TPUTrainConfig,
        gang: Optional[int] = None,
        model_cfg: Optional[tfm.ModelConfig] = None,
    ) -> PlacementPlan:
        """Cost one explicit layout without enumerating alternatives.

        The benchmark/A-B entry point: same prediction the search ranks
        by, for a config the caller already fixed. ``model_cfg`` overrides
        the zoo lookup (mirrors ``build_train_program``'s escape hatch);
        without it, raises ``ValueError`` with ``no_estimate:<model>``
        for models outside the zoo.
        """
        if model_cfg is None:
            if config.model_name not in tfm.MODEL_CONFIGS:
                with self._lock:
                    self.no_estimate_refusals_total += 1
                raise ValueError(f"no_estimate:{config.model_name}")
            model_cfg = tfm.MODEL_CONFIGS[config.model_name]
        g = gang if gang is not None else gang_size(config, None)
        return self._predict(config, model_cfg, g)

    # -- planning (enumerate + HBM filter + rank) ----------------------------

    def plan(
        self,
        config: TPUTrainConfig,
        *,
        devices: Optional[list[Any]] = None,
        reserved: Optional[dict[int, float]] = None,
        gang: Optional[int] = None,
        n_avail: Optional[int] = None,
        saved_topology: Optional[dict] = None,
        **enum_kw: Any,
    ) -> PlannerResult:
        """Ranked feasible plans for ``config`` against the live fleet.

        ``devices``: eligible fleet devices (``TPUDevice``-shaped: index /
        hbm_free_gb / hbm_total_gb); None degrades the HBM gate to
        capacity-only — missing telemetry must not brick planning.
        ``reserved``: the scheduler's device-index → GiB ledger.
        ``gang``: pin the search to one gang size; default searches every
        admissible size up to the available device count ("best
        available") — predicted-fastest wins, which naturally prefers the
        largest gang unless its layouts are HBM-infeasible.
        ``saved_topology``: the mesh factorization a resume candidate's
        checkpoints were saved under (``tpu_engine.reshard`` manifest).
        Plans the reshard plane cannot bridge to (pipe extent change) are
        marked infeasible with a ``no_topology_compatible_checkpoint``
        skip reason; every other plan is priced with
        ``predicted_reshard_s`` and ranking prefers a same-topology
        resume within ``prefer_same_topology_max_slowdown_pct`` of the
        fastest — the remap only wins on a real step-time edge.
        """
        t_search0 = time.time()
        if config.model_name not in tfm.MODEL_CONFIGS:
            with self._lock:
                self.no_estimate_refusals_total += 1
            return PlannerResult(
                plans=[], infeasible=[], pruned=[], evaluated=0,
                skip_reason=f"no_estimate:{config.model_name}",
            )
        if n_avail is None:
            n_avail = len(devices) if devices is not None else None
        if n_avail is None:
            import jax

            n_avail = jax.device_count()
        gangs = [gang] if gang else self._candidate_gangs(n_avail)

        reserved = reserved or {}
        feasible: list[PlacementPlan] = []
        infeasible: list[PlacementPlan] = []
        pruned: list[dict[str, str]] = []
        evaluated = 0
        for g in gangs:
            plans, dropped = self.enumerate(config, g, **enum_kw)
            pruned.extend(dropped)
            evaluated += len(plans) + len(dropped)
            for p in plans:
                est = None
                try:
                    est = self.estimate_fn(p.config, g)
                except Exception:  # estimator must never block planning
                    est = None
                p.hbm_estimate = est
                ok, reason = self._hbm_feasible(est, g, devices, reserved)
                if ok and saved_topology is not None:
                    ok, reason = self._annotate_reshard(p, saved_topology)
                p.feasible = ok
                p.skip_reason = reason
                (feasible if ok else infeasible).append(p)
        # Normalize by samples/step: within one gang every plan carries the
        # same global batch (so this is exactly predicted step time), but
        # across gangs an elastic data=-1 job scales its batch with the
        # devices — raw step time would crown a 1-chip gang that simply
        # does less work. Per-sample time is the throughput-fair order.
        def _per_sample(p: PlacementPlan) -> float:
            samples = (
                p.mesh["data"] * p.mesh["fsdp"]
                * p.micro_batch_size * p.gradient_accumulation_steps
            )
            return p.predicted_step_time_s / samples

        # Warm-first band: with a compile index attached, any WARM layout
        # predicted within ``prefer_warm_max_slowdown_pct`` of the fastest
        # feasible plan outranks every cold one — admission then pays zero
        # compile instead of the cold EMA. The band bounds the trade: a
        # warm plan more than the knob slower never wins on warmth alone.
        best_ps = min(map(_per_sample, feasible), default=0.0)
        warm_band = best_ps * (1.0 + self.prefer_warm_max_slowdown_pct / 100.0)
        reshard_band = best_ps * (
            1.0 + self.prefer_same_topology_max_slowdown_pct / 100.0
        )

        # Same-topology band (only bites with ``saved_topology``): a plan
        # resuming without a remap and predicted within the band of the
        # fastest outranks every topology-changing plan — mirroring the
        # warm-first band, because both costs are one-time admission taxes
        # a small step-time edge never amortizes.
        def _reshard_rank(p: PlacementPlan) -> int:
            if p.reshard_same_topology is None:
                return 0  # no resume topology: the term is inert
            return 0 if (
                p.reshard_same_topology and _per_sample(p) <= reshard_band
            ) else 1

        # Tiebreak equal predicted throughput by expected one-time
        # admission cost (compile when cold + reshard when topology
        # changes), then projected HBM: when two layouts cost the same
        # (fully-overlapped comm makes e.g. fsdp16 and data2xfsdp8
        # identical), the cheaper-to-enter one resumes faster and the one
        # with more headroom is strictly safer to admit.
        feasible.sort(key=lambda p: (
            0 if (p.compile_warm and _per_sample(p) <= warm_band) else 1,
            _reshard_rank(p),
            _per_sample(p),
            p.expected_compile_s + p.predicted_reshard_s,
            p.hbm_estimate.device_total_gib if p.hbm_estimate else float("inf"),
            -p.gang,
        ))
        warm_tiebreak = bool(
            feasible
            and feasible[0].compile_warm
            and _per_sample(feasible[0]) > best_ps
        )
        reshard_tiebreak = bool(
            feasible
            and feasible[0].reshard_same_topology
            and _per_sample(feasible[0]) > best_ps
        )
        with self._lock:
            self.plans_hbm_rejected_total += len(infeasible)
            self.last_feasible = len(feasible)
            if warm_tiebreak:
                self.warm_tiebreaks_total += 1
            if reshard_tiebreak:
                self.reshard_tiebreaks_total += 1
        return PlannerResult(
            plans=feasible, infeasible=infeasible, pruned=pruned,
            evaluated=evaluated, search_s=time.time() - t_search0,
        )

    def _candidate_gangs(self, n_avail: int) -> list[int]:
        """Gang sizes worth searching, largest first. Exhaustive up to
        ``max_gang_enumeration`` devices; beyond that, the full fleet plus
        powers of two (the shapes real slices come in)."""
        if n_avail <= 0:
            return []
        if n_avail <= self.max_gang_enumeration:
            return list(range(n_avail, 0, -1))
        sizes = {n_avail}
        p = 1
        while p <= n_avail:
            sizes.add(p)
            p *= 2
        return sorted(sizes, reverse=True)

    def _annotate_reshard(
        self, p: PlacementPlan, saved_topology: dict
    ) -> tuple[bool, Optional[str]]:
        """Price resuming saved checkpoints onto this plan's mesh.

        Same-topology → zero remap; a bridgeable change → the reshard
        cost model over the model's params+optimizer bytes; a pipe
        extent change → infeasible with the structured skip reason the
        scheduler surfaces verbatim."""
        from tpu_engine import reshard

        ok, why = reshard.topology_compatible(saved_topology, p.mesh)
        if not ok:
            with self._lock:
                self.topology_rejected_total += 1
            return False, f"no_topology_compatible_checkpoint: {why}"
        p.reshard_same_topology = reshard.same_topology(saved_topology, p.mesh)
        if not p.reshard_same_topology:
            state_bytes = reshard.state_bytes_for_model(
                p.config.model_name if p.config is not None else ""
            )
            p.predicted_reshard_s = reshard.reshard_cost_s(state_bytes or 0)
        return True, None

    def _hbm_feasible(
        self,
        est: Optional[HBMEstimate],
        gang: int,
        devices: Optional[list[Any]],
        reserved: dict[int, float],
    ) -> tuple[bool, Optional[str]]:
        """Mirror of the scheduler's admission HBM gate: enough devices
        with ``free - reserved >= need``, where ``need`` carries the
        ``hbm_margin_frac`` surcharge for XLA temporaries the analytic
        estimate cannot see. Capacity-only (always feasible) when there is
        no fleet view or no HBM telemetry."""
        if devices is None or not devices:
            return True, None
        if len(devices) < gang:
            return False, f"gang {gang} > {len(devices)} eligible chip(s)"
        if est is None or not all(
            getattr(d, "hbm_total_gb", 0) > 0 for d in devices
        ):
            return True, None
        need = est.device_total_gib * (1.0 + self.hbm_margin_frac)
        fits = sum(
            1 for d in devices
            if d.hbm_free_gb - reserved.get(d.index, 0.0) >= need
        )
        if fits < gang:
            return False, (
                f"needs {need:.2f} GiB/device (est + "
                f"{self.hbm_margin_frac:.0%} margin) on {gang} chip(s); "
                f"only {fits} have that headroom"
            )
        return True, None

    # -- grow-back support ---------------------------------------------------

    def grow_target(
        self,
        config: TPUTrainConfig,
        devices: list[Any],
        reserved: dict[int, float],
        current_gang: int,
        estimate_fn: Optional[Callable[..., Optional[HBMEstimate]]] = None,
    ) -> Optional[int]:
        """Largest gang (> ``current_gang``) a shrunk job could grow to on
        ``devices`` — the full configured gang when it fits, else the
        largest *intermediate* mesh from the elastic family, HBM-gated
        against per-device headroom minus ``reserved`` (the caller drops
        the job's own reservation first). None → stay at the current size.
        """
        from tpu_engine.hbm_estimate import elastic_shrink_plan

        est_fn = estimate_fn or self.estimate_fn
        n = len(devices)
        full = gang_size(config, n)
        if current_gang < full <= n:
            try:
                est = est_fn(config, full)
            except Exception:
                est = None
            if self._hbm_feasible(est, full, devices, reserved)[0]:
                return full
        probe = n
        while probe > current_gang:
            try:
                shrink = elastic_shrink_plan(config, probe, est_fn)
            except Exception:
                return None
            if shrink is None:
                return None
            _, n_use, est = shrink
            if n_use <= current_gang:
                return None
            if self._hbm_feasible(est, n_use, devices, reserved)[0]:
                return n_use
            probe = n_use - 1
        return None

    # -- telemetry -----------------------------------------------------------

    def _account(
        self, evaluated: int, pruned_n: int, reasons: list[str]
    ) -> None:
        with self._lock:
            self.plans_evaluated_total += evaluated
            self.plans_pruned_total += pruned_n
            for r in reasons:
                key = r.split("(")[0].split(":")[0].strip()[:60]
                self.prune_reasons[key] = self.prune_reasons.get(key, 0) + 1

    def note_chosen(self, plan: PlacementPlan) -> None:
        with self._lock:
            self.plans_chosen_total += 1
            self.last_chosen_predicted_s = plan.predicted_step_time_s

    def record_observation(self, predicted_s: float, observed_s: float) -> None:
        """Predicted-vs-observed step time for an admitted auto plan
        (the scheduler calls this at reap with wall seconds / steps)."""
        if predicted_s <= 0 or observed_s <= 0:
            return
        with self._lock:
            self._observations.append((predicted_s, observed_s))
            del self._observations[:-200]
            rel_err = abs(predicted_s - observed_s) / observed_s
            prev = self._calib_ema_rel_error
            a = self.calibration_alpha
            self._calib_ema_rel_error = (
                rel_err if prev is None else (1 - a) * prev + a * rel_err
            )
            self._calib_observations_total += 1
            self._calib_last = (predicted_s, observed_s)
        if self._calibration_path is not None:
            self._persist_calibration()

    # -- calibration sidecar -------------------------------------------------

    CALIBRATION_SIDECAR = "placement_calibration.json"

    def attach_calibration(self, cache_dir: str) -> None:
        """Persist predicted-vs-observed calibration under ``cache_dir``.

        Mirrors the compile-index sidecar: load whatever a previous run
        learned (the EMA survives restarts, fixing the silent loss of
        in-memory-only calibration), then keep the file fresh on every
        ``record_observation``. Attach is idempotent and failure-tolerant.
        """
        path = os.path.join(cache_dir, self.CALIBRATION_SIDECAR)
        self._calibration_path = path
        self._load_calibration()
        self._persist_calibration()

    def _load_calibration(self) -> None:
        path = self._calibration_path
        if path is None or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                doc = json.load(f)
            if not isinstance(doc, dict):
                raise ValueError(f"sidecar is not a JSON object: {type(doc).__name__}")
        except Exception:
            # Torn/garbage sidecar (crash mid-write) — warn, count, start
            # fresh; calibration rebuilds from live observations.
            with self._lock:
                self.calibration_load_errors_total += 1
            log.warning("placement calibration sidecar unreadable: %s", path)
            return
        try:
            with self._lock:
                ema = doc.get("ema_rel_error")
                if ema is not None and self._calib_ema_rel_error is None:
                    self._calib_ema_rel_error = float(ema)
                self._calib_observations_total += int(
                    doc.get("observations_total", 0)
                )
                last = doc.get("last")
                if self._calib_last is None and isinstance(last, (list, tuple)):
                    if len(last) == 2:
                        self._calib_last = (float(last[0]), float(last[1]))
        except (TypeError, ValueError):
            with self._lock:
                self.calibration_load_errors_total += 1
            log.warning("placement calibration sidecar malformed: %s", path)

    def _persist_calibration(self) -> None:
        path = self._calibration_path
        if path is None:
            return
        with self._lock:
            doc = {
                "version": 1,
                "ema_rel_error": self._calib_ema_rel_error,
                "alpha": self.calibration_alpha,
                "observations_total": self._calib_observations_total,
                "last": list(self._calib_last) if self._calib_last else None,
            }
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, path)  # atomic on POSIX: readers never see a torn file
        except OSError:
            with self._lock:
                self.calibration_persist_errors_total += 1
            log.warning("placement calibration persist failed: %s", path)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            obs = list(self._observations)
            top_reasons = dict(
                sorted(self.prune_reasons.items(), key=lambda kv: -kv[1])[:8]
            )
            out = {
                "plans_evaluated_total": self.plans_evaluated_total,
                "plans_pruned_total": self.plans_pruned_total,
                "plans_hbm_rejected_total": self.plans_hbm_rejected_total,
                "plans_chosen_total": self.plans_chosen_total,
                "no_estimate_refusals_total": self.no_estimate_refusals_total,
                "warm_tiebreaks_total": self.warm_tiebreaks_total,
                "topology_rejected_total": self.topology_rejected_total,
                "reshard_tiebreaks_total": self.reshard_tiebreaks_total,
                "compile_index_attached": self.compile_index is not None,
                "prefer_warm_max_slowdown_pct": self.prefer_warm_max_slowdown_pct,
                "last_feasible": self.last_feasible,
                "last_chosen_predicted_s": self.last_chosen_predicted_s,
                "prune_reasons": top_reasons,
                "observations_total": len(obs),
                "throughput_fn_attached": self.throughput_fn is not None,
                "calibration": {
                    "attached": self._calibration_path is not None,
                    "path": self._calibration_path,
                    "ema_rel_error": self._calib_ema_rel_error,
                    "observations_total": self._calib_observations_total,
                    "persist_errors_total": self.calibration_persist_errors_total,
                    "load_errors_total": self.calibration_load_errors_total,
                },
            }
        if obs:
            errs = [abs(p - o) / o for p, o in obs]
            out["step_time_abs_rel_error"] = sum(errs) / len(errs)
            out["last_predicted_s"], out["last_observed_s"] = obs[-1]
        else:
            out["step_time_abs_rel_error"] = None
        return out


# ---------------------------------------------------------------------------
# Serving-pool planning (disaggregated prefill/decode — tpu_engine/disagg.py)
# ---------------------------------------------------------------------------


class ServingPoolPlan(BaseModel):
    """One candidate layout for a disaggregated serving pool, with the
    role-specific cost-model verdict. Prefill pools rank by the compute
    roofline (per-request prefill latency at ``max_len``); decode pools by
    aggregate KV-pool decode throughput (slots served per HBM-bound step,
    summed over replicas)."""

    model_config = ConfigDict(arbitrary_types_allowed=True)

    role: str  # "prefill" | "decode" | "draft"
    tensor_parallel: int
    replicas: int
    max_slots: int
    max_len: int
    kv_quant: bool = False
    weight_quant: Optional[str] = None
    predicted_prefill_s: float = 0.0  # one max_len prompt through one replica
    predicted_decode_tok_s: float = 0.0  # pool-aggregate steady-state tokens/s
    predicted_propose_s: float = 0.0  # gamma sequential draft steps (draft role)
    hbm_estimate: Optional[HBMEstimate] = None
    feasible: bool = True
    skip_reason: Optional[str] = None

    @property
    def label(self) -> str:
        tags = []
        if self.kv_quant:
            tags.append("kvq")
        if self.weight_quant:
            tags.append(self.weight_quant)
        return "·".join(
            [f"{self.role}", f"tp{self.tensor_parallel}x{self.replicas}",
             f"slots{self.max_slots}", *tags]
        )


# HBM stream bandwidth closes the decode roofline the same way
# NOMINAL_PEAK_FLOPS closes the prefill one: absolute values are nominal
# (v5e HBM2E), ranking depends only on the ratios.
NOMINAL_HBM_BYTES_S = 8.1e11


def plan_serving_pool(
    model_name: str,
    role: str,
    n_devices: int,
    *,
    hbm_free_gib: float = 16.0,
    max_len: int = 1024,
    candidate_slots: Sequence[int] = (4, 8, 16, 32),
    inflight_handoffs: int = 4,
    compute_dtype: Precision = Precision.BF16,
    kv_quant: bool = False,
    weight_quant: Optional[str] = None,
    prefill_chunk: int = 256,
    spec_gamma: int = 4,
) -> list[ServingPoolPlan]:
    """Enumerate → HBM-filter → rank layouts for ONE disaggregated serving
    pool over ``n_devices`` chips. The same enumerate/filter/rank recipe as
    the training planner, with the serving cost model:

    - every ``tensor_parallel`` that divides ``n_devices`` (and the model's
      kv/q heads), each yielding ``n_devices // tp`` replicas;
    - per-device HBM through :func:`estimate_serving_hbm` with the pool's
      ``pool_role`` — the SAME admission gate the scheduler enforces, so a
      plan this function ranks first is a plan the ledger will admit;
    - **prefill** rank: roofline latency of one ``max_len`` prompt,
      ``2·P·T / (tp·peak·MFU)`` plus per-chunk dispatch overhead — more
      tensor parallelism is better until chunk dispatch dominates; slots
      are pinned to ``inflight_handoffs`` (the pool's only job is holding
      finished requests for extraction);
    - **decode** rank: aggregate tokens/sec with every slot busy — each
      step streams the weight shard once for the whole batch plus one
      resident KV row per slot, so bigger pools amortize the weight read
      until the KV term (or HBM) bites. This is exactly the
      "decode ranked by KV-pool capacity" axis;
    - **draft** rank (``tpu_engine/spec_pool.py``): latency of one
      draft-propose leg — ``spec_gamma`` *sequential* memory-bound decode
      steps, each streaming the draft weight shard + resident KV rows.
      Tie-break toward SMALLER tensor parallelism: draft pools exist to
      backfill the fragmented single-chip headroom the verify pools leave
      behind, and callers express that by passing the fragmented
      ``hbm_free_gib`` as the filter. Slots come from ``candidate_slots``
      like decode.

    Returns ALL candidates, feasible first in rank order (infeasible tail
    carries ``skip_reason``) — callers record ``plans[0].label`` as the
    planner-chosen layout. Empty list for unknown models.
    """
    from tpu_engine.hbm_estimate import estimate_serving_hbm

    if role not in ("prefill", "decode", "draft"):
        raise ValueError(f"role must be prefill|decode|draft, got {role!r}")
    model_cfg = tfm.MODEL_CONFIGS.get(model_name)
    if model_cfg is None:
        return []

    n_devices = max(int(n_devices), 1)
    n_params = tfm.param_count(model_cfg)
    compute_b = 1.02 if weight_quant == "int8" else (
        2 if compute_dtype != Precision.FP32 else 4)
    kv_row_bytes = (  # one token's K+V across all layers, as stored
        2 * model_cfg.n_layers * model_cfg.n_kv_heads * model_cfg.head_dim
        * (1 if kv_quant else (2 if compute_dtype != Precision.FP32 else 4))
    )

    plans: list[ServingPoolPlan] = []
    slot_choices = (
        [max(int(inflight_handoffs), 1)] if role == "prefill"
        else sorted({max(int(s), 1) for s in candidate_slots})
    )
    for tp in _divisors(n_devices):
        if model_cfg.n_heads % tp or model_cfg.n_kv_heads % tp:
            continue  # serving.py would replicate heads — not a real layout
        replicas = n_devices // tp
        for slots in slot_choices:
            est = estimate_serving_hbm(
                model_name, slots, max_len,
                tensor_parallel=tp, compute_dtype=compute_dtype,
                kv_quant=kv_quant, weight_quant=weight_quant,
                prefill_chunk=prefill_chunk, pool_role=role,
                inflight_handoffs=(
                    inflight_handoffs if role == "prefill" else None),
            )
            # Prefill: compute roofline over the tp shard + one dispatch
            # latency per chunk (why tp→∞ is not free).
            n_chunks = -(-int(max_len) // max(int(prefill_chunk), 1))
            prefill_s = (
                2.0 * n_params * max_len
                / (tp * NOMINAL_PEAK_FLOPS * ASSUMED_MFU)
                + n_chunks * 2e-3
            )
            # Decode: per step, stream the weight shard once + every
            # resident KV row (half-full on average); all slots emit one
            # token per step, replicas are independent.
            kv_shard = tp if model_cfg.n_kv_heads % tp == 0 else 1
            step_bytes = (
                n_params * compute_b / tp
                + slots * (max_len / 2) * kv_row_bytes / kv_shard
            )
            tok_s = replicas * slots / (step_bytes / NOMINAL_HBM_BYTES_S)
            # Draft: one propose leg = spec_gamma SEQUENTIAL decode steps
            # (all slots share each step's weight stream, so the leg's
            # latency is per-step time, not per-token).
            propose_s = max(int(spec_gamma), 1) * step_bytes / NOMINAL_HBM_BYTES_S
            plan = ServingPoolPlan(
                role=role, tensor_parallel=tp, replicas=replicas,
                max_slots=slots, max_len=int(max_len), kv_quant=kv_quant,
                weight_quant=weight_quant,
                predicted_prefill_s=prefill_s,
                predicted_decode_tok_s=tok_s,
                predicted_propose_s=propose_s,
                hbm_estimate=est,
            )
            if est is not None and est.device_total_gib > hbm_free_gib:
                plan.feasible = False
                plan.skip_reason = (
                    f"needs {est.device_total_gib:.2f} GiB/device, "
                    f"{hbm_free_gib:.2f} free"
                )
            plans.append(plan)

    def rank_key(p: ServingPoolPlan) -> tuple:
        if role == "prefill":
            # Fastest single-prompt prefill; tie-break toward more
            # parallel lanes (replicas) for burst absorption.
            return (p.predicted_prefill_s, -p.replicas, p.tensor_parallel)
        if role == "draft":
            # Fastest propose leg; tie-break toward SMALLER tp — draft
            # pools backfill fragmented single-chip headroom.
            return (p.predicted_propose_s, p.tensor_parallel, -p.max_slots)
        return (-p.predicted_decode_tok_s, p.tensor_parallel, -p.max_slots)

    feasible = sorted([p for p in plans if p.feasible], key=rank_key)
    infeasible = sorted([p for p in plans if not p.feasible], key=rank_key)
    return feasible + infeasible
