"""ZeRO++-style quantized & hierarchical collectives for the multislice path.

The GSPMD train step moves full-width values over every link: ZeRO-3 weight
all-gathers carry fp32/bf16 over the ``fsdp`` axis, and the data-parallel
gradient reduction carries fp32 across DCN when ``dcn_data > 1``. ZeRO++
(arXiv:2306.10209) cuts that volume ~4x with three composable mechanisms,
which map directly onto the TPU ICI-vs-DCN bandwidth asymmetry:

- **qwZ** (``comm_quant_weights``): block-quantized int8 weight all-gather.
  Each ZeRO-3 shard is quantized to int8 with per-block absmax scales
  BEFORE the gather, so the ``fsdp`` collective moves 1 byte/element plus
  a small scale sidecar; the full-width weights are reconstructed on every
  device AFTER the gather. Gradients flow to the primary fp32 partition via
  a straight-through estimator whose transpose is the exact ZeRO-3
  reduce-scatter (``psum_scatter`` over ``fsdp``).
- **hpZ** (``comm_secondary_weights``): a secondary int8 parameter replica
  (codes + scales), sharded like the primary partition and refreshed from
  it after each optimizer step. Steady-state forward/backward gathers read
  the pre-quantized secondary store — the quantize work leaves the
  per-microbatch hot path (it would otherwise run once per microbatch per
  remat pass), and in deployments where the primary partition lives in
  host memory or spans slices the gather source stays in device HBM on
  ICI. Gradients still target the primary partition (straight-through).
- **qgZ** (``comm_quant_grads``): hierarchical gradient reduction for
  hybrid meshes. Gradients are first psum-reduced in fp32 WITHIN each
  slice (ICI, cheap), then block-quantized int8 partials are exchanged
  ACROSS slices (DCN, the slow link) and dequantize-summed locally — the
  cross-slice wire carries 1 byte/element instead of 4. Quantization uses
  stochastic rounding so the error is zero-mean and does not bias the
  optimizer (the stateless alternative to error-feedback buffers, which
  would add a persistent fp32 residual per leaf).

Mechanism: the per-microbatch loss/grad computation runs inside ONE
full-manual ``shard_map`` over the whole mesh, so the collectives are
explicit ``jax.lax`` calls whose operand dtype *is* the wire dtype — XLA
cannot fuse a dequantize below an implicit GSPMD gather and silently move
fp32 (observed: constraint-based int8 resharding does exactly that).
Full-manual is also a hard requirement: partial-auto ``shard_map`` with a
real-extent auto axis aborts the SPMD partitioner on the collectives this
module emits, which is why compression requires pipe = sequence = model = 1
(enforced at config/build time — a partitioner abort kills the process).
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from tpu_engine.mesh_runtime import BATCH_AXES

# Leaf names whose (>=2-D, fsdp-sharded) tensors ride the quantized gather;
# everything else (norm scales, biases) gathers full-width — those leaves
# are a sliver of the bytes and the most quantization-sensitive.
_QUANT_LEAF_NAMES = ("kernel", "embedding")


# ---------------------------------------------------------------------------
# Blockwise int8 quantization (last-axis blocks, absmax/127 scales)
# ---------------------------------------------------------------------------


def _n_blocks(last: int, block: int) -> int:
    return -(-last // block)


def stochastic_round(y: jax.Array, key: jax.Array) -> jax.Array:
    """Unbiased stochastic rounding: ``floor(y + u)``, ``u ~ U[0,1)`` —
    ``E[result] == y``. The shared rounding helper for the quantized
    collectives here (qgZ) and the quantized training matmuls
    (tpu_engine/quant_train.py): zero-mean error needs no error-feedback
    state."""
    return jnp.floor(y + jax.random.uniform(key, y.shape))


def blockwise_quantize(
    x: jax.Array, block: int, key: Optional[jax.Array] = None
) -> tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization in blocks of ``block`` along the last
    axis. Returns ``(codes, scales)`` where ``codes`` is int8 with the last
    axis PADDED up to a whole number of blocks (``n_blocks * block``) and
    ``scales`` is fp32 with shape ``x.shape[:-1] + (n_blocks,)``.

    ``key`` switches round-to-nearest to stochastic rounding
    (``floor(v + u)``, ``u ~ U[0,1)``) — unbiased: ``E[deq] == x``.

    The padded-codes convention is deliberate: a shard gathered over a
    mesh axis concatenates per-shard block grids, and keeping each shard's
    grid whole means the gathered codes always reshape cleanly to
    ``(..., n_blocks, block)`` regardless of the shard extent.
    """
    last = x.shape[-1]
    nb = _n_blocks(last, block)
    pad = nb * block - last
    xf = x.astype(jnp.float32)
    if pad:
        xf = jnp.pad(xf, [(0, 0)] * (x.ndim - 1) + [(0, pad)])
    xb = xf.reshape(*x.shape[:-1], nb, block)
    absmax = jnp.max(jnp.abs(xb), axis=-1)
    scales = jnp.maximum(absmax, 1e-30) / 127.0
    y = xb / scales[..., None]
    if key is not None:
        y = stochastic_round(y, key)
    else:
        y = jnp.round(y)
    codes = jnp.clip(y, -127.0, 127.0).astype(jnp.int8)
    return codes.reshape(*x.shape[:-1], nb * block), scales


def blockwise_dequantize(
    codes: jax.Array, scales: jax.Array, block: int,
    last: Optional[int] = None, dtype=jnp.float32,
) -> jax.Array:
    """Inverse of :func:`blockwise_quantize`: padded int8 codes + fp32
    scales → float array, trimmed to ``last`` elements on the final axis
    (default: the codes' own padded extent)."""
    nb = codes.shape[-1] // block
    cb = codes.astype(jnp.float32).reshape(*codes.shape[:-1], nb, block)
    out = (cb * scales[..., None]).reshape(*codes.shape[:-1], nb * block)
    if last is not None and last != out.shape[-1]:
        out = out[..., :last]
    return out.astype(dtype)


def _dequantize_gathered(
    codes_g: jax.Array, scales_g: jax.Array, *, gather_dim: int, block: int,
    shard_last: int, global_last: int, dtype,
) -> jax.Array:
    """Dequantize codes that were tile-gathered along ``gather_dim``.

    When the gather dim IS the last axis, the gathered codes interleave
    per-shard padding (each shard contributed its own whole block grid):
    dequantize per segment, trim each segment to the shard's true extent,
    and re-merge. Any other gather dim leaves block grids untouched.
    """
    ndim = codes_g.ndim
    if gather_dim != ndim - 1:
        return blockwise_dequantize(
            codes_g, scales_g, block, last=global_last, dtype=dtype
        )
    n_shards = global_last // shard_last
    seg = codes_g.shape[-1] // n_shards  # per-shard padded extent
    full = blockwise_dequantize(codes_g, scales_g, block, dtype=dtype)
    full = full.reshape(*full.shape[:-1], n_shards, seg)[..., :shard_last]
    return full.reshape(*full.shape[:-2], n_shards * shard_last)


# ---------------------------------------------------------------------------
# Hybrid-mesh replica groups (data axis = dcn_data outer blocks of slices)
# ---------------------------------------------------------------------------


def data_slice_groups(
    data_size: int, dcn_data: int
) -> tuple[list[list[int]], list[list[int]]]:
    """(intra-slice, cross-slice) ``axis_index_groups`` over the data axis.

    The mesh lays whole slices as the outer blocks of the data axis
    (``mesh_runtime.build_mesh``), so data indices ``[s*k, (s+1)*k)`` share
    slice ``s`` (``k = data/dcn``). Intra groups reduce over ICI; cross
    groups connect the same intra-slice position across slices (DCN).
    """
    if data_size % dcn_data != 0:
        raise ValueError(
            f"data axis {data_size} not divisible by dcn_data={dcn_data}"
        )
    per = data_size // dcn_data
    intra = [list(range(s * per, (s + 1) * per)) for s in range(dcn_data)]
    cross = [[s * per + i for s in range(dcn_data)] for i in range(per)]
    return intra, cross


# ---------------------------------------------------------------------------
# Per-leaf metadata
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class LeafPlan:
    """How one parameter leaf moves through the compressed step."""

    fsdp_dim: Optional[int]  # index of "fsdp" in the leaf's PartitionSpec
    quantize: bool           # ride the int8 gather (qwZ/hpZ)
    global_last: int         # full extent of the leaf's final axis
    shard_last: int          # per-shard extent of the final axis


def _leaf_name(path) -> str:
    last = path[-1]
    return getattr(last, "key", getattr(last, "name", str(last)))


def build_leaf_plans(
    pspecs: Any, abs_params: Any, fsdp_size: int, quant_weights: bool
) -> Any:
    """A :class:`LeafPlan` tree aligned with the params tree."""

    def plan(path, spec, leaf):
        parts = tuple(spec)
        fsdp_dim = parts.index("fsdp") if "fsdp" in parts else None
        shape = tuple(leaf.shape)
        for d, ax in enumerate(parts):
            if ax is None:
                continue
            # fsdp is the only >1 manual axis params shard over here
            # (pipe/sequence/model are forced to 1); uneven shards would
            # make shard_map reject the spec with an opaque error.
            if ax == "fsdp" and shape[d] % fsdp_size != 0:
                raise ValueError(
                    f"comm compression: leaf {jax.tree_util.keystr(path)} "
                    f"dim {d} ({shape[d]}) is not divisible by the fsdp "
                    f"axis size {fsdp_size}"
                )
        shard_last = shape[-1]
        if fsdp_dim == len(shape) - 1:
            shard_last = shape[-1] // fsdp_size
        quantize = (
            quant_weights
            and fsdp_dim is not None
            and len(shape) >= 2
            and _leaf_name(path) in _QUANT_LEAF_NAMES
        )
        return LeafPlan(fsdp_dim, quantize, shape[-1], shard_last)

    flat_specs, treedef = jax.tree_util.tree_flatten_with_path(
        pspecs, is_leaf=lambda x: isinstance(x, P)
    )
    flat_abs = jax.tree_util.tree_leaves(abs_params)
    return jax.tree_util.tree_unflatten(
        treedef,
        [plan(p, s, a) for (p, s), a in zip(flat_specs, flat_abs)],
    )


# ---------------------------------------------------------------------------
# Gather primitives (inside the full-manual shard_map region)
# ---------------------------------------------------------------------------


def _qwz_gather(shard, plan: LeafPlan, block: int, dtype):
    """Quantize-gather-dequantize over ``fsdp`` with a straight-through
    backward: the cotangent of the full weight reduce-scatters back to the
    primary shard — exactly the ZeRO-3 gradient collective."""

    @jax.custom_vjp
    def gather(x):
        codes, scales = blockwise_quantize(x, block)
        codes_g = jax.lax.all_gather(
            codes, "fsdp", axis=plan.fsdp_dim, tiled=True
        )
        scales_g = jax.lax.all_gather(
            scales, "fsdp", axis=plan.fsdp_dim, tiled=True
        )
        return _dequantize_gathered(
            codes_g, scales_g, gather_dim=plan.fsdp_dim, block=block,
            shard_last=plan.shard_last, global_last=plan.global_last,
            dtype=dtype,
        )

    def fwd(x):
        return gather(x), None

    def bwd(_, ct):
        g = jax.lax.psum_scatter(
            ct.astype(jnp.float32), "fsdp",
            scatter_dimension=plan.fsdp_dim, tiled=True,
        )
        return (g,)

    gather.defvjp(fwd, bwd)
    return gather(shard)


def _hpz_gather(shard, codes, scales, plan: LeafPlan, block: int, dtype):
    """qwZ gather reading the pre-quantized SECONDARY store (hpZ): the
    forward never touches the primary shard (and never re-quantizes), but
    the straight-through backward still routes the cotangent to it. The
    int8 codes/scales are closed over, not primal inputs — they carry no
    gradient by construction."""
    codes = jax.lax.stop_gradient(codes)
    scales = jax.lax.stop_gradient(scales)

    @jax.custom_vjp
    def gather(x):
        codes_g = jax.lax.all_gather(
            codes, "fsdp", axis=plan.fsdp_dim, tiled=True
        )
        scales_g = jax.lax.all_gather(
            scales, "fsdp", axis=plan.fsdp_dim, tiled=True
        )
        return _dequantize_gathered(
            codes_g, scales_g, gather_dim=plan.fsdp_dim, block=block,
            shard_last=plan.shard_last, global_last=plan.global_last,
            dtype=dtype,
        )

    def fwd(x):
        return gather(x), None

    def bwd(_, ct):
        g = jax.lax.psum_scatter(
            ct.astype(jnp.float32), "fsdp",
            scatter_dimension=plan.fsdp_dim, tiled=True,
        )
        return (g,)

    gather.defvjp(fwd, bwd)
    return gather(shard)


def _fp_gather(shard, plan: LeafPlan):
    """Full-width gather over ``fsdp`` for non-quantized sharded leaves.
    Same custom_vjp structure as the quantized path so every leaf's
    backward collective is the explicit psum_scatter."""

    @jax.custom_vjp
    def gather(x):
        return jax.lax.all_gather(x, "fsdp", axis=plan.fsdp_dim, tiled=True)

    def fwd(x):
        return gather(x), None

    def bwd(_, ct):
        g = jax.lax.psum_scatter(
            ct.astype(jnp.float32), "fsdp",
            scatter_dimension=plan.fsdp_dim, tiled=True,
        )
        return (g,)

    gather.defvjp(fwd, bwd)
    return gather(shard)


# ---------------------------------------------------------------------------
# The compression context: compressed grad fn + hpZ refresh
# ---------------------------------------------------------------------------


@dataclass
class CommCompression:
    """Bound compressed-communication step pieces for one train program.

    ``accumulate(params, hpz, batch, key)`` replaces
    ``train.accumulate_grads`` (same contract: summed loss, summed fp32
    grads at the ZeRO-3 grad shardings). ``refresh(params)`` produces the
    hpZ secondary store (None when hpZ is off); ``hpz_pspecs`` its
    PartitionSpec tree for the state shardings.
    """

    quant_weights: bool
    secondary_weights: bool
    quant_grads: bool
    block_size: int
    accumulate: Callable[..., tuple[jax.Array, Any]]
    refresh: Optional[Callable[[Any], Any]]
    hpz_pspecs: Optional[dict[str, Any]]


def enabled(cfg) -> bool:
    """True when any comm-compression mechanism is on for ``cfg``."""
    return bool(
        cfg.comm_quant_weights
        or cfg.comm_secondary_weights
        or cfg.comm_quant_grads
    )


def validate_runtime(cfg, runtime, model_cfg, *, attn_mesh) -> None:
    """Runtime-shaped rejections the config validators cannot see.

    These MUST fail at build time: the full-manual shard_map region cannot
    contain a second manual region (the flash/ring/ulysses attention
    kernels) and cannot leave a real-extent axis in auto mode — the SPMD
    partitioner hard-aborts the process on that combination rather than
    raising.
    """
    sizes = runtime.axis_sizes
    for ax in ("pipe", "sequence", "model"):
        if sizes[ax] > 1:
            raise ValueError(
                f"comm compression requires a mesh with {ax}=1 (got "
                f"{sizes[ax]}): the quantized collectives run in a "
                "full-manual shard_map over (data, fsdp) only"
            )
    if attn_mesh is not None:
        raise ValueError(
            "comm compression requires attention_impl='xla' (the "
            "flash/ring/ulysses kernels are shard_map regions and cannot "
            "nest inside the compression region)"
        )
    if model_cfg.is_moe:
        raise ValueError(
            "comm compression does not support MoE models (the router aux "
            "loss is a batch mean whose per-shard decomposition differs "
            "from the global mean)"
        )


def build(
    *,
    mesh: Mesh,
    loss_fn: Callable[..., jax.Array],
    pspecs: Any,
    abs_params: Any,
    grad_sh: Any,
    data_size: int,
    fsdp_size: int,
    dcn_data: int,
    quant_weights: bool,
    secondary_weights: bool,
    quant_grads: bool,
    block_size: int,
    dtype=jnp.float32,
) -> CommCompression:
    """Assemble the compressed gradient path for one train program.

    ``loss_fn(params, tokens, include_aux, denom=..., aux_weight=...)`` is
    the per-microbatch loss; inside the manual region it sees locally-
    sharded tokens and FULL (gathered) params, and returns this device's
    loss contribution (sums over local rows / the global denom) — summing
    over devices reproduces the GSPMD objective exactly.
    """
    plans = build_leaf_plans(pspecs, abs_params, fsdp_size, quant_weights)
    intra_groups, cross_groups = data_slice_groups(data_size, dcn_data)
    block = block_size
    n_leaves = len(jax.tree_util.tree_leaves(abs_params))

    def gather_full(shard, codes, scales, plan):
        if plan.quantize and secondary_weights:
            return _hpz_gather(shard, codes, scales, plan, block, dtype)
        if plan.quantize:
            return _qwz_gather(shard, plan, block, dtype)
        if plan.fsdp_dim is not None:
            return _fp_gather(shard, plan)
        return shard  # replicated over fsdp; grads reduced post-hoc

    def reduce_grad(g, plan, key):
        # fsdp-sharded leaves arrive fsdp-reduced (the gathers' backward
        # psum_scatter); replicated leaves hold per-device partials.
        if plan.fsdp_dim is None and fsdp_size > 1:
            g = jax.lax.psum(g, "fsdp")
        if data_size == 1:
            return g
        if not quant_grads:
            return jax.lax.psum(g, "data")
        # qgZ: fp32 within the slice (ICI), int8 partials across slices
        # (DCN), dequantize-sum locally. With dcn_data == 1 there is no
        # cross-slice link to compress — plain fp32 psum (documented).
        if dcn_data > 1:
            if data_size > dcn_data:
                g = jax.lax.psum(g, "data", axis_index_groups=intra_groups)
            codes, scales = blockwise_quantize(g, block, key=key)
            codes_x = jax.lax.all_gather(
                codes, "data", axis_index_groups=cross_groups
            )
            scales_x = jax.lax.all_gather(
                scales, "data", axis_index_groups=cross_groups
            )
            parts = blockwise_dequantize(
                codes_x, scales_x, block, last=g.shape[-1]
            )
            return jnp.sum(parts, axis=0)
        return jax.lax.psum(g, "data")

    def body(shards, hpz, tokens, denom, key):
        codes_tree = hpz["codes"] if secondary_weights else plans
        scales_tree = hpz["scales"] if secondary_weights else plans

        def local_loss(shards_):
            full = jax.tree_util.tree_map(
                gather_full, shards_, codes_tree, scales_tree, plans,
                is_leaf=lambda x: isinstance(x, LeafPlan),
            ) if secondary_weights else jax.tree_util.tree_map(
                lambda s, p: gather_full(s, None, None, p), shards_, plans,
                is_leaf=lambda x: isinstance(x, LeafPlan),
            )
            return loss_fn(full, tokens, True, denom=denom)

        loss, grads = jax.value_and_grad(local_loss)(shards)
        leaves, treedef = jax.tree_util.tree_flatten(grads)
        plan_leaves = jax.tree_util.tree_leaves(
            plans, is_leaf=lambda x: isinstance(x, LeafPlan)
        )
        keys = jax.random.split(key, len(leaves))
        reduced = [
            reduce_grad(g, p, k)
            for g, p, k in zip(leaves, plan_leaves, keys)
        ]
        grads = jax.tree_util.tree_unflatten(treedef, reduced)
        return jax.lax.psum(loss, ("data", "fsdp")), grads

    spec_trees = _hpz_spec_trees(pspecs, plans) if secondary_weights else None
    hpz_in_spec = (
        {"codes": spec_trees["codes"], "scales": spec_trees["scales"]}
        if secondary_weights
        else P()  # placeholder leaf for the empty {} pytree
    )
    sm_grad = shard_map(
        body,
        mesh,
        in_specs=(pspecs, hpz_in_spec, P(BATCH_AXES), P(), P()),
        out_specs=(P(), pspecs),
        check_rep=False,
    )

    def accumulate(params, hpz, batch, key):
        """Drop-in for ``train.accumulate_grads``: scan the microbatches
        through the compressed grad fn, summing loss and fp32 grads."""
        accum = batch.shape[0]
        denom = jnp.maximum(
            jnp.sum((batch[:, :, 1:] >= 0).astype(jnp.float32)), 1.0
        )
        if hpz is None:
            hpz = {}

        def accum_body(carry, xs):
            loss_acc, grad_acc = carry
            tokens, k = xs
            loss, grads = sm_grad(params, hpz, tokens, denom, k)
            grad_acc = jax.tree.map(
                lambda a, g: a + g.astype(jnp.float32), grad_acc, grads
            )
            return (loss_acc + loss, grad_acc), None

        zero_grads = jax.tree.map(
            lambda p: jnp.zeros(p.shape, jnp.float32), params
        )
        zero_grads = jax.lax.with_sharding_constraint(zero_grads, grad_sh)
        keys = jax.random.split(key, accum)
        (loss, grad_sum), _ = jax.lax.scan(
            accum_body, (jnp.zeros((), jnp.float32), zero_grads),
            (batch, keys),
        )
        return loss, grad_sum

    refresh = None
    hpz_pspecs = None
    if secondary_weights:
        hpz_pspecs = spec_trees

        def refresh_body(shards):
            def q(s, plan):
                if not plan.quantize:
                    return None
                return blockwise_quantize(s, block)

            pairs = jax.tree_util.tree_map(
                q, shards, plans, is_leaf=lambda x: isinstance(x, LeafPlan)
            )
            codes = jax.tree_util.tree_map(
                lambda pr: pr[0], pairs,
                is_leaf=lambda x: isinstance(x, tuple),
            )
            scales = jax.tree_util.tree_map(
                lambda pr: pr[1], pairs,
                is_leaf=lambda x: isinstance(x, tuple),
            )
            return {"codes": codes, "scales": scales}

        sm_refresh = shard_map(
            refresh_body,
            mesh,
            in_specs=(pspecs,),
            out_specs={"codes": spec_trees["codes"],
                       "scales": spec_trees["scales"]},
            check_rep=False,
        )

        def refresh(params):
            """Re-quantize the secondary int8 store from the (updated)
            primary partition — runs once per optimizer step."""
            return sm_refresh(params)

    return CommCompression(
        quant_weights=quant_weights,
        secondary_weights=secondary_weights,
        quant_grads=quant_grads,
        block_size=block_size,
        accumulate=accumulate,
        refresh=refresh,
        hpz_pspecs=hpz_pspecs,
    )


def _hpz_spec_trees(pspecs: Any, plans: Any) -> dict[str, Any]:
    """PartitionSpec trees for the hpZ store: quantized leaves keep their
    param spec (codes AND scales concatenate along the same mesh axes);
    non-quantized leaves are dropped (None — pruned from the pytree)."""

    def keep(spec, plan):
        return spec if plan.quantize else None

    specs = jax.tree_util.tree_map(
        keep, pspecs, plans,
        is_leaf=lambda x: isinstance(x, (P, LeafPlan)),
    )
    return {"codes": specs, "scales": specs}


# ---------------------------------------------------------------------------
# HLO collective accounting (benchmarks + tests)
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

_COLLECTIVE_RE = re.compile(
    r"=\s+(?:\([^)]*\)|(?P<dtype>\w+)\[(?P<shape>[\d,]*)\][^ ]*)\s+"
    r"(?P<op>all-gather|all-reduce|reduce-scatter|all-to-all|"
    r"collective-permute)(?:-start)?\("
)
_TUPLE_SHAPE_RE = re.compile(r"(\w+)\[([\d,]*)\]")
_GROUPS_RE = re.compile(
    r"replica_groups=(?:\{(?P<explicit>[^}]*(?:\},\{[^}]*)*)\}\}|"
    r"\[(?P<iota_dims>[\d,]+)\]<=\[(?P<iota_reshape>[\d,]+)\]"
    r"(?:T\((?P<iota_perm>[\d,]+)\))?)"
)


def _parse_groups(line: str, n_devices: int) -> list[list[int]]:
    """Replica groups from an HLO instruction line — both the explicit
    ``{{0,1},{2,3}}`` form and the iota ``[2,4]<=[8]`` / ``T(...)`` form."""
    m = _GROUPS_RE.search(line)
    if not m:
        return [list(range(n_devices))]
    if m.group("explicit") is not None:
        raw = m.group("explicit")
        return [
            [int(x) for x in grp.split(",") if x.strip() != ""]
            for grp in raw.replace("{", "").split("},")
        ]
    import numpy as np

    dims = [int(x) for x in m.group("iota_dims").split(",")]
    reshape = [int(x) for x in m.group("iota_reshape").split(",")]
    ids = np.arange(int(np.prod(reshape))).reshape(reshape)
    if m.group("iota_perm"):
        ids = ids.transpose([int(x) for x in m.group("iota_perm").split(",")])
    ids = ids.reshape(-1, dims[-1]) if len(dims) > 1 else ids.reshape(1, -1)
    # v2 iota semantics: reshape the (possibly transposed) iota to `dims`;
    # the final dim indexes within a group.
    ids = ids.flatten().reshape(dims)
    return ids.reshape(-1, dims[-1]).tolist()


def _payload_bytes(line: str) -> int:
    """Total element bytes of the instruction's result (tuple-aware)."""
    head = line.split("=", 1)[1] if "=" in line else line
    head = head.split("(", 1)[0]
    total = 0
    for dtype, shape in _TUPLE_SHAPE_RE.findall(head):
        if dtype not in _DTYPE_BYTES:
            continue
        elems = 1
        for d in shape.split(","):
            if d.strip():
                elems *= int(d)
        total += elems * _DTYPE_BYTES[dtype]
    return total


def slice_of_partition(mesh_shape: dict[str, int], dcn_data: int) -> list[int]:
    """partition-id → slice-id for a hybrid mesh: the partition order is
    the row-major flattening of the mesh device array, whose outer data
    blocks are whole slices."""
    total = 1
    for v in mesh_shape.values():
        total *= v
    data = mesh_shape.get("data", 1)
    inner = total // data
    per_slice_data = data // dcn_data
    return [
        (p // inner) // per_slice_data if per_slice_data else 0
        for p in range(total)
    ]


def collective_stats(
    hlo_text: str, slice_of: Optional[list[int]] = None
) -> dict[str, Any]:
    """Wire-byte accounting over an HLO module's collectives.

    Uses the standard ring cost model per participant group of size g:
    all-gather / reduce-scatter / all-to-all move (g-1)/g of the payload,
    all-reduce 2(g-1)/g, collective-permute the full payload. A collective
    whose replica group spans devices on different slices (``slice_of``)
    is charged to ``cross_slice_bytes``; with no slice map everything is
    intra-slice.
    """
    n_devices = len(slice_of) if slice_of else 1
    ops = []
    total = 0.0
    cross = 0.0
    for line in hlo_text.splitlines():
        m = _COLLECTIVE_RE.search(line)
        if not m or "-done" in line.split("=", 1)[-1][:40]:
            continue
        op = m.group("op")
        payload = _payload_bytes(line)
        groups = _parse_groups(line, n_devices)
        g = max(len(grp) for grp in groups) if groups else 1
        if op == "all-reduce":
            wire = payload * 2 * (g - 1) / max(g, 1)
        elif op == "collective-permute":
            wire = float(payload)
        else:
            wire = payload * (g - 1) / max(g, 1)
        crossing = False
        if slice_of:
            for grp in groups:
                slices = {slice_of[d] for d in grp if d < len(slice_of)}
                if len(slices) > 1:
                    crossing = True
                    break
        total += wire
        if crossing:
            cross += wire
        ops.append({
            "op": op, "bytes": int(wire), "payload_bytes": payload,
            "group_size": g, "cross_slice": crossing,
        })
    return {
        "total_wire_bytes": int(total),
        "cross_slice_bytes": int(cross),
        "collectives": ops,
    }


def expected_volume_factors(block_size: int) -> dict[str, float]:
    """Analytic per-element wire reduction: int8 codes + fp32 per-block
    scales versus fp32 full-width (the number the docs/plan report)."""
    f = 4.0 / (1.0 + 4.0 / block_size)
    return {
        "weight_gather": f,
        "grad_cross_slice": f,
    }
