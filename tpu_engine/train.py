"""Sharded training program: optimizer, loss, and the pjit train step.

This is the in-process engine that replaces the reference's subprocess
launch of an external DeepSpeed script (``ai_engine/deepspeed_launcher.py:354``
— fire-and-forget ``Popen``). The engine *owns* the step function:

- AdamW + warmup-cosine schedule with floor (reference config blocks
  ``deepspeed_launcher.py:145-164`` — ``WarmupDecayLR`` + AdamW);
- gradient accumulation via ``lax.scan`` (reference
  ``gradient_accumulation_steps``, ``:44``);
- global-norm gradient clipping (reference ``gradient_clipping``, ``:46``);
- bf16 compute with fp32 master params — no loss scaling needed on TPU
  (the reference needs fp16 dynamic loss scaling, ``:176-183``);
- activation checkpointing via ``jax.checkpoint`` (reference ``:215-223``);
- ZeRO-stage sharding applied through NamedShardings from
  ``tpu_engine.sharding`` — gradients are reduce-scattered (stage ≥ 2) by
  constraining their sharding, optimizer state sharded (stage ≥ 1), params
  sharded (stage 3); XLA emits the all-gathers/reduce-scatters over ICI.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpu_engine import comm_compress
from tpu_engine.mesh_runtime import BATCH_AXES, MeshRuntime
from tpu_engine.models import transformer as tfm
from tpu_engine.sharding import (
    OffloadDevice,
    ShardingStage,
    TPUTrainConfig,
    dtype_of,
    grad_pspecs,
    host_memory_kind_available,
    named_shardings,
    opt_state_pspecs,
    param_pspecs,
    resolve_pipeline_schedule,
)


def make_schedule(cfg: TPUTrainConfig) -> optax.Schedule:
    """Warmup + the configured decay shape (reference WarmupDecayLR,
    ``:145-153``, generalised: cosine | linear | constant | rsqrt)."""
    warmup = max(cfg.warmup_steps, 1)
    decay_steps = max(cfg.total_steps, cfg.warmup_steps + 1)
    if cfg.lr_schedule == "cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=cfg.learning_rate, warmup_steps=warmup,
            decay_steps=decay_steps, end_value=cfg.min_lr,
        )
    warm = optax.linear_schedule(0.0, cfg.learning_rate, warmup)
    if cfg.lr_schedule == "linear":
        tail = optax.linear_schedule(
            cfg.learning_rate, cfg.min_lr, max(decay_steps - warmup, 1)
        )
    elif cfg.lr_schedule == "constant":
        tail = optax.constant_schedule(cfg.learning_rate)
    else:  # rsqrt: lr · sqrt(warmup / step) past warmup, floored at min_lr
        def tail(step):
            lr = cfg.learning_rate * jnp.sqrt(warmup / jnp.maximum(step + warmup, 1))
            return jnp.maximum(lr, cfg.min_lr)
    return optax.join_schedules([warm, tail], boundaries=[warmup])


def accumulate_grads(grad_fn, reduce_grads, params_g, params_like, batch,
                     grad_sh):
    """Gradient accumulation over ``batch`` [accum, B, S]: the masked-SFT
    global-denominator scan shared by the in-memory train step and the
    disk-tier grad step — ONE definition so the two paths' objectives
    cannot silently diverge. Returns (summed loss, summed fp32 grads)."""
    accum = batch.shape[0]
    # Batch-wide valid-target count (masked SFT targets excluded): each
    # microbatch contributes raw sums / this denominator, so the summed
    # loss and grads realise the global mean.
    denom = jnp.maximum(
        jnp.sum((batch[:, :, 1:] >= 0).astype(jnp.float32)), 1.0
    )

    def accum_body(carry, tokens):
        loss_acc, grad_acc = carry
        loss, grads = grad_fn(params_g, tokens, True, denom=denom,
                              aux_weight=1.0 / accum)
        # Stage >= 2: the constraint to fsdp shards makes XLA
        # reduce-scatter instead of all-reduce (ZeRO-2 semantics);
        # reduce_grads routes the collective through the configured
        # comm dtype, accumulation stays fp32.
        grads = reduce_grads(grads)
        grad_acc = jax.tree.map(jnp.add, grad_acc, grads)
        return (loss_acc + loss, grad_acc), None

    zero_grads = jax.tree.map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params_like
    )
    zero_grads = jax.lax.with_sharding_constraint(zero_grads, grad_sh)
    (loss, grad_sum), _ = jax.lax.scan(
        accum_body, (jnp.zeros((), jnp.float32), zero_grads), batch
    )
    return loss, grad_sum


def kernel_decay_mask(params: Any) -> Any:
    """Path-based weight-decay mask: matmul kernels and LoRA adapter
    factors decay; norm scales and embeddings do not. ndim alone cannot
    distinguish them — the stacked layout makes per-layer norm scales
    [L, D]. ONE definition, shared by the optax chain and the disk-tier
    host AdamW (their masks must never drift)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _: getattr(path[-1], "key", None) in ("kernel", "A", "B"),
        params,
    )


def make_optimizer(cfg: TPUTrainConfig) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """The configured optimizer (AdamW matches the reference's block,
    ``:156-164``; Adafactor/Lion are the TPU-era memory-efficient options).

    The learning rate is deliberately NOT baked into the transformation: the
    train step applies ``-lr`` itself, where ``lr = schedule(step) × lr_scale``
    and ``lr_scale`` lives in the train state. That lets the supervisor cut
    the LR after a divergence rollback (mechanising the reference's
    "reduce learning rate" remediation strings, ``loss_monitor.py:131-136``)
    without recompiling the step function.

    Weight decay applies only to ≥2-D kernels unless ``decay_all_params``
    (norm scales and embeddings are conventionally undecayed).
    """
    schedule = make_schedule(cfg)
    mu_dtype = dtype_of(cfg.moment_dtype) if cfg.moment_dtype is not None else None
    if cfg.optimizer == "adafactor":
        if cfg.moment_dtype is not None:
            raise ValueError(
                "moment_dtype is not supported with optimizer='adafactor' "
                "(factored statistics have no dtype knob)"
            )
        # Honor an explicitly-set beta2 as the factored-RMS decay rate;
        # otherwise keep Adafactor's conventional 0.8 (Adam's 0.95 default
        # is not a sensible factored decay).
        decay_rate = cfg.beta2 if "beta2" in cfg.model_fields_set else 0.8
        scaler = optax.scale_by_factored_rms(decay_rate=decay_rate)
    elif cfg.optimizer == "lion":
        scaler = optax.scale_by_lion(
            b1=cfg.beta1, b2=cfg.beta2, mu_dtype=mu_dtype
        )
    else:
        scaler = optax.scale_by_adam(
            b1=cfg.beta1, b2=cfg.beta2, eps=1e-8, mu_dtype=mu_dtype
        )
    decay = optax.add_decayed_weights(
        cfg.weight_decay,
        mask=None if cfg.decay_all_params else kernel_decay_mask,
    )
    tx = optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip_norm), scaler, decay
    )
    return tx, schedule


def _ce_sums(
    logits: jax.Array, tokens: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Raw next-token CE sums: (Σ log-likelihood, Σ logZ², valid count).

    Positions whose *target* token is negative are excluded (the in-band
    SFT masking convention — see ``decode_masked_tokens``). Returning sums
    lets the caller choose the normaliser — per-call mean (``lm_loss``) or
    a global valid-target count across gradient-accumulation microbatches
    (the train/eval steps), which keeps the objective the documented
    global mean rather than a mean of per-microbatch means.
    """
    targets = tokens[:, 1:]
    valid = (targets >= 0).astype(jnp.float32)
    logits = logits[:, :-1, :].astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)  # [B, S-1]
    logp = logits - logz[..., None]
    ll = jnp.take_along_axis(
        logp, jnp.maximum(targets, 0)[..., None], axis=-1
    ).squeeze(-1)
    return (
        jnp.sum(ll * valid),
        jnp.sum(jnp.square(logz) * valid),
        jnp.sum(valid),
    )


def lm_loss(
    logits: jax.Array, tokens: jax.Array, z_loss_coef: float = 0.0
) -> jax.Array:
    """Next-token cross-entropy in fp32. logits [B,S,V], tokens [B,S]:
    the mean over this call's valid targets (masked targets excluded).

    ``z_loss_coef > 0`` adds the PaLM-style logit-normaliser penalty
    ``coef·mean(log Z²)``, keeping softmax logits from drifting — the
    standard bf16-training stabiliser.
    """
    ll_sum, z_sum, n_valid = _ce_sums(logits, tokens)
    denom = jnp.maximum(n_valid, 1.0)
    loss = -ll_sum / denom
    if z_loss_coef:
        loss = loss + z_loss_coef * z_sum / denom
    return loss


def decode_masked_tokens(raw: jax.Array) -> tuple[jax.Array, jax.Array]:
    """In-band SFT loss masking: a position stored as ``-(token+1)`` is a
    real context token whose *prediction* must not be trained on (prompt
    tokens, padding). Returns (clean tokens for the forward pass, loss-view
    tokens where masked positions are ``-1`` so both loss paths skip them
    as targets). A no-op (identity, empty mask) for ordinary streams."""
    masked = raw < 0
    clean = jnp.where(masked, -raw - 1, raw)
    return clean, jnp.where(masked, -1, raw)


def _chunked_ce_sums(
    params: Any,
    hidden: jax.Array,
    tokens: jax.Array,
    model_cfg: tfm.ModelConfig,
    chunk: int,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Raw CE sums (see :func:`_ce_sums`) computed ``chunk`` sequence
    positions at a time, so the full fp32 [B, S, V] logits tensor is never
    materialised (at 1B scale that buffer plus its softmax temp is ~4 GB of
    HBM — often the difference between fitting a config and not). The chunk
    body is wrapped in ``jax.checkpoint`` so the backward pass recomputes
    each chunk's logits instead of keeping them alive.
    """
    B, S, D = hidden.shape
    n_chunks = S // chunk
    h = hidden.reshape(B, n_chunks, chunk, D).swapaxes(0, 1)  # [n, B, chunk, D]
    # Target for position i is tokens[i+1]; the final position has none.
    tgt = jnp.concatenate(
        [tokens[:, 1:], jnp.full((B, 1), -1, tokens.dtype)], axis=1
    ).reshape(B, n_chunks, chunk).swapaxes(0, 1)

    def body(acc, xs):
        hc, tc = xs
        logits = tfm.unembed(params, hc, model_cfg)
        logz = jax.nn.logsumexp(logits, axis=-1)
        logp = logits - logz[..., None]
        mask = tc >= 0
        ll = jnp.take_along_axis(
            logp, jnp.maximum(tc, 0)[..., None].astype(jnp.int32), axis=-1
        ).squeeze(-1)
        ll_sum, z_sum, n_sum = acc
        return (
            ll_sum + jnp.sum(ll * mask),
            z_sum + jnp.sum(jnp.square(logz) * mask),
            n_sum + jnp.sum(mask.astype(jnp.float32)),
        ), None

    (ll_total, z_total, n_total), _ = jax.lax.scan(
        jax.checkpoint(body),
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32),
         jnp.zeros((), jnp.float32)),
        (h, tgt),
    )
    return ll_total, z_total, n_total


def chunked_lm_loss(
    params: Any,
    hidden: jax.Array,
    tokens: jax.Array,
    model_cfg: tfm.ModelConfig,
    chunk: int,
    z_loss_coef: float = 0.0,
) -> jax.Array:
    """Chunked next-token cross-entropy — numerically identical to
    ``lm_loss(unembed(params, hidden), tokens)`` (masked targets excluded
    from the mean), with the flash-memory profile of
    :func:`_chunked_ce_sums`."""
    ll_total, z_total, n_total = _chunked_ce_sums(
        params, hidden, tokens, model_cfg, chunk
    )
    denom = jnp.maximum(n_total, 1.0)
    loss = -ll_total / denom
    if z_loss_coef:
        loss = loss + z_loss_coef * z_total / denom
    return loss


@dataclass
class TrainProgram:
    """A compiled, sharded training program bound to a mesh.

    ``init()`` creates the (sharded) train state; ``step(state, batch)`` runs
    one optimizer step over ``gradient_accumulation_steps`` microbatches.
    ``batch`` has shape [accum, global_micro_batch, seq_len] int32.
    """

    config: TPUTrainConfig
    model_config: tfm.ModelConfig
    runtime: MeshRuntime
    state_shardings: Any
    batch_sharding: NamedSharding
    init: Callable[[jax.Array], Any]
    step: Callable[[Any, jax.Array], tuple[Any, dict[str, jax.Array]]]
    # Held-out loss (no optimizer update, no MoE aux term): (state, batch) → scalar.
    eval_step: Optional[Callable[[Any, jax.Array], jax.Array]] = None
    # LoRA only: the frozen base weights and a jitted adapter→full-params
    # merge (for generation/export). None for full-parameter training.
    base_params: Any = None
    merged_params: Optional[Callable[[Any], Any]] = None
    # The RESOLVED pipeline schedule ("gpipe" | "1f1b") — config "auto"
    # is decided at build time (see build_train_program's selection rule).
    pipeline_schedule: str = "gpipe"
    # Disk-tier only: the live DiskAdamW spill store (spill_bytes(),
    # step_on_disk, masters() for export). None on in-memory programs.
    disk_store: Any = None
    # Disk-tier overlap only: joins the in-flight host walk and returns a
    # step-consistent state (params include every applied update). The
    # supervisor calls this before checkpoint saves and eval; no-op
    # (returns its argument) when nothing is in flight. None elsewhere.
    flush: Optional[Callable[[Any], Any]] = None

    @property
    def mesh(self) -> Mesh:
        return self.runtime.mesh

    def global_batch_shape(self) -> tuple[int, int, int]:
        dp = self.runtime.data_parallel_size()
        return (
            self.config.gradient_accumulation_steps,
            self.config.micro_batch_size * dp,
            self.config.seq_len,
        )

    def synthetic_batch(self, seed: int = 0) -> jax.Array:
        """Deterministic synthetic token batch (for smoke tests and benches)."""
        shape = self.global_batch_shape()
        rng = jax.random.PRNGKey(seed)
        host = jax.random.randint(rng, shape, 0, self.model_config.vocab_size, jnp.int32)
        return jax.device_put(host, self.batch_sharding)


def build_train_program(
    cfg: TPUTrainConfig,
    model_cfg: Optional[tfm.ModelConfig] = None,
    runtime: Optional[MeshRuntime] = None,
    base_params: Optional[Any] = None,
) -> TrainProgram:
    """Assemble the sharded train program for ``cfg`` on ``runtime``'s mesh.

    ``base_params`` only applies to LoRA runs (``cfg.lora_rank`` set): the
    frozen base model weights to adapt — e.g. an imported HF checkpoint
    (``tpu_engine.models.convert.from_hf_llama``). Default: deterministic
    init from ``cfg.seed``.
    """
    if model_cfg is None:
        model_cfg = tfm.MODEL_CONFIGS[cfg.model_name]
    if runtime is None:
        runtime = MeshRuntime(cfg.mesh)
    mesh = runtime.mesh
    # Attention implementation resolution:
    # - a >1 'sequence' axis forces sequence-parallel attention (GSPMD alone
    #   would all-gather the sequence dim): ring by default, or the
    #   all-to-all Ulysses formulation when requested explicitly;
    # - "auto" → the Pallas flash kernel on TPU, XLA elsewhere;
    # - explicit "xla" / "flash" / "ring" / "ulysses" is honoured.
    if runtime.axis_sizes["sequence"] > 1:
        impl = "ulysses" if cfg.attention_impl == "ulysses" else "ring"
    elif cfg.attention_impl == "auto":
        impl = "flash" if mesh.devices.flat[0].platform == "tpu" else "xla"
    else:
        impl = cfg.attention_impl
    # Flash under pipeline parallelism: the stage vmap runs with
    # spmd_axis_name="pipe" (tpu_engine/parallel/pipeline.py), whose
    # shard_map batching rule threads the pipe axis into the kernel's
    # in/out specs — the round-2 "cannot nest inside the pipeline's vmap"
    # restriction is gone.
    if model_cfg.attention_impl != impl:
        model_cfg = model_cfg.with_(attention_impl=impl)
    if cfg.sliding_window is not None and model_cfg.sliding_window != cfg.sliding_window:
        model_cfg = model_cfg.with_(sliding_window=cfg.sliding_window)
    if cfg.moe_impl is not None:
        if not model_cfg.is_moe:
            # Checked BEFORE the no-op short-circuit: moe_impl='dense'
            # on a dense model must error like 'ragged' does, not be
            # silently swallowed because it matches the default.
            raise ValueError(
                f"moe_impl={cfg.moe_impl!r} set on the dense model "
                f"{model_cfg.name!r} (no experts to dispatch)"
            )
        if model_cfg.moe_impl != cfg.moe_impl:
            model_cfg = model_cfg.with_(moe_impl=cfg.moe_impl)
    # MXU int8 quantized training (tpu_engine/quant_train.py): resolve the
    # config knobs onto the model config exactly like attention_impl —
    # every parallelism layout's loss path reads model_cfg, so the
    # quantized-dot hook reaches plain GSPMD, comm-compressed shard_map,
    # gpipe pipeline, disk tier and offload builds alike.
    if (
        model_cfg.quant_training != cfg.quant_training
        or model_cfg.quant_train_targets != tuple(cfg.quant_train_targets)
    ):
        model_cfg = model_cfg.with_(
            quant_training=cfg.quant_training,
            quant_train_targets=tuple(cfg.quant_train_targets),
        )
    if (
        model_cfg.quant_training == "int8"
        and model_cfg.is_moe
        and model_cfg.moe_impl == "ragged"
        and "moe" in model_cfg.quant_train_targets
    ):
        # Config validation sees cfg.moe_impl=None when the MODEL preset
        # carries ragged — re-check on the resolved model config.
        raise ValueError(
            "quant_training='int8' cannot quantize ragged MoE "
            "(lax.ragged_dot takes no per-channel scales); use "
            "moe_impl='dense' or drop 'moe' from quant_train_targets"
        )
    # Reject window × sequence-parallel here, at build time, rather than
    # letting the job fail at first-step trace deep inside _attention.
    if model_cfg.sliding_window and impl in ("ring", "ulysses"):
        raise ValueError(
            f"sliding_window={model_cfg.sliding_window} is not supported with "
            f"attention_impl={impl!r} (a windowed model has no use for "
            "full-sequence context parallelism); use a mesh without a "
            "sequence axis, or set sliding_window=0"
        )
    # Ragged MoE × expert parallelism: lax.ragged_dot is a primitive GSPMD
    # cannot partition over the expert dim — sharded experts must keep the
    # dense-dispatch einsum path. Reject at build, not at first trace.
    if (
        model_cfg.is_moe
        and model_cfg.moe_impl == "ragged"
        and mesh.shape.get("model", 1) > 1
    ):
        raise ValueError(
            "moe_impl='ragged' does not support expert parallelism "
            "(ragged_dot cannot shard over the expert dim); use "
            "moe_impl='dense' on meshes with a model axis"
        )
    # Mesh is threaded into the forward pass for sequence-parallel attention
    # (shard_map over the 'sequence' axis) and for the flash kernel on
    # multi-device meshes (Mosaic calls cannot be GSPMD-partitioned — the
    # kernel runs under shard_map, see transformer._attention).
    attn_mesh = (
        mesh
        if impl in ("ring", "ulysses") or (impl == "flash" and mesh.size > 1)
        else None
    )
    seq_size = runtime.axis_sizes["sequence"]
    if impl == "ulysses":
        local_heads = model_cfg.n_heads // runtime.axis_sizes["model"]
        if local_heads % seq_size != 0:
            raise ValueError(
                f"attention_impl='ulysses' needs the per-device head count "
                f"({model_cfg.n_heads} heads / model axis "
                f"{runtime.axis_sizes['model']} = {local_heads}) divisible by "
                f"the sequence axis size {seq_size}"
            )
    # ZeRO++-style comm compression (tpu_engine/comm_compress.py): the
    # grad path moves into a full-manual shard_map whose collectives are
    # explicit int8 gathers/reductions. Config validators reject most bad
    # combos; the runtime-shaped ones (resolved attention kernel, actual
    # mesh axis extents) must be re-checked here — reaching the SPMD
    # partitioner with a nested/partial-auto manual region aborts the
    # process rather than raising.
    compress = comm_compress.enabled(cfg)
    if compress:
        comm_compress.validate_runtime(cfg, runtime, model_cfg, attn_mesh=attn_mesh)

    stage = cfg.sharding_stage
    compute_dtype = cfg.compute_dtype()
    master_dtype = cfg.master_dtype()

    # Pipeline parallelism: a >1 'pipe' axis switches the step to the GPipe
    # schedule (tpu_engine/parallel/pipeline.py); the gradient-accumulation
    # microbatches become the pipeline stream.
    pipe_size = runtime.axis_sizes["pipe"]
    if pipe_size > 1 and model_cfg.n_layers % pipe_size != 0:
        raise ValueError(
            f"model n_layers={model_cfg.n_layers} must be divisible by the "
            f"pipe axis size {pipe_size}"
        )
    # Schedule auto-selection lives in sharding.resolve_pipeline_schedule
    # (one resolver shared with the launcher plan and HBM admission):
    # auto → zb at M > P when the manual-vjp schedules support the config
    # (no chunked exit loss, no quant_training custom backward, no
    # reduced-dtype grad collectives), gpipe otherwise. Measured A/B in
    # benchmarks/RESULTS.md §Pipeline.
    pipe_schedule = resolve_pipeline_schedule(cfg)
    if cfg.loss_chunk_size and cfg.seq_len % cfg.loss_chunk_size != 0:
        raise ValueError(
            f"loss_chunk_size={cfg.loss_chunk_size} must divide seq_len={cfg.seq_len}"
        )
    tfm.resolve_remat_policy(cfg.remat_policy)  # fail fast on typos
    if (
        cfg.remat_policy == "offload_dots"
        and mesh.devices.flat[0].platform != "tpu"
    ):
        raise ValueError(
            "remat_policy='offload_dots' requires TPU (the CPU SPMD "
            "partitioner cannot compile the policy's host-placement "
            "annotations)"
        )

    use_lora = cfg.lora_rank is not None
    if use_lora:
        from tpu_engine import lora as lora_mod

        lora_targets = lora_mod.validate_targets(model_cfg, cfg.lora_targets)
        if pipe_size > 1:
            raise ValueError("LoRA is not supported with pipeline parallelism")

    # Host-offloaded params (reference ZeRO-3 param CPU offload,
    # ``deepspeed_launcher.py:204-212``): the master params live in pinned
    # host memory; the forward/backward streams one layer at a time to
    # device inside the remat-wrapped scan body (weight residency stays
    # O(one layer) in both passes), and the optimizer update's param shards
    # transit device memory before the new params return to host via the
    # step's out-shardings. Fail fast on unsupported combinations rather
    # than silently ignoring the knob.
    offload_params = cfg.param_offload == OffloadDevice.HOST
    if offload_params and use_lora:
        raise ValueError(
            "param_offload is not supported with LoRA (the trainable "
            "adapters are rank-sized; offloading them saves nothing and the "
            "frozen base is better streamed via its own placement)"
        )
    if offload_params and pipe_size > 1:
        raise ValueError(
            "param_offload is not supported with pipeline parallelism "
            "(pipeline stages re-enter their layer block per microbatch; "
            "host-streaming weights per stage visit would thrash PCIe)"
        )
    if offload_params and not host_memory_kind_available(mesh):
        raise ValueError(
            "param_offload=host requires a backend with pinned_host memory "
            "support (TPU, or the JAX CPU backend)"
        )

    # Disk-tier optimizer offload (the NVMe analogue): the jitted step
    # computes + clips gradients only; masters and Adam moments live in
    # memmap spill files and a fused host AdamW applies the update
    # (tpu_engine/disk_offload.py). Config-level combos are validated by
    # TPUTrainConfig; runtime-shaped ones here.
    disk_tier = cfg.optimizer_offload == OffloadDevice.DISK
    if disk_tier and pipe_size > 1:
        raise ValueError(
            "optimizer_offload='disk' with pipeline parallelism is not "
            "supported (the host update walks the flat gradient tree)"
        )
    if (
        disk_tier
        and jax.process_count() > 1
        and cfg.sharding_stage < ShardingStage.FULL_PARTITIONING
    ):
        # Multi-host spill updates each process's ADDRESSABLE master
        # shards from the grad shards at the SAME indices — which holds
        # at ZeRO-3 (grad and param pspecs coincide). Below it, grads may
        # be reduce-scattered while params stay replicated (stage 2), and
        # per-shard pairing breaks.
        raise ValueError(
            "optimizer_offload='disk' across processes requires "
            "sharding_stage=3 (param and gradient shards must coincide "
            "per host)"
        )

    logical = tfm.logical_axes(model_cfg)

    # The *trainable* parameter space: the full model, or (LoRA) only the
    # rank-sized adapter tree — grads/optimizer state/checkpoints follow it.
    train_logical = lora_mod.lora_logical_axes(logical, lora_targets) if use_lora else logical
    p_pspecs = param_pspecs(train_logical, stage)
    g_pspecs = grad_pspecs(train_logical, stage)
    o_pspecs = opt_state_pspecs(train_logical, stage)

    param_sh = named_shardings(
        mesh, p_pspecs, memory_kind="pinned_host" if offload_params else None
    )
    # Full-model sharding: for LoRA this differs from the trainable tree's
    # (frozen base + merged exports); otherwise it IS the trainable one.
    full_param_sh = (
        named_shardings(mesh, param_pspecs(logical, stage)) if use_lora else param_sh
    )

    # The per-layer slice sharding: the stacked spec minus its leading layer
    # dimension. Used by the offload streaming hook and by the in-body
    # sharding anchor below.
    def _slice_spec(spec: P) -> P:
        parts = tuple(spec)
        return P(*parts[1:]) if parts else P()

    # Anchor each layer's sliced weights (and, through the constraint's
    # transpose, their cotangents) to their canonical shardings inside the
    # scan body. GSPMD sharding propagation through the remat-wrapped
    # backward loses the weight layout once manual (shard_map) regions —
    # the Pallas flash kernel — interrupt propagation, and the partitioner
    # then fully rematerialises (all-gathers) per-layer weights that should
    # stay sharded. One explicit constraint per slice removes the ambiguity
    # at zero cost when the layout already matches.
    # Under comm compression the loss runs inside a full-manual shard_map
    # region, where with_sharding_constraint is illegal (there is no GSPMD
    # propagation to anchor) — the explicit gathers pin every layout.
    layer_constraint = None
    if mesh.size > 1 and not compress:
        _full_layer_pspecs = (
            param_pspecs(logical, stage)["layers"] if use_lora
            else p_pspecs["layers"]
        )
        _layer_anchor_sh = named_shardings(
            mesh,
            jax.tree.map(
                _slice_spec, _full_layer_pspecs, is_leaf=lambda x: isinstance(x, P)
            ),
        )

        def layer_constraint(layer):
            return jax.lax.with_sharding_constraint(layer, _layer_anchor_sh)

    layer_stream = None
    if offload_params:
        # Per-layer pinned_host→device transfer + compute cast, applied
        # inside the scan body (tfm.remat_scan_body).
        layer_slice_sh = named_shardings(
            mesh,
            jax.tree.map(
                _slice_spec, p_pspecs["layers"], is_leaf=lambda x: isinstance(x, P)
            ),
            memory_kind="device",
        )

        def layer_stream(layer):
            moved = jax.tree.map(jax.device_put, layer, layer_slice_sh)
            return jax.tree.map(
                lambda a: a.astype(compute_dtype)
                if jnp.issubdtype(a.dtype, jnp.floating)
                else a,
                moved,
            )

        # Non-layer params (embeddings, final norm, head — O(vocab·d), a
        # sliver of the total) get an explicit on-device view per loss call:
        # XLA requires operands of one op to share a memory space, and
        # jnp.take/einsum consume these directly. Their cotangents still
        # accumulate in device space (device_put's transpose does not bounce
        # them through host).
        _nonlayer_dev_sh = {
            k: named_shardings(mesh, v, memory_kind="device")
            for k, v in p_pspecs.items()
            if k != "layers"
        }

        def _device_view(params):
            out = dict(params)
            for k, sh in _nonlayer_dev_sh.items():
                out[k] = jax.tree.map(jax.device_put, params[k], sh)
            return out
    else:
        def _device_view(params):
            return params

    if use_lora:
        if base_params is None:
            base_params = jax.jit(
                lambda rng: tfm.init_params(rng, model_cfg, dtype=master_dtype),
                out_shardings=full_param_sh,
            )(jax.random.PRNGKey(cfg.seed))
        else:
            base_params = jax.device_put(base_params, full_param_sh)

    # Optimizer-state offload: pinned host memory when the backend supports it
    # (reference CPU offload, ``deepspeed_launcher.py:197-203``).
    opt_memory_kind = None
    if cfg.optimizer_offload == OffloadDevice.HOST and host_memory_kind_available(mesh):
        opt_memory_kind = "pinned_host"
    opt_leaf_sh = named_shardings(mesh, o_pspecs, memory_kind=opt_memory_kind)
    grad_sh = named_shardings(mesh, g_pspecs)
    replicated = NamedSharding(mesh, P())

    tx, schedule = make_optimizer(cfg)

    def init_fn(rng: jax.Array) -> dict[str, Any]:
        if use_lora:
            params = lora_mod.init_lora_params(
                rng, model_cfg, cfg.lora_rank, lora_targets, dtype=master_dtype
            )
        else:
            params = tfm.init_params(rng, model_cfg, dtype=master_dtype)
        opt_state = tx.init(params)
        return {
            "params": params,
            "opt_state": opt_state,
            "step": jnp.zeros((), jnp.int32),
            "lr_scale": jnp.ones((), jnp.float32),
        }

    # Optimizer-state sharding tree: leaves shaped like params take the
    # opt pspecs; everything else (counts, schedule state, Adafactor's
    # factored row/col statistics — param-pathed but differently shaped)
    # replicates.
    def _opt_state_shardings(opt_state_shape, param_shapes) -> Any:
        flat_param_sh = {id_path: sh for id_path, sh in _path_leaves(opt_leaf_sh)}
        flat_param_shape = {
            id_path: leaf.shape for id_path, leaf in _path_leaves(param_shapes)
        }

        def assign(path, leaf):
            # Leaves inside the opt state that mirror a param (mu/nu) carry
            # the param's path as a suffix; match on path AND shape (a
            # factored statistic shares the path but not the shape).
            for p_path, sh in flat_param_sh.items():
                if _path_endswith(path, p_path):
                    if getattr(leaf, "shape", None) == flat_param_shape.get(p_path):
                        return sh
                    return replicated
            return replicated

        return _tree_map_with_path(assign, opt_state_shape)

    state_shape = jax.eval_shape(init_fn, jax.random.PRNGKey(0))
    state_shardings = {
        "params": param_sh,
        "opt_state": _opt_state_shardings(state_shape["opt_state"], state_shape["params"]),
        "step": replicated,
        "lr_scale": replicated,
    }

    opt_sh_tree = state_shardings["opt_state"]

    def _device_kinds(sh_tree):
        """The same sharding specs with the default (device) memory kind."""
        return jax.tree.map(
            lambda sh: NamedSharding(mesh, sh.spec),
            sh_tree,
            is_leaf=lambda x: isinstance(x, NamedSharding),
        )

    # Initialise with device memory kinds, then place offloaded subtrees in
    # pinned host memory with a one-time device_put outside jit: mixed-kind
    # out-shardings on constant outputs trip the SPMD partitioner's
    # placement-annotation handling (observed on the CPU backend), and init
    # runs once — the transfer is free relative to compile.
    has_host_kinds = offload_params or opt_memory_kind is not None
    if has_host_kinds:
        _jit_init = jax.jit(init_fn, out_shardings=_device_kinds(state_shardings))

        def jit_init(rng):
            return jax.device_put(_jit_init(rng), state_shardings)
    else:
        jit_init = jax.jit(init_fn, out_shardings=state_shardings)

    seq_ax = "sequence" if runtime.axis_sizes["sequence"] > 1 else None
    batch_sharding = NamedSharding(mesh, P(None, BATCH_AXES, seq_ax))

    def loss_fn(params, raw_tokens, include_aux: bool = True, lora_params=None,
                denom=None, aux_weight: float = 1.0):
        """Masked LM loss for one microbatch.

        ``denom=None`` → this microbatch's own valid-target mean. With a
        ``denom`` (the batch-wide valid count), returns raw sums divided by
        it, so summing over microbatches yields the *global* valid-target
        mean — not a mean of per-microbatch means, which would up-weight
        tokens in sparsely-supervised (heavily masked) microbatches.
        ``aux_weight`` scales the MoE router term (1/accum when summing).
        """
        # In-band SFT masking: -(t+1) positions are context-only (no loss).
        tokens, loss_tokens = decode_masked_tokens(raw_tokens)
        params = _device_view(params)  # no-op unless param_offload
        hidden, aux = tfm.forward_hidden_and_aux(
            params,
            tokens,
            model_cfg,
            compute_dtype=compute_dtype,
            remat=cfg.activation_checkpointing,
            remat_policy=cfg.remat_policy,
            mesh=attn_mesh,
            lora=lora_params,
            lora_scale=(cfg.lora_alpha / cfg.lora_rank) if use_lora else 1.0,
            layer_stream=layer_stream,
            layer_constraint=layer_constraint,
        )
        # include_aux gates the training-only regularisers (MoE aux, z-loss)
        # so eval_step reports pure cross-entropy.
        z_coef = cfg.z_loss_coef if include_aux else 0.0
        if cfg.loss_chunk_size:
            ll_sum, z_sum, n_valid = _chunked_ce_sums(
                params, hidden, loss_tokens, model_cfg, cfg.loss_chunk_size
            )
        else:
            ll_sum, z_sum, n_valid = _ce_sums(
                tfm.unembed(params, hidden, model_cfg), loss_tokens
            )
        d = jnp.maximum(n_valid, 1.0) if denom is None else denom
        loss = -ll_sum / d
        if z_coef:
            loss = loss + z_coef * z_sum / d
        if model_cfg.is_moe and include_aux:
            loss = loss + aux_weight * model_cfg.router_aux_coef * aux
        return loss

    if use_lora:
        # Trainable space = adapters, applied activation-side inside each
        # projection (h@A@B — never a full ΔW, so cotangents stay
        # rank-sized). The frozen base enters the compiled step as captured
        # constants.
        def train_loss_fn(adapter_params, tokens, include_aux: bool = True,
                          denom=None, aux_weight: float = 1.0):
            return loss_fn(base_params, tokens, include_aux,
                           lora_params=adapter_params, denom=denom,
                           aux_weight=aux_weight)
    else:
        train_loss_fn = loss_fn

    grad_fn = jax.value_and_grad(train_loss_fn)

    # Compressed gradient path: one full-manual shard_map per microbatch.
    # Inside it ``train_loss_fn`` sees locally-sharded tokens and the
    # gathered (dequantized) params, and its raw-sums/global-denom form
    # makes the per-device losses sum to exactly the GSPMD objective.
    compression = None
    if compress:
        compression = comm_compress.build(
            mesh=mesh,
            loss_fn=train_loss_fn,
            pspecs=p_pspecs,
            abs_params=state_shape["params"],
            grad_sh=grad_sh,
            data_size=runtime.axis_sizes["data"],
            fsdp_size=runtime.axis_sizes["fsdp"],
            dcn_data=cfg.mesh.dcn_data,
            quant_weights=cfg.comm_quant_weights,
            secondary_weights=cfg.comm_secondary_weights,
            quant_grads=cfg.comm_quant_grads,
            block_size=cfg.comm_quant_block_size,
            dtype=compute_dtype,
        )
        if compression.refresh is not None:
            # hpZ: the secondary int8 store rides the train state so the
            # steady-state step never re-quantizes (and restores resume
            # with a consistent replica via init/refresh).
            hpz_sh = jax.tree.map(
                lambda spec: NamedSharding(mesh, spec),
                compression.hpz_pspecs,
                is_leaf=lambda x: isinstance(x, P),
            )
            state_shardings = {**state_shardings, "hpz": hpz_sh}
            _base_init = init_fn

            def init_fn(rng: jax.Array) -> dict[str, Any]:
                state = _base_init(rng)
                state["hpz"] = compression.refresh(state["params"])
                return state

            # compress excludes every host-memory-kind combo, so the
            # simple jit path is always the one being replaced here.
            jit_init = jax.jit(init_fn, out_shardings=state_shardings)

    # ---- pipelined loss (pipe axis > 1): one forward over all microbatches,
    # streamed through the stages; autodiff gives the reverse pipeline. ----
    if pipe_size > 1:
        from tpu_engine.parallel.pipeline import pipeline_apply, stage_layer_stack

        def _staged_spec(spec: P) -> P:
            parts = tuple(spec)
            return P(parts[0] if parts else None, None, *parts[1:])

        staged_sh = named_shardings(
            mesh,
            jax.tree.map(_staged_spec, p_pspecs["layers"], is_leaf=lambda x: isinstance(x, P)),
        )
        buf_sh = NamedSharding(mesh, P("pipe", BATCH_AXES, seq_ax))

        def _pipe_prologue(raw_batch):
            """Shared GPipe/1F1B front half: in-band SFT mask decode,
            positions, staged (cast, pipe-sharded) layer stack, and the
            batch-wide valid-target denominator — ONE place so the two
            schedules' objectives cannot silently diverge. Returns
            (batch, loss_batch, positions, staged_builder, denom)."""
            batch, loss_batch = decode_masked_tokens(raw_batch)
            B, S = batch.shape[1], batch.shape[2]
            positions = jnp.broadcast_to(
                jnp.arange(S, dtype=jnp.int32)[None, :], (B, S)
            )

            def staged_of(p):
                staged = stage_layer_stack(
                    tfm.cast_layer_stack(p, compute_dtype), pipe_size,
                    model_cfg.n_layers,
                )
                return jax.lax.with_sharding_constraint(staged, staged_sh)

            denom = jnp.maximum(
                jnp.sum((loss_batch[:, :, 1:] >= 0).astype(jnp.float32)), 1.0
            )
            return batch, loss_batch, positions, staged_of, denom

        def pipe_loss_fn(params, raw_batch, include_aux: bool = True):
            batch, loss_batch, positions, staged_of, denom = _pipe_prologue(
                raw_batch
            )
            # positions also feed learned absolute embeddings (gpt2 family).
            x_mb = tfm.embed_tokens(params, batch, compute_dtype,
                                    positions=positions,
                                    cfg=model_cfg)  # [M, B, S, D]
            staged = staged_of(params)
            outputs, aux_mean = pipeline_apply(
                staged,
                x_mb,
                model_cfg,
                positions=positions,
                mesh=attn_mesh,
                remat=cfg.activation_checkpointing,
                remat_policy=cfg.remat_policy,
                buf_sharding=buf_sh,
                layer_constraint=layer_constraint,
            )

            z_coef = cfg.z_loss_coef if include_aux else 0.0

            def loss_body(acc, xs):
                out, toks = xs
                if cfg.loss_chunk_size:
                    ll, zz, _ = _chunked_ce_sums(
                        params, out, toks, model_cfg, cfg.loss_chunk_size
                    )
                else:
                    ll, zz, _ = _ce_sums(tfm.unembed(params, out, model_cfg), toks)
                return acc + (-ll + z_coef * zz), None

            body = jax.checkpoint(loss_body) if cfg.activation_checkpointing else loss_body
            loss_sum, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), (outputs, loss_batch))
            loss = loss_sum / denom
            if model_cfg.is_moe and include_aux:
                loss = loss + model_cfg.router_aux_coef * aux_mean
            return loss

        pipe_grad_fn = jax.value_and_grad(pipe_loss_fn)

        if pipe_schedule in ("1f1b", "zb"):
            # Manual per-stage-vjp schedules: O(P) in-flight stage inputs
            # instead of GPipe-by-autodiff's O(M + P) saved boundary
            # buffers. "1f1b" interleaves one forward and one combined
            # backward per tick (tpu_engine/parallel/pipeline_1f1b.py);
            # "zb" additionally splits the drain backwards into B/W phases
            # and retires deferred weight gradients in lanes 1f1b burns as
            # masked bubble compute (tpu_engine/parallel/pipeline_zb.py).
            # Both take the same arguments and return the same gradient
            # pieces — the schedules are pure reorderings of the same
            # per-stage vjps. Gradients are assembled manually — no
            # jax.grad above this.
            if cfg.loss_chunk_size:
                raise ValueError(
                    f"loss_chunk_size is not supported with "
                    f"pipeline_schedule={pipe_schedule!r} (the exit loss "
                    "runs inside the schedule's scan)"
                )
            from tpu_engine.parallel.pipeline_1f1b import pipeline_1f1b_grads
            from tpu_engine.parallel.pipeline_zb import pipeline_zb_grads

            schedule_grads = (
                pipeline_zb_grads if pipe_schedule == "zb"
                else pipeline_1f1b_grads
            )

            def pipe_grad_fn(params, raw_batch):  # noqa: F811 — manual-vjp override
                batch, loss_batch, positions, staged_of, denom = (
                    _pipe_prologue(raw_batch)
                )
                accum = batch.shape[0]
                x_mb, embed_vjp = jax.vjp(
                    lambda p: tfm.embed_tokens(
                        p, batch, compute_dtype, positions=positions,
                        cfg=model_cfg,
                    ),
                    params,
                )
                staged = staged_of(params)
                z_coef = cfg.z_loss_coef
                outer_sub = {k: v for k, v in params.items() if k != "layers"}

                def exit_scalar(outer, y, toks):
                    ll, zz, _ = _ce_sums(tfm.unembed(outer, y, model_cfg), toks)
                    return (-ll + z_coef * zz) / denom

                def exit_fn(y, toks):
                    val, vjp = jax.vjp(
                        lambda o, yy: exit_scalar(o, yy, toks), outer_sub, y
                    )
                    d_outer, dy = vjp(jnp.ones((), jnp.float32))
                    return val, dy, d_outer

                outer_zero = jax.tree.map(
                    lambda a: jnp.zeros(a.shape, jnp.float32), outer_sub
                )
                aux_cot = (
                    model_cfg.router_aux_coef / (model_cfg.n_layers * accum)
                    if model_cfg.is_moe else 0.0
                )
                loss_sum, aux_sum, dstaged, d_outer, dx_mb = schedule_grads(
                    staged, x_mb, loss_batch, model_cfg,
                    positions=positions, exit_fn=exit_fn,
                    outer_grad_zero=outer_zero, mesh=attn_mesh,
                    remat=cfg.activation_checkpointing,
                    remat_policy=cfg.remat_policy,
                    buf_sharding=buf_sh, aux_cotangent=aux_cot,
                    layer_constraint=layer_constraint,
                )
                # Assemble the full gradient tree: embedding cotangent from
                # dx_mb, stage grads reshaped back to the [L, ...] stack
                # (the bf16 cast's vjp is the cast back), and the exit-side
                # outer grads (final norm, head, tied embedding).
                (grads,) = embed_vjp(dx_mb)
                grads = jax.tree.map(lambda a: a.astype(jnp.float32), grads)
                L = model_cfg.n_layers
                d_layers = jax.tree.map(
                    lambda a: a.reshape((L,) + a.shape[2:]), dstaged
                )
                grads["layers"] = jax.tree.map(
                    lambda a, b: a + b, grads["layers"], d_layers
                )
                for k, v in d_outer.items():
                    grads[k] = jax.tree.map(
                        lambda a, b: a + b.astype(jnp.float32), grads[k], v
                    )
                loss = loss_sum
                if model_cfg.is_moe:
                    loss = loss + model_cfg.router_aux_coef * aux_sum / (
                        model_cfg.n_layers * accum
                    )
                return loss, grads

    # Gradient collective dtype (reference ``communication_data_type``,
    # ``deepspeed_launcher.py:60-62,167-169``). A post-hoc cast cannot move
    # the collective's dtype — XLA inserts the grad reduction inside the
    # backward pass, upstream of anything applied to ``grad_fn``'s result.
    # The mechanism that works (and is what DeepSpeed's fp16-grads mode
    # actually does) is differentiating with respect to the *compute-dtype*
    # params: the whole cotangent chain, including the reduction point,
    # then carries the comm dtype; the upcast to fp32 happens once, after
    # the sharding constraint, for accumulation and the master update.
    # Config validation guarantees comm dtype == compute dtype (or fp32).
    comm_dtype = (
        dtype_of(cfg.grad_allreduce_dtype)
        if cfg.grad_allreduce_dtype is not None
        else None
    )
    reduced_comm = comm_dtype is not None and comm_dtype != jnp.float32
    if reduced_comm and pipe_size > 1 and pipe_schedule in ("1f1b", "zb"):
        raise ValueError(
            f"grad_allreduce_dtype with pipeline_schedule="
            f"{pipe_schedule!r} is not supported: the manual-vjp schedule "
            "accumulates gradients in fp32 inside its scan, so the "
            "reduced-dtype collective the option exists for would never "
            "materialise (use 'gpipe', or drop grad_allreduce_dtype)"
        )
    if reduced_comm and offload_params:
        raise ValueError(
            "grad_allreduce_dtype with param_offload=host is not supported: "
            "offloaded layers already stream in the compute dtype, and the "
            "host-resident master tree cannot be re-cast in device code"
        )

    def _cast_for_grad(params):
        if not reduced_comm:
            return params
        return jax.tree.map(
            lambda p: p.astype(comm_dtype)
            if jnp.issubdtype(p.dtype, jnp.floating)
            else p,
            params,
        )

    def _reduce_grads(grads):
        # Grads arrive in the comm dtype (reduced_comm) or fp32; the
        # constraint pins where XLA materialises the reduce-scatter /
        # all-reduce (stage >= 2: sharded — ZeRO-2 semantics).
        grads = jax.lax.with_sharding_constraint(grads, grad_sh)
        return jax.tree.map(lambda g: g.astype(jnp.float32), grads)

    def train_step(state, batch):
        params = state["params"]
        params_g = _cast_for_grad(params)

        if pipe_size > 1:
            loss, grads = pipe_grad_fn(params_g, batch)
            grads = _reduce_grads(grads)
        elif compression is not None:
            # Step-deterministic key for qgZ's stochastic rounding (and
            # restart-reproducible: derived from seed + step, not a
            # threaded RNG state).
            qkey = jax.random.fold_in(
                jax.random.PRNGKey(cfg.seed), state["step"]
            )
            loss, grads = compression.accumulate(
                params_g, state.get("hpz"), batch, qkey
            )
        else:
            loss, grads = accumulate_grads(
                grad_fn, _reduce_grads, params_g, params, batch, grad_sh
            )
        grad_norm = optax.global_norm(grads)

        # Offloaded subtrees stream through device memory for the update
        # math (the per-device transient is the 1/N shard — reference
        # "streamed to device inside the update", ``deepspeed_launcher.py:
        # 197-203``) and are placed back in pinned host memory explicitly,
        # so the step's out-shardings see already-host-resident values.
        opt_in = state["opt_state"]
        if opt_memory_kind is not None:
            opt_in = jax.tree.map(jax.device_put, opt_in, _device_kinds(opt_sh_tree))
        params_upd = params
        if offload_params:
            params_upd = jax.tree.map(jax.device_put, params, _device_kinds(param_sh))

        lr = schedule(state["step"]).astype(jnp.float32) * state["lr_scale"]
        updates, new_opt_state = tx.update(grads, opt_in, params_upd)
        updates = jax.tree.map(lambda u: (-lr * u).astype(u.dtype), updates)
        new_params = optax.apply_updates(params_upd, updates)
        new_state = {
            "params": new_params,
            "opt_state": new_opt_state,
            "step": state["step"] + 1,
            "lr_scale": state["lr_scale"],
        }
        if compression is not None and compression.refresh is not None:
            # hpZ refresh: re-quantize the secondary store from the
            # just-updated primary partition, once per optimizer step.
            new_state["hpz"] = compression.refresh(new_params)
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "learning_rate": lr,
            "step": new_state["step"],
        }
        return new_state, metrics

    # Host-kind out-shardings are the production (TPU) path: the updated
    # offloaded subtrees materialise straight into pinned host memory. The
    # CPU backend's SPMD partitioner cannot compile placement-annotated
    # outputs (RET_CHECK on the annotation it puts on replicated scalars)
    # and silently drops in-body host placements — so off-TPU the step
    # computes with device-kind outputs and the offloaded subtrees are
    # re-placed on host with a device_put *outside* jit. Semantically
    # identical; the CPU path exists so the 8-virtual-device test mesh can
    # exercise offloaded configs at all.
    on_tpu = mesh.devices.flat[0].platform == "tpu"
    if has_host_kinds and not on_tpu:
        _jit_step = jax.jit(
            train_step,
            in_shardings=(state_shardings, batch_sharding),
            out_shardings=None,
        )

        def jit_step(state, batch):
            new_state, metrics = _jit_step(state, batch)
            return jax.device_put(new_state, state_shardings), metrics
    else:
        jit_step = jax.jit(
            train_step,
            in_shardings=(state_shardings, batch_sharding),
            out_shardings=(state_shardings, None),
            donate_argnums=(0,),
        )

    def eval_step(state, batch):
        """Held-out loss over one [accum, B, S] batch — pure cross-entropy
        (no MoE aux term, so exp(loss) is an honest perplexity), no update."""
        params = state["params"]
        if pipe_size > 1:
            return pipe_loss_fn(params, batch, include_aux=False)

        denom = jnp.maximum(
            jnp.sum((batch[:, :, 1:] >= 0).astype(jnp.float32)), 1.0
        )

        def body(acc, tokens):
            return acc + train_loss_fn(params, tokens, include_aux=False,
                                       denom=denom), None

        loss_sum, _ = jax.lax.scan(body, jnp.zeros((), jnp.float32), batch)
        return loss_sum

    jit_eval = jax.jit(
        eval_step, in_shardings=(state_shardings, batch_sharding), out_shardings=None
    )

    merged_fn = None
    if use_lora:
        # Merged tree in the compute dtype: generation casts to it anyway,
        # and at bf16 the one-off merged copy is half the master-dtype size.
        merged_fn = jax.jit(
            lambda adapters: jax.tree.map(
                lambda a: a.astype(compute_dtype),
                lora_mod.merge_lora(
                    base_params, adapters, cfg.lora_alpha, cfg.lora_rank
                ),
            ),
            out_shardings=full_param_sh,
        )

    if disk_tier:
        return _assemble_disk_tier(
            cfg, model_cfg, runtime, mesh, schedule, grad_fn,
            _cast_for_grad, _reduce_grads, eval_step,
            param_sh=param_sh, grad_sh=grad_sh, replicated=replicated,
            batch_sharding=batch_sharding,
            compute_dtype=compute_dtype, master_dtype=master_dtype,
            pipe_schedule=pipe_schedule,
        )

    return TrainProgram(
        config=cfg,
        model_config=model_cfg,
        runtime=runtime,
        state_shardings=state_shardings,
        batch_sharding=batch_sharding,
        init=jit_init,
        step=jit_step,
        eval_step=jit_eval,
        base_params=base_params if use_lora else None,
        merged_params=merged_fn,
        pipeline_schedule=pipe_schedule,
    )


def _assemble_disk_tier(
    cfg, model_cfg, runtime, mesh, schedule, grad_fn,
    _cast_for_grad, _reduce_grads, eval_step, *,
    param_sh, grad_sh, replicated, batch_sharding,
    compute_dtype, master_dtype, pipe_schedule,
) -> TrainProgram:
    """Disk-tier (NVMe-analogue) program: device = forward/backward/clip
    on compute-dtype params; host = fused AdamW over memmap spill slabs
    (``tpu_engine/disk_offload.py``). The train state carries NO
    optimizer state and the params at COMPUTE dtype — HBM holds exactly
    what the forward pass reads.

    Rollback/restore semantics: the spill persists its applied-step
    count; when the incoming state's step disagrees (supervisor rollback,
    a restart that restored an older checkpoint, or a fresh run reusing
    a spill dir), masters reseed from the restored params with the Adam
    moments ZEROED and the bias-correction counter reset — exactly the
    behavior of loading a checkpoint without optimizer state. Where a
    master still rounds to the incoming compute-dtype value it is kept
    at full precision (see ``reseed_masters`` ``cast_dtype``).
    """
    import numpy as np

    from tpu_engine import disk_offload as dsk

    state_shardings = {
        "params": param_sh,
        "step": replicated,
        "lr_scale": replicated,
    }
    flat_param_sh = dsk.flatten_with_paths(param_sh)

    def _to_compute(params):
        return jax.tree.map(
            lambda a: a.astype(compute_dtype)
            if jnp.issubdtype(a.dtype, jnp.floating) else a,
            params,
        )

    def _decay_mask(params):
        if cfg.decay_all_params:
            return jax.tree.map(lambda _: True, params)
        return kernel_decay_mask(params)

    # Each process spills under its own subdirectory — slab files hold
    # only the shards ITS devices own (single-process runs keep the flat
    # directory, so existing spills still re-attach).
    spill_dir = cfg.optimizer_spill_dir
    if jax.process_count() > 1:
        spill_dir = os.path.join(spill_dir, f"proc{jax.process_index()}")
        if jax.process_index() == 0 and os.path.isdir(cfg.optimizer_spill_dir):
            # A dir previously used single-process holds FLAT slab files
            # this multi-host run will never touch — clean them (proc 0
            # only; they are stale for this layout either way).
            for f in os.listdir(cfg.optimizer_spill_dir):
                if f.endswith(".f32") or f == "disk_adamw.json":
                    try:
                        os.remove(os.path.join(cfg.optimizer_spill_dir, f))
                    except OSError:
                        pass

    def _all_hosts(flag: bool) -> bool:
        """Cross-process consensus on a local boolean (True only when
        EVERY process reports True). Attach/reseed decisions must agree
        cluster-wide: a host that attaches warm moments while another
        reseeds fresh would stitch a global tree from divergent
        trajectories — silently."""
        if jax.process_count() == 1:
            return flag
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([bool(flag)])
        )
        return bool(np.all(flags))
    store = dsk.DiskAdamW(
        spill_dir, b1=cfg.beta1, b2=cfg.beta2, eps=1e-8,
        weight_decay=cfg.weight_decay,
    )

    _abs_params = jax.eval_shape(
        lambda r: tfm.init_params(r, model_cfg, dtype=master_dtype),
        jax.random.PRNGKey(0),
    )
    _abs_flat = dsk.flatten_with_paths(_abs_params)
    _flat_mask_by_leaf = dsk.flatten_with_paths(_decay_mask(_abs_params))

    # ---- shard-granular slab layout (multi-host / multi-device) ----------
    # Slabs are keyed per unique addressable shard of each leaf:
    # ``path`` when one full-leaf shard (replicated or single device —
    # backward-compatible with existing spills), ``path@a-b_c-d…``
    # otherwise. AdamW is elementwise, so every shard updates
    # independently; no cross-shard (or cross-host) communication exists
    # in the walk at all.

    def _suffix(shape, index) -> str:
        if not index or all(
            (s.start in (None, 0)) and (s.stop in (None, dim))
            for s, dim in zip(index, shape)
        ):
            return ""
        return "@" + "_".join(
            f"{0 if s.start is None else s.start}-"
            f"{dim if s.stop is None else s.stop}"
            for s, dim in zip(index, shape)
        )

    def _index_shape(shape, index):
        if not index:
            return tuple(shape)
        return tuple(
            (dim if s.stop is None else s.stop)
            - (0 if s.start is None else s.start)
            for s, dim in zip(index, shape)
        )

    # key → (leaf path, suffix, index slices, [devices holding the shard])
    _key_info: dict[str, tuple[str, str, tuple, list]] = {}
    for _path, _abs in _abs_flat.items():
        _shape = tuple(_abs.shape)
        _by_sig: dict[str, tuple] = {}
        for _dev, _idx in flat_param_sh[_path] \
                .addressable_devices_indices_map(_shape).items():
            _sig = _suffix(_shape, _idx)
            if _sig in _by_sig:
                _by_sig[_sig][1].append(_dev)
            else:
                _by_sig[_sig] = (_idx, [_dev])
        for _sig, (_idx, _devs) in sorted(_by_sig.items()):
            _key_info[_path + _sig] = (_path, _sig, tuple(_idx), _devs)

    _flat_shapes = {
        key: _index_shape(tuple(_abs_flat[path].shape), idx)
        for key, (path, _, idx, _) in _key_info.items()
    }
    _flat_mask = {
        key: _flat_mask_by_leaf[path]
        for key, (path, _, _, _) in _key_info.items()
    }
    _leaf_shapes = {p: tuple(a.shape) for p, a in _abs_flat.items()}

    def _shard_host(arr, path: str, sig: str, idx: tuple) -> np.ndarray:
        """The block of ``arr`` matching a slab key's index signature, as
        a host fp32 array. Prefers a matching addressable shard (no
        cross-device traffic); when the array's own sharding differs from
        the slab layout (e.g. stage-2 grads are fsdp-sharded while the
        params the slabs mirror are replicated), a single process falls
        back to materialising the leaf and slicing — cross-process that
        mismatch is rejected at build time."""
        shape = tuple(arr.shape)
        for s in arr.addressable_shards:
            if _suffix(shape, s.index) == sig:
                return np.asarray(jax.device_get(s.data), np.float32)
        if jax.process_count() == 1:
            return np.asarray(jax.device_get(arr), np.float32)[
                tuple(idx) if idx else ()
            ]
        raise ValueError(
            f"leaf {path}: no addressable shard matches slab key suffix "
            f"{sig!r} (sharding changed under the spill?)"
        )

    def _leaf_fetcher(params):
        """key → fp32 host block, ONE shard at a time — the full fp32
        tree must never be host-resident at once (the tier targets models
        where it cannot be)."""
        flat = dsk.flatten_with_paths(params)

        def fetch(key):
            path, sig, idx, _ = _key_info[key]
            return _shard_host(flat[path], path, sig, idx)

        return fetch

    def _grad_fetchers(grads):
        """key → deferred host fetch of the matching gradient shard (the
        walk's prefetch thread calls these one ahead of the update)."""
        flat = dsk.flatten_with_paths(grads)
        return {
            key: (lambda a=flat[path], p=path, s=sig, i=idx:
                  _shard_host(a, p, s, i))
            for key, (path, sig, idx, _) in _key_info.items()
        }

    def _make_uploader():
        return dsk.AsyncShardUploader(
            {key: (path, devs) for key, (path, _, _, devs) in _key_info.items()},
            _leaf_shapes, flat_param_sh, compute_dtype,
        )

    def _ensure_store(params) -> bool:
        """Attach if a clean matching spill exists ON EVERY HOST
        (shape-only check — no device fetch); otherwise ALL hosts seed a
        fresh spill from ``params`` (one host's lost/torn spill forces a
        cluster-wide reseed — mixed warm/fresh moments would silently
        diverge the stitched global state)."""
        attached = bool(store.slabs) or store.try_attach(_flat_shapes, _flat_mask)
        if _all_hosts(attached):
            return True
        return store.initialize(_leaf_fetcher(params), _flat_mask,
                                shapes=_flat_shapes, force_fresh=True)

    def _params_from_masters():
        # Shard-at-a-time through the SAME uploader the update walk uses
        # (one implementation of the block-stitch): copy one master slab,
        # cast, device_put to the shard's devices, assemble global arrays.
        up = _make_uploader()
        try:
            for key, slab in store.slabs.items():
                up.emit(key, slab.master)
        finally:
            up.close()
        return dsk.unflatten_like(_abs_params, up.result())

    def disk_init(rng):
        def pure(r):
            return {
                "params": _to_compute(
                    tfm.init_params(r, model_cfg, dtype=master_dtype)
                ),
                "step": jnp.zeros((), jnp.int32),
                "lr_scale": jnp.ones((), jnp.float32),
            }

        if isinstance(rng, jax.core.Tracer):
            # eval_shape path (the supervisor derives state shapes by
            # tracing init) — no host I/O under a tracer.
            return pure(rng)
        if _all_hosts(
            bool(store.slabs) or store.try_attach(_flat_shapes, _flat_mask)
        ):
            # A matching clean spill exists on EVERY host: its masters
            # are the truth (warm restart) — no throwaway random init,
            # no D2H fetch.
            params = _params_from_masters()
        else:
            masters = jax.jit(
                lambda r: tfm.init_params(r, model_cfg, dtype=master_dtype),
                out_shardings=param_sh,
            )(rng)
            # force_fresh: a host that COULD attach must still reseed
            # when any peer cannot (cluster-wide agreement).
            store.initialize(_leaf_fetcher(masters), _flat_mask,
                             shapes=_flat_shapes, force_fresh=True)
            params = jax.jit(
                _to_compute, donate_argnums=(0,), out_shardings=param_sh
            )(masters)
        _verified_step[0] = None  # init/attach: first step re-checks
        return {
            "params": params,
            "step": jax.device_put(jnp.zeros((), jnp.int32), replicated),
            "lr_scale": jax.device_put(jnp.ones((), jnp.float32), replicated),
        }

    def grad_step(state, batch):
        params_g = _cast_for_grad(state["params"])
        loss, grads = accumulate_grads(
            grad_fn, _reduce_grads, params_g, state["params"], batch, grad_sh
        )
        grad_norm = optax.global_norm(grads)
        # optax.clip_by_global_norm semantics: scale = min(1, clip/norm).
        scale = jnp.minimum(
            1.0, cfg.grad_clip_norm / jnp.maximum(grad_norm, 1e-12)
        )
        grads = jax.tree.map(lambda g: g * scale, grads)
        lr = schedule(state["step"]).astype(jnp.float32) * state["lr_scale"]
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "learning_rate": lr,
            "step": state["step"] + 1,
        }
        return grads, metrics

    jit_grad = jax.jit(
        grad_step,
        in_shardings=(state_shardings, batch_sharding),
        out_shardings=(grad_sh, None),
    )

    # Delayed-parameter-update overlap (``disk_update_overlap``): the one
    # in-flight host walk. Only the engine thread touches this.
    pending: list[Any] = [None]

    # Discontinuity-consensus cache: the ``_all_hosts`` call below is a
    # blocking cross-host collective, and running it EVERY step would
    # serialise each disk step behind the slowest host (it used to).
    # Continuity only changes at attach/init, checkpoint restore, or
    # rollback — all of which surface as an incoming step that does NOT
    # continue the last step this process applied or verified, so the
    # steady state skips the collective entirely after the first agreeing
    # step. The cache is deterministic (every host sees the same
    # ``state.step`` sequence and the same walk outcomes), so all hosts
    # take the same skip/check branch and the collective stays aligned.
    _verified_step = [None]
    store.consensus_checks = 0  # observability: actual collective runs

    def _check_discontinuity(state, t):
        # ONE discontinuity check covering every path — lazy attach,
        # warm init-attach, in-process rollback, restored checkpoint at
        # a different step: the spill's applied-step must be exactly the
        # incoming state's step, else the state's weights are the truth
        # and the trajectory restarts from them (masters reseeded,
        # moments zeroed, bias-correction counter reset — the LR
        # schedule keeps the state's step).
        if _verified_step[0] == t - 1:
            return  # steady state: this process applied step t-1 itself
        store.consensus_checks += 1
        needs = store.step_on_disk is not None and store.step_on_disk != t - 1
        if not _all_hosts(not needs):
            # Any ONE host's discontinuity reseeds every host — moments
            # must restart together or the stitched state mixes Adam
            # bias-correction counters. cast_dtype: where a master still
            # rounds to exactly the incoming (compute-dtype-truncated)
            # value, keep the fp32 master — a reseed from a state that
            # never diverged (warm re-attach without a restored step
            # counter) must not shave master precision to bf16.
            store.reseed_masters(
                _leaf_fetcher(state["params"]), step=t - 1,
                cast_dtype=compute_dtype,
            )
        _verified_step[0] = t - 1

    def disk_step(state, batch):
        grads, metrics = jit_grad(state, batch)
        t = int(state["step"]) + 1
        if not store.slabs:
            _ensure_store(state["params"])  # restored-without-init path
            _verified_step[0] = None  # fresh attach: re-establish consensus
        _check_discontinuity(state, t)
        uploader = _make_uploader()
        try:
            store.update(
                _grad_fetchers(grads),
                float(metrics["learning_rate"]), t, uploader.emit,
            )
        finally:
            uploader.close()  # never leak the worker on an update failure
        new_params = dsk.unflatten_like(state["params"], uploader.result())
        _verified_step[0] = t  # this process applied t: continuity holds
        new_state = {
            "params": new_params,
            "step": metrics["step"],
            "lr_scale": state["lr_scale"],
        }
        return new_state, metrics

    def disk_step_overlap(state, batch):
        """Delayed parameter update (ZeRO-Offload DPU analogue): dispatch
        this step's forward/backward on the CURRENT (one-walk-stale)
        params, join the PREVIOUS step's host walk, then hand this step's
        gradients to a fresh background walk and return. Device compute
        for step N+1 and the host AdamW for step N run concurrently —
        step time approaches max(device, host) instead of their sum.
        Tradeoff (documented on the config field): gradients are computed
        on params missing the in-flight update — one step of staleness,
        pinned exactly by ``test_disk_offload.py::test_overlap_semantics``.
        """
        # Async dispatch: the device starts on this step's grads NOW and
        # crunches while the host joins the previous walk below.
        grads, metrics = jit_grad(state, batch)
        t = int(state["step"]) + 1
        if not store.slabs:
            _ensure_store(state["params"])
            _verified_step[0] = None  # fresh attach: re-establish consensus
        prev = pending[0]
        pending[0] = None
        prev_leaves = None
        if prev is not None:
            if prev.step == int(state["step"]):
                prev_leaves = prev.join()       # host walk N ∥ device grads N+1
            else:
                # The incoming state is NOT the continuation of the
                # in-flight walk (supervisor rollback / restored
                # checkpoint): the walk's trajectory is abandoned.
                prev.discard()
        _check_discontinuity(state, t)
        # float(lr) blocks until jit_grad is done — by now the previous
        # walk has already been joined, so nothing serialises behind it.
        pending[0] = dsk.WalkInFlight(
            store, _grad_fetchers(grads),
            float(metrics["learning_rate"]), t, _make_uploader(),
        )
        # The in-flight walk will apply t (a failure raises at the next
        # join and aborts the run — there is no silent-miss path).
        _verified_step[0] = t
        params = state["params"] if prev_leaves is None else \
            dsk.unflatten_like(state["params"], prev_leaves)
        new_state = {
            "params": params,   # stale by exactly the in-flight walk
            "step": metrics["step"],
            "lr_scale": state["lr_scale"],
        }
        return new_state, metrics

    def disk_flush(state):
        """Join the in-flight walk and return a step-consistent state
        (its ``step`` already counts the walk's update; only the params
        were lagging). No-op when nothing is in flight."""
        walk = pending[0]
        if walk is None:
            return state
        pending[0] = None
        if walk.step != int(state["step"]):
            walk.discard()  # flushing a state the walk does not continue
            return state
        leaves = walk.join()
        return {
            **state,
            "params": dsk.unflatten_like(state["params"], leaves),
        }

    jit_eval = jax.jit(
        eval_step,
        in_shardings=(state_shardings, batch_sharding),
        out_shardings=None,
    )

    return TrainProgram(
        config=cfg,
        model_config=model_cfg,
        runtime=runtime,
        state_shardings=state_shardings,
        batch_sharding=batch_sharding,
        init=disk_init,
        step=disk_step_overlap if cfg.disk_update_overlap else disk_step,
        eval_step=jit_eval,
        pipeline_schedule=pipe_schedule,
        disk_store=store,
        flush=disk_flush if cfg.disk_update_overlap else None,
    )


# ---------------------------------------------------------------------------
# Pytree path helpers (match optimizer-state leaves to their param shardings)
# ---------------------------------------------------------------------------


def _path_leaves(tree):
    flat, _ = jax.tree_util.tree_flatten_with_path(
        tree, is_leaf=lambda x: isinstance(x, NamedSharding)
    )
    return [(tuple(_key_str(k) for k in path), leaf) for path, leaf in flat]


def _key_str(k) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "name"):
        return str(k.name)
    if hasattr(k, "idx"):
        return str(k.idx)
    return str(k)


def _path_endswith(path: tuple[str, ...], suffix: tuple[str, ...]) -> bool:
    return len(path) >= len(suffix) and path[-len(suffix):] == suffix


def _tree_map_with_path(fn, tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    leaves = [fn(tuple(_key_str(k) for k in path), leaf) for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, leaves)
