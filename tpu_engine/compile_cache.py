"""Persistent XLA compilation cache.

SURVEY.md §7 hard part (c): MTTR < 90 s auto-resume needs warm-start
compilation — a preempted worker that restarts must not pay the full
multi-minute XLA compile again. JAX's persistent compilation cache keys
compiled executables by (HLO, compile options, libtpu version) and reuses
them across processes, so the supervisor's resume path costs restore + one
*cache hit* instead of restore + cold compile.

Enabled by the worker CLI and by every supervised job
(``tpu_engine/supervisor.py``); idempotent and safe to call at any point —
JAX consults the cache per compilation, not at backend init.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger(__name__)

DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "tpu_engine", "xla-cache"
)

_enabled_dir: Optional[str] = None


def enable_compilation_cache(
    cache_dir: Optional[str] = None, force: bool = False
) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir`` (idempotent).

    Resolution order: explicit argument > ``JAX_COMPILATION_CACHE_DIR`` env
    (set by infra/tpu-jobset.yaml onto a persistent volume) > the local
    default. Returns the directory in use, or None when skipped. The
    thresholds are lowered so the train step (which takes seconds to
    minutes to compile) always qualifies, while trivial sub-second compiles
    stay out of the cache.

    NOT enabled on the CPU backend unless ``force``: XLA:CPU AOT reloads
    are compiled with machine-feature sets that do not round-trip
    (``cpu_aot_loader`` warns of possible SIGILL, and hard interpreter
    crashes were observed in the CPU test mesh). The cache's purpose —
    warm TPU restarts — does not apply there anyway.
    """
    global _enabled_dir
    d = (
        cache_dir
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or DEFAULT_CACHE_DIR
    )
    if _enabled_dir == d:
        return d
    import jax

    if not force and jax.default_backend() == "cpu":
        log.info("CPU backend: persistent compilation cache not enabled")
        return None

    os.makedirs(d, exist_ok=True)
    prev = getattr(jax.config, "jax_compilation_cache_dir", None)
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    if prev != d:
        # Two latches make a plain config update insufficient: the cache
        # object binds to the directory it was first used with, and
        # ``is_cache_used`` memoizes a cache-OFF verdict at the process's
        # FIRST compile — so enabling after any earlier jit (telemetry
        # probe, eval_shape warm-up) would silently cache nothing.
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            log.warning("could not reset jax compilation cache singleton")
    _enabled_dir = d
    log.info("persistent XLA compilation cache: %s", d)
    return d


def cache_dir_in_use() -> Optional[str]:
    """The directory the cache was enabled with, or None."""
    return _enabled_dir
