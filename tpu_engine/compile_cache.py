"""Persistent XLA compilation cache.

SURVEY.md §7 hard part (c): MTTR < 90 s auto-resume needs warm-start
compilation — a preempted worker that restarts must not pay the full
multi-minute XLA compile again. JAX's persistent compilation cache keys
compiled executables by (HLO, compile options, libtpu version) and reuses
them across processes, so the supervisor's resume path costs restore + one
*cache hit* instead of restore + cold compile.

Enabled by the worker CLI and by every supervised job
(``tpu_engine/supervisor.py``); idempotent and safe to call at any point —
JAX consults the cache per compilation, not at backend init. The fleet-level
warm/cold bookkeeping over this cache lives in
``tpu_engine/compile_index.py`` — enabling here attaches that index's JSON
sidecar to the cache dir.
"""

from __future__ import annotations

import logging
import os
from dataclasses import dataclass
from typing import Optional

log = logging.getLogger(__name__)

DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "tpu_engine", "xla-cache"
)

_enabled_dir: Optional[str] = None


@dataclass(frozen=True, eq=False)
class CacheEnableResult:
    """Structured outcome of :func:`enable_compilation_cache`.

    ``dir`` is the directory the cache is active with after this call (None
    when nothing is enabled); ``changed`` means this call touched JAX config
    (first enable, or a re-point); ``repointed`` flags the explicit
    already-enabled → different-explicit-dir transition; ``skipped_reason``
    names why the call was a no-op (currently only ``"cpu-backend"``).

    Compares equal to the directory string (and to None when nothing is
    enabled) so existing ``enable_compilation_cache(d) == d`` call sites
    keep working; truthiness is "the cache is enabled".
    """

    dir: Optional[str]
    enabled: bool
    changed: bool = False
    repointed: bool = False
    skipped_reason: Optional[str] = None

    def __bool__(self) -> bool:
        return self.enabled

    def __eq__(self, other: object) -> bool:
        if isinstance(other, CacheEnableResult):
            return (self.dir, self.enabled, self.changed, self.repointed,
                    self.skipped_reason) == (
                        other.dir, other.enabled, other.changed,
                        other.repointed, other.skipped_reason)
        if other is None or isinstance(other, str):
            return self.dir == other
        return NotImplemented


def enable_compilation_cache(
    cache_dir: Optional[str] = None, force: bool = False
) -> CacheEnableResult:
    """Point JAX's persistent compilation cache at ``cache_dir`` (idempotent).

    Resolution order: explicit argument > ``JAX_COMPILATION_CACHE_DIR`` env
    (set by infra/tpu-jobset.yaml onto a persistent volume) > the local
    default. Returns a :class:`CacheEnableResult`. The thresholds are
    lowered so the train step (which takes seconds to minutes to compile)
    always qualifies, while trivial sub-second compiles stay out of the
    cache.

    Calling again with a *different* explicit directory is an explicit
    **re-point**: the cache singleton is reset (so executables land in the
    new directory, not the first one), the transition is logged, and the
    result carries ``repointed=True``. Entries already written to the old
    directory are not migrated.

    NOT enabled on the CPU backend unless ``force``: XLA:CPU AOT reloads
    are compiled with machine-feature sets that do not round-trip
    (``cpu_aot_loader`` warns of possible SIGILL, and hard interpreter
    crashes were observed in the CPU test mesh). The cache's purpose —
    warm TPU restarts — does not apply there anyway.
    """
    global _enabled_dir
    d = (
        cache_dir
        or os.environ.get("JAX_COMPILATION_CACHE_DIR")
        or DEFAULT_CACHE_DIR
    )
    if _enabled_dir == d:
        return CacheEnableResult(dir=d, enabled=True, changed=False)
    import jax

    if not force and jax.default_backend() == "cpu":
        log.info("CPU backend: persistent compilation cache not enabled")
        return CacheEnableResult(
            dir=_enabled_dir,
            enabled=_enabled_dir is not None,
            skipped_reason="cpu-backend",
        )

    repointed = _enabled_dir is not None
    if repointed:
        log.warning(
            "persistent XLA compilation cache re-pointed: %s -> %s "
            "(existing entries are not migrated)",
            _enabled_dir, d,
        )
    os.makedirs(d, exist_ok=True)
    prev = getattr(jax.config, "jax_compilation_cache_dir", None)
    jax.config.update("jax_compilation_cache_dir", d)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    if prev != d:
        # Two latches make a plain config update insufficient: the cache
        # object binds to the directory it was first used with, and
        # ``is_cache_used`` memoizes a cache-OFF verdict at the process's
        # FIRST compile — so enabling after any earlier jit (telemetry
        # probe, eval_shape warm-up) would silently cache nothing.
        try:
            from jax._src import compilation_cache as _cc

            _cc.reset_cache()
        except Exception:
            log.warning("could not reset jax compilation cache singleton")
    _enabled_dir = d
    log.info("persistent XLA compilation cache: %s", d)
    # The fleet compile index persists its layout-keyed sidecar next to the
    # executables it describes — warmth then survives the process.
    try:
        from tpu_engine.compile_index import get_index

        get_index().attach_dir(d)
    except Exception:  # the index must never break cache enablement
        log.debug("compile index sidecar attach failed", exc_info=True)
    return CacheEnableResult(dir=d, enabled=True, changed=True, repointed=repointed)


def cache_dir_in_use() -> Optional[str]:
    """The directory the cache was enabled with, or None."""
    return _enabled_dir
