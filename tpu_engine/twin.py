"""Trace-replay digital twin: one virtual-clock fleet engine.

Every fleet policy in this repo used to be evaluated on one of three
bespoke virtual-clock harnesses (``benchmarks/scheduler_sim.py``,
``benchmarks/serving_fleet_sim.py``, ``benchmarks/chaos.py``) that could
not ingest what the flight recorder actually captured. This module is the
shared engine those sims are now thin scenario definitions over, plus the
piece none of them had: replaying a *recorded* run.

Three layers:

- **Trace ingestion** (:func:`read_recorder_jsonl`,
  :class:`ReplayWorkload`): parse flight-recorder JSONL (spans, events,
  explicit timestamps, parent links) into a replayable workload — job
  submissions with their observed priorities/durations, serving request
  arrivals, fault timelines — tolerating rotated files, a torn partial
  last line, and unknown ``schema_version`` lines (skipped and counted,
  never raised mid-replay). Composable synthetic generators
  (:func:`bursty_arrivals`, :func:`diurnal_arrivals`,
  :func:`heavy_tail_prefill_arrivals`) cover scenarios never yet
  observed; the bursty generator reproduces the legacy sims' seeded
  traces draw-for-draw.

- **Replay core** (:class:`TwinEngine` + the scenario lanes): drives the
  real control-plane components through their existing
  explicit-timestamp APIs under one :class:`VirtualClock` —
  ``HeteroRebalancer``, ``ReplicaAutoscaler``/``FleetRouter``,
  ``CompileCacheIndex``, ``GoodputLedger``/``SLOBurnRateAlerter`` — and
  records the replayed run back onto a fresh :class:`FlightRecorder`
  with deterministic span ids, so every twin run is itself
  Perfetto-exportable and byte-for-byte diffable against the source
  trace (or a previous replay).

- **A/B scorecard** (:func:`ab_scorecard`,
  :func:`default_policy_scorecard`): N policy variants over the same
  ingested trace, one JSON artifact with per-variant goodput
  decomposition, queue-wait, MTTR and SLO-burn deltas against the first
  (baseline) variant.

Health counters for the ``tpu_engine_twin_*`` Prometheus families live
in module state (:func:`twin_stats`); ``POST /api/v1/twin/replay`` is
the dry-run HTTP entry (``backend/routers/twin.py``); ``bench.py`` and
``tools/bench_sentinel.py`` share :func:`twin_bench_line`.
"""

from __future__ import annotations

import collections
import dataclasses
import heapq
import json
import math
import os
import random
import tempfile
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from tpu_engine import hetero as hetero_mod
from tpu_engine import historian as historian_mod
from tpu_engine.autopilot import AutopilotConfig, FleetAutopilot
from tpu_engine.compile_index import CompileCacheIndex
from tpu_engine.faults import FaultInjector, FaultKind, FaultPlan, FaultSpec
from tpu_engine.goodput import CATEGORIES, GoodputLedger, SLOBurnRateAlerter
from tpu_engine.tracing import SCHEMA_VERSION, FlightRecorder

__all__ = [
    "VirtualClock",
    "deterministic_ids",
    "read_recorder_jsonl",
    "ReplayWorkload",
    "TwinEngine",
    "decomposition_diff",
    "bursty_arrivals",
    "diurnal_arrivals",
    "heavy_tail_prefill_arrivals",
    "TrainTwinParams",
    "HeteroTwinParams",
    "ServingTwinParams",
    "chip_fault_timeline",
    "replay_self_heal",
    "replay_die_and_restart",
    "goodput_lane",
    "host_slow_plan",
    "replay_hetero",
    "run_hetero_ab",
    "SlotReplica",
    "run_open_loop",
    "replay_serving_fleet",
    "serving_metrics",
    "percentile",
    "warm_admission_lane",
    "ab_scorecard",
    "default_policy_scorecard",
    "admission_policy_scorecard",
    "replay_fidelity",
    "twin_bench_line",
    "historian_lane",
    "historian_bench_line",
    "replay_autopilot",
    "autopilot_lane",
    "autopilot_bench_line",
    "ScaleLaneParams",
    "scale_lane",
    "ctl_scale_profile",
    "ctl_scale_bench_line",
    "PrefixPlaneLaneParams",
    "prefix_plane_lane",
    "prefix_plane_ab",
    "prefix_plane_bench_line",
    "ReshardLaneParams",
    "replay_reshard_resume",
    "reshard_roundtrip_report",
    "reshard_migration_report",
    "reshard_ab",
    "reshard_bench_line",
    "CtlCrashLaneParams",
    "ctl_crash_lane",
    "ctl_crash_ab",
    "ctl_crash_bench_line",
    "twin_stats",
]


# -- virtual clock / deterministic ids ----------------------------------------


class VirtualClock:
    """A callable simulated clock: pass as any component's ``clock=``."""

    __slots__ = ("t",)

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def now(self) -> float:
        return self.t

    def advance(self, dt: float) -> float:
        self.t += float(dt)
        return self.t

    def set(self, t: float) -> float:
        self.t = float(t)
        return self.t


def deterministic_ids(prefix: str = "twin") -> Callable[[], str]:
    """A counter-based id factory for :class:`FlightRecorder` — replays
    get byte-stable span/event ids instead of uuid4."""
    n = 0

    def _next() -> str:
        nonlocal n
        n += 1
        return f"{prefix}-{n:08d}"

    return _next


# -- module health counters (tpu_engine_twin_* Prometheus families) -----------

SKIP_REASONS = ("torn_tail", "parse_error", "unknown_schema", "unknown_record")

_STATS_LOCK = threading.Lock()
_STATS: Dict[str, Any] = {
    "replays_total": 0,
    "ab_runs_total": 0,
    "ingest_files_total": 0,
    "ingest_lines_total": 0,
    "ingest_skipped_lines_total": 0,
    "ingest_skipped_by_reason": {r: 0 for r in SKIP_REASONS},
    "replayed_spans_total": 0,
    "replayed_events_total": 0,
    "fleet_seconds_total": 0.0,
    "cpu_seconds_total": 0.0,
    "last_fleet_seconds_per_cpu_second": 0.0,
}


def twin_stats() -> Dict[str, Any]:
    """Snapshot of the twin's monotonic health counters."""
    with _STATS_LOCK:
        out = dict(_STATS)
        out["ingest_skipped_by_reason"] = dict(_STATS["ingest_skipped_by_reason"])
    return out


def _reset_stats_for_tests() -> None:
    with _STATS_LOCK:
        for k, v in list(_STATS.items()):
            if isinstance(v, dict):
                _STATS[k] = {r: 0 for r in SKIP_REASONS}
            else:
                _STATS[k] = 0 if isinstance(v, int) else 0.0


def _bump(**deltas: float) -> None:
    with _STATS_LOCK:
        for k, v in deltas.items():
            _STATS[k] += v


# -- trace ingestion ----------------------------------------------------------


def read_recorder_jsonl(path: str) -> Tuple[List[dict], Dict[str, Any]]:
    """Read flight-recorder JSONL at ``path`` (plus its rotated ``.1``
    generation, oldest first) into record dicts.

    Hardened for mid-write capture: an undecodable *final* line of the
    live file is a torn tail (the recorder was mid-append), any other bad
    line is a parse error, a ``schema_version`` above this build's
    :data:`SCHEMA_VERSION` is an unknown future format — all are skipped
    and counted (``twin_ingest_skipped_lines_total``), never raised."""
    files = [p for p in (path + ".1", path) if os.path.exists(p)]
    records: List[dict] = []
    stats: Dict[str, Any] = {
        "files": len(files),
        "lines": 0,
        "accepted": 0,
        "skipped": 0,
        "skipped_by_reason": {},
        "legacy_lines": 0,
        "schema_version": SCHEMA_VERSION,
    }

    def _skip(reason: str) -> None:
        stats["skipped"] += 1
        by = stats["skipped_by_reason"]
        by[reason] = by.get(reason, 0) + 1

    for fi, fp in enumerate(files):
        with open(fp, encoding="utf-8", errors="replace") as f:
            lines = f.read().split("\n")
        if lines and lines[-1] == "":
            lines.pop()
        for li, line in enumerate(lines):
            if not line.strip():
                continue
            stats["lines"] += 1
            # Only the live file's final line can be a torn partial write;
            # rotation happens on line boundaries.
            torn_candidate = fi == len(files) - 1 and li == len(lines) - 1
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                _skip("torn_tail" if torn_candidate else "parse_error")
                continue
            if not isinstance(rec, dict):
                _skip("parse_error")
                continue
            sv = rec.get("schema_version")
            if sv is None:
                stats["legacy_lines"] += 1  # pre-versioning trace: accepted
            elif not isinstance(sv, int) or sv < 1 or sv > SCHEMA_VERSION:
                _skip("unknown_schema")
                continue
            if rec.get("record") not in ("span", "event"):
                _skip("unknown_record")
                continue
            records.append(rec)
            stats["accepted"] += 1

    with _STATS_LOCK:
        _STATS["ingest_files_total"] += stats["files"]
        _STATS["ingest_lines_total"] += stats["lines"]
        _STATS["ingest_skipped_lines_total"] += stats["skipped"]
        for r, n in stats["skipped_by_reason"].items():
            by = _STATS["ingest_skipped_by_reason"]
            by[r] = by.get(r, 0) + n
    return records, stats


class ReplayWorkload:
    """Ingested recorder records plus the reconstructed fleet views:
    job submissions (kind ``job`` roots + their ``submit`` events),
    serving request arrivals (kind ``request``), and the fault timeline
    (kind ``fault`` spans/events)."""

    def __init__(self, records: List[dict], ingest_stats: Optional[dict] = None):
        self.records = list(records)
        self.ingest = dict(ingest_stats or {})
        self.spans = [r for r in self.records if r.get("record") == "span"]
        self.events = [r for r in self.records if r.get("record") == "event"]
        submit_by_trace: Dict[Any, dict] = {}
        self.faults: List[dict] = []
        self.requests: List[dict] = []
        self.jobs: List[dict] = []
        for e in self.events:
            if e.get("name") == "submit" and e.get("kind") == "scheduler":
                submit_by_trace.setdefault(e.get("trace_id"), e)
            elif e.get("kind") == "fault":
                self.faults.append({
                    "t": float(e.get("ts") or 0.0),
                    "name": e.get("name"),
                    "trace_id": e.get("trace_id"),
                    "attrs": dict(e.get("attrs") or {}),
                })
        for s in self.spans:
            kind = s.get("kind")
            attrs = dict(s.get("attrs") or {})
            if kind == "job":
                sub = submit_by_trace.get(s.get("trace_id"))
                sub_attrs = dict((sub or {}).get("attrs") or {})
                self.jobs.append({
                    "trace_id": s.get("trace_id"),
                    "name": s.get("name"),
                    "t0": float(s.get("t0") or 0.0),
                    "t1": s.get("t1"),
                    "duration_s": s.get("duration_s"),
                    "priority": attrs.get("priority") or sub_attrs.get("priority"),
                    "workload": attrs.get("workload") or sub_attrs.get("workload"),
                    "gang": attrs.get("n_chips") or attrs.get("gang")
                    or attrs.get("full_gang"),
                    "attrs": attrs,
                })
            elif kind == "fault":
                self.faults.append({
                    "t": float(s.get("t0") or 0.0),
                    "name": s.get("name"),
                    "trace_id": s.get("trace_id"),
                    "attrs": attrs,
                })
            elif kind == "request":
                self.requests.append({
                    "t": float(s.get("t0") or 0.0),
                    "name": s.get("name"),
                    "trace_id": s.get("trace_id"),
                    "duration_s": s.get("duration_s"),
                    "attrs": attrs,
                })
        self.faults.sort(key=lambda f: f["t"])
        self.requests.sort(key=lambda r: r["t"])
        self.jobs.sort(key=lambda j: (j["t0"], str(j["name"])))

    @classmethod
    def from_jsonl(cls, path: str) -> "ReplayWorkload":
        records, stats = read_recorder_jsonl(path)
        return cls(records, stats)

    @property
    def t_range(self) -> Tuple[float, float]:
        lo, hi = math.inf, -math.inf
        for s in self.spans:
            t0 = float(s.get("t0") or 0.0)
            t1 = float(s.get("t1") if s.get("t1") is not None else t0)
            lo, hi = min(lo, t0), max(hi, t1)
        for e in self.events:
            ts = float(e.get("ts") or 0.0)
            lo, hi = min(lo, ts), max(hi, ts)
        if lo is math.inf:
            return 0.0, 0.0
        return lo, hi


# -- replay core --------------------------------------------------------------


class TwinEngine:
    """Replays a :class:`ReplayWorkload` onto a fresh deterministic-id
    :class:`FlightRecorder` under one :class:`VirtualClock`, then accounts
    every job trace through the real :class:`GoodputLedger`.

    The replayed recorder (``self.recorder``) carries the same spans,
    events, timestamps and parent links as the source run, so it exports
    the same Perfetto document and decomposes to the same goodput
    categories — the diffability contract the determinism tests gate."""

    def __init__(
        self,
        max_spans: int = 65536,
        max_events: int = 65536,
        id_prefix: str = "twin",
    ):
        self.max_spans = int(max_spans)
        self.max_events = int(max_events)
        self.id_prefix = id_prefix
        self.clock = VirtualClock(0.0)
        self.recorder: Optional[FlightRecorder] = None

    def replay(
        self,
        workload: ReplayWorkload,
        bucket_s: float = 60.0,
        history_buckets: int = 256,
    ) -> Dict[str, Any]:
        t_cpu0 = time.perf_counter()
        self.clock = VirtualClock(0.0)
        # Stream-order ids: record i gets "<prefix>-<i+1>". Every replayed
        # record consumes exactly one factory call (span records always
        # pass an explicit trace_id below, so new_trace_id never fires),
        # which lets parent links be remapped without a dry run.
        n = len(workload.records)
        new_ids = {
            r["span_id"]: f"{self.id_prefix}-{i + 1:08d}"
            for i, r in enumerate(workload.records)
            if r.get("record") == "span" and r.get("span_id")
        }
        counter = {"n": 0}

        def _factory() -> str:
            counter["n"] += 1
            return f"{self.id_prefix}-{counter['n']:08d}"

        rec = FlightRecorder(
            max_spans=self.max_spans,
            max_events=self.max_events,
            clock=self.clock,
            id_factory=_factory,
        )
        self.recorder = rec
        spans_n = events_n = 0
        for r in workload.records:
            parent = r.get("parent_id")
            parent = new_ids.get(parent, parent)
            attrs = dict(r.get("attrs") or {})
            if r.get("record") == "span":
                t0 = float(r.get("t0") or 0.0)
                t1 = r.get("t1")
                t1 = t0 if t1 is None else float(t1)
                self.clock.t = max(self.clock.t, t1)
                rec.record_span(
                    str(r.get("name") or "span"),
                    kind=str(r.get("kind") or "span"),
                    trace_id=r.get("trace_id") or f"{self.id_prefix}-orphan",
                    parent=parent,
                    t0=t0,
                    t1=t1,
                    attrs=attrs,
                )
                spans_n += 1
            else:
                ts = float(r.get("ts") or 0.0)
                self.clock.t = max(self.clock.t, ts)
                rec.event(
                    str(r.get("name") or "event"),
                    kind=str(r.get("kind") or "event"),
                    trace_id=r.get("trace_id"),
                    parent=parent,
                    ts=ts,
                    attrs=attrs,
                )
                events_n += 1

        # Account every job trace through the REAL ledger — the same
        # decomposition live submissions get.
        ledger = GoodputLedger(
            clock=self.clock, bucket_s=bucket_s, history_buckets=history_buckets
        )
        traces: Dict[str, Any] = {}
        for job in workload.jobs:
            tid = job["trace_id"]
            if tid is None or tid in traces:
                continue
            gang = job.get("gang")
            ledger.track(
                tid,
                tenant=str(job["attrs"].get("submitter") or "twin"),
                workload=str(job.get("workload") or "training"),
                full_gang=int(gang) if gang else None,
            )
            now = job["t1"] if job["t1"] is not None else self.clock.t
            d = ledger.finalize(rec, tid, now=float(now))
            if d is None:
                continue
            traces[tid] = {
                "root": job["name"],
                "wall_s": d["wall_s"],
                "goodput_fraction": d["goodput_fraction"],
                "categories": dict(d["categories"]),
                "compile_split": dict(d.get("compile_split") or {}),
            }
        cpu_s = max(time.perf_counter() - t_cpu0, 1e-9)
        t_lo, t_hi = workload.t_range
        fleet_s = max(0.0, t_hi - t_lo)
        speedup = fleet_s / cpu_s
        _bump(
            replays_total=1,
            replayed_spans_total=spans_n,
            replayed_events_total=events_n,
            fleet_seconds_total=fleet_s,
            cpu_seconds_total=cpu_s,
        )
        with _STATS_LOCK:
            _STATS["last_fleet_seconds_per_cpu_second"] = round(speedup, 1)
        return {
            "spans_replayed": spans_n,
            "events_replayed": events_n,
            "records": n,
            "traces": traces,
            "ingest": dict(workload.ingest),
            "fleet_seconds": round(fleet_s, 3),
            "cpu_seconds": round(cpu_s, 6),
            "fleet_seconds_per_cpu_second": round(speedup, 1),
        }


def decomposition_diff(
    source: Dict[str, float], replayed: Dict[str, float], wall_s: float
) -> Dict[str, Any]:
    """Per-category |source − replay| as % of the wall clock (the
    fidelity acceptance metric: every category within 1%)."""
    per = {
        c: round(
            abs(float(source.get(c, 0.0)) - float(replayed.get(c, 0.0)))
            / max(wall_s, 1e-9)
            * 100.0,
            4,
        )
        for c in CATEGORIES
    }
    return {
        "per_category_pct": per,
        "max_error_pct": max(per.values()) if per else 0.0,
    }


# -- synthetic traffic generators ---------------------------------------------


def _open_loop_arrivals(
    rng: random.Random,
    rate_fn: Callable[[float], float],
    duration_s: float,
    n_prefixes: int,
    prefix_len: int,
    mean_new_tokens: float,
    min_new_tokens: int,
    prefill_fn: Optional[Callable[[random.Random], float]],
) -> List[dict]:
    """Shared open-loop arrival core. The draw order (interarrival →
    prefix → [prefill] → n_new) matches the legacy sims' generators
    exactly, so seeded traces reproduce byte-for-byte."""
    out: List[dict] = []
    t = 0.0
    while t < duration_s:
        t += rng.expovariate(rate_fn(t))
        if t >= duration_s:
            break
        pid = rng.randrange(n_prefixes)
        # Prompt = shared prefix tokens + a unique tail (router affinity
        # keys on the first tokens; the tail keeps requests distinct).
        prompt = [pid * prefix_len + i for i in range(prefix_len)]
        prompt.append(10_000 + len(out))
        req: Dict[str, Any] = {"t": t, "prefix_id": pid, "prompt": prompt}
        if prefill_fn is not None:
            req["prefill_units"] = prefill_fn(rng)
        req["n_new"] = max(
            min_new_tokens, int(rng.expovariate(1.0 / mean_new_tokens))
        )
        out.append(req)
    return out


def bursty_arrivals(
    seed: int,
    duration_s: float = 600.0,
    base_rps: float = 1.0,
    burst_rps: float = 14.0,
    burst_every_s: float = 120.0,
    burst_len_s: float = 35.0,
    n_prefixes: int = 4,
    prefix_len: int = 32,
    mean_new_tokens: float = 96,
    min_new_tokens: int = 8,
    prefill_mean_s: Optional[float] = None,
    prefill_min_s: float = 0.3,
    seed_offset: int = 0,
) -> List[dict]:
    """Seeded bursty open-loop arrivals: [{t, prefix_id, prompt, n_new}]
    (+ ``prefill_units`` seconds when ``prefill_mean_s`` is set)."""
    rng = random.Random(seed + seed_offset)

    def rate(t: float) -> float:
        return burst_rps if (t % burst_every_s) < burst_len_s else base_rps

    prefill = None
    if prefill_mean_s is not None:
        def prefill(r: random.Random) -> float:
            return max(prefill_min_s, r.expovariate(1.0 / prefill_mean_s))

    return _open_loop_arrivals(
        rng, rate, duration_s, n_prefixes, prefix_len,
        mean_new_tokens, min_new_tokens, prefill,
    )


def diurnal_arrivals(
    seed: int,
    duration_s: float = 600.0,
    trough_rps: float = 0.5,
    peak_rps: float = 4.0,
    period_s: float = 300.0,
    n_prefixes: int = 4,
    prefix_len: int = 32,
    mean_new_tokens: float = 96,
    min_new_tokens: int = 8,
) -> List[dict]:
    """Sinusoidal day/night arrival rate between trough and peak."""
    rng = random.Random(seed)

    def rate(t: float) -> float:
        phase = 0.5 * (1.0 - math.cos(2.0 * math.pi * t / period_s))
        return trough_rps + (peak_rps - trough_rps) * phase

    return _open_loop_arrivals(
        rng, rate, duration_s, n_prefixes, prefix_len,
        mean_new_tokens, min_new_tokens, None,
    )


def heavy_tail_prefill_arrivals(
    seed: int,
    duration_s: float = 600.0,
    base_rps: float = 0.4,
    burst_rps: float = 3.0,
    burst_every_s: float = 120.0,
    burst_len_s: float = 35.0,
    alpha: float = 1.5,
    prefill_min_s: float = 0.3,
    n_prefixes: int = 4,
    prefix_len: int = 32,
    mean_new_tokens: float = 96,
    min_new_tokens: int = 8,
) -> List[dict]:
    """Bursty arrivals whose prefill cost is Pareto(``alpha``) — the
    heavy-tail regime where a single huge prompt can wedge a symmetric
    replica's slot pool."""
    rng = random.Random(seed)

    def rate(t: float) -> float:
        return burst_rps if (t % burst_every_s) < burst_len_s else base_rps

    def prefill(r: random.Random) -> float:
        return prefill_min_s * r.paretovariate(alpha)

    return _open_loop_arrivals(
        rng, rate, duration_s, n_prefixes, prefix_len,
        mean_new_tokens, min_new_tokens, prefill,
    )


# -- training lane: self-heal vs die-and-restart under chip faults ------------


@dataclasses.dataclass(frozen=True)
class TrainTwinParams:
    """The chaos training-gang scenario knobs (defaults = the seeded
    benchmark the sentinel gates; ``benchmarks/chaos.py`` re-exports
    them as module constants)."""

    n_chips: int = 8
    model_axis: int = 2
    min_chips: int = 2
    total_steps: int = 1_000
    step_time_s: float = 0.5
    ckpt_interval_steps: int = 100
    ckpt_save_s: float = 5.0
    resume_admit_s: float = 5.0
    cold_compile_s: float = 15.0
    warm_compile_s: float = 1.5
    die_detect_s: float = 30.0
    die_restart_s: float = 120.0
    chip_recovery_base_s: float = 60.0
    chip_recovery_per_duration_s: float = 30.0
    layout_prefix: str = "chaos"


def chip_fault_timeline(
    seed: int, n_faults: int = 12, params: TrainTwinParams = TrainTwinParams()
) -> List[dict]:
    """Chip-unhealthy events from a seeded plan: (step, device, recovery_s).

    Draws a larger random plan and keeps the chip faults — same seed,
    same trace, every policy replays it identically."""
    plan = FaultPlan.random(
        seed,
        n_faults=n_faults * 3,
        max_step=params.total_steps,
        n_devices=params.n_chips,
    )
    events, seen_steps = [], set()
    for s in plan.specs:
        if s.kind is not FaultKind.CHIP_UNHEALTHY or s.at_step is None:
            continue
        if s.at_step in seen_steps:  # one fault per step keeps the sim simple
            continue
        seen_steps.add(s.at_step)
        events.append({
            "step": int(s.at_step),
            "device": int(s.device_index or 0),
            "recovery_s": params.chip_recovery_base_s
            + params.chip_recovery_per_duration_s * float(s.duration_steps or 1),
        })
    events.sort(key=lambda e: e["step"])
    return events[:n_faults]


def _usable(healthy: int, params: TrainTwinParams) -> int:
    return max(params.min_chips, (healthy // params.model_axis) * params.model_axis)


def _layout_key(use: int, params: TrainTwinParams) -> str:
    """Index key for the shrunk-mesh layout running on ``use`` chips."""
    return f"{params.layout_prefix}|data{use // params.model_axis}xfsdp{params.model_axis}"


def seed_initial_compile(
    index: CompileCacheIndex, params: TrainTwinParams = TrainTwinParams()
) -> None:
    """The job's own startup compile put the full-mesh layout in the cache."""
    key = _layout_key(params.n_chips, params)
    index.record(
        key, params.cold_compile_s, cache_hit=False,
        label=key.split("|", 1)[1], model=params.layout_prefix,
        via=params.layout_prefix,
    )


def _resume_compile(
    index: Optional[CompileCacheIndex], use: int, params: TrainTwinParams
) -> Tuple[float, bool]:
    """Compile cost of a shrink-resume onto ``use`` chips: (seconds, warm)."""
    if index is None:  # index off: a fresh process always compiles cold
        return params.cold_compile_s, False
    key = _layout_key(use, params)
    if index.is_warm(key):
        index.record(key, params.warm_compile_s, cache_hit=True,
                     via=params.layout_prefix)
        return params.warm_compile_s, True
    index.record(key, params.cold_compile_s, cache_hit=False,
                 label=key.split("|", 1)[1], model=params.layout_prefix,
                 via=params.layout_prefix)
    return params.cold_compile_s, False


def _grow_compile(
    index: Optional[CompileCacheIndex], use: int, params: TrainTwinParams
) -> Tuple[float, bool]:
    """Compile cost of a grow-back preempt-resume onto ``use`` chips.

    With the index on, the scheduler precompiles the target layout in the
    background *before* preempting, so the cold compile never lands on
    the critical path — the resume pays only the warm relink either way;
    a never-seen layout is recorded as a background precompile."""
    if index is None:
        return params.cold_compile_s, False
    key = _layout_key(use, params)
    if not index.is_warm(key):
        index.record(key, params.cold_compile_s, cache_hit=False,
                     label=key.split("|", 1)[1], model=params.layout_prefix,
                     via="precompile")
    index.record(key, params.warm_compile_s, cache_hit=True,
                 via=params.layout_prefix)
    return params.warm_compile_s, True


def replay_self_heal(
    events: List[dict],
    params: TrainTwinParams = TrainTwinParams(),
    recorder: Optional[FlightRecorder] = None,
    trace_id: Optional[str] = None,
    compile_index: Optional[CompileCacheIndex] = None,
) -> dict:
    """Self-heal policy over a chip-fault timeline on the virtual clock:
    in-band detect, emergency save, shrink re-admit (zero lost steps),
    grow back when the chip recovers. Records the causal recovery chain
    (detect → emergency_save → requeue → shrink_admit → compile → resume)
    when given a recorder."""
    clock = 0.0
    healthy = params.n_chips
    pending: List[float] = []  # clocks at which a failed chip becomes healthy
    mttrs: List[float] = []
    grow_backs = 0
    degraded_s = 0.0
    warm_resumes = 0
    cold_resumes = 0
    compile_s_total = 0.0
    i = 0
    # Flight-recorder lane (virtual-clock timestamps — the recorder takes
    # explicit t0/t1 everywhere for exactly this). Each fault's recovery
    # chain links causally; a later grow_back chains off the resume.
    root = chain_tail = None
    if recorder is not None:
        trace_id = trace_id or recorder.new_trace_id()
        root = recorder.start_span(
            "job:chaos-self-heal", kind="job", trace_id=trace_id, t0=0.0,
            attrs={"n_chips": params.n_chips, "total_steps": params.total_steps},
        )
    for step in range(1, params.total_steps + 1):
        # Grow back as soon as a chip has recovered: preempt-save-resume at
        # the larger mesh (the scheduler's _maybe_grow pass).
        while pending and pending[0] <= clock and healthy < params.n_chips:
            pending.pop(0)
            healthy += 1
            if _usable(healthy, params) > _usable(healthy - 1, params):
                g_compile_s, g_warm = _grow_compile(
                    compile_index, _usable(healthy, params), params
                )
                g_admit_end = clock + params.ckpt_save_s + params.resume_admit_s
                if recorder is not None:
                    recorder.record_span(
                        "grow_back", kind="admission", trace_id=trace_id,
                        parent=chain_tail or root, t0=clock, t1=g_admit_end,
                        attrs={"step": step, "mesh": _usable(healthy, params)},
                    )
                    recorder.record_span(
                        "compile", kind="compile", trace_id=trace_id,
                        parent=chain_tail or root, t0=g_admit_end,
                        t1=g_admit_end + g_compile_s,
                        attrs={"cache_hit": g_warm,
                               "compile_s": g_compile_s,
                               "layout": _layout_key(_usable(healthy, params), params)},
                    )
                clock = g_admit_end + g_compile_s
                compile_s_total += g_compile_s
                warm_resumes += 1 if g_warm else 0
                cold_resumes += 0 if g_warm else 1
                grow_backs += 1
        use = _usable(healthy, params)
        step_t = params.step_time_s * params.n_chips / use
        clock += step_t
        if use < params.n_chips:
            degraded_s += step_t
        if step % params.ckpt_interval_steps == 0:
            if recorder is not None:
                recorder.record_span(
                    "checkpoint_save", kind="checkpoint_save",
                    trace_id=trace_id, parent=root, t0=clock,
                    t1=clock + params.ckpt_save_s, attrs={"step": step},
                )
            clock += params.ckpt_save_s
        if i < len(events) and step >= events[i]["step"]:
            ev = events[i]
            i += 1
            healthy -= 1
            # Detection is the in-band health check on this very step;
            # emergency save persists `step`, shrink-resume follows. The
            # compile leg is warm iff the index has seen this layout.
            compile_s, warm = _resume_compile(
                compile_index, _usable(healthy, params), params
            )
            down = params.ckpt_save_s + params.resume_admit_s + compile_s
            admit_end = clock + params.ckpt_save_s + params.resume_admit_s
            if recorder is not None:
                detect = recorder.record_span(
                    "detect", kind="fault", trace_id=trace_id, parent=root,
                    t0=clock, t1=clock,
                    attrs={"step": step, "device": ev["device"]},
                )
                save = recorder.record_span(
                    "emergency_save", kind="emergency_save",
                    trace_id=trace_id, parent=detect, t0=clock,
                    t1=clock + params.ckpt_save_s, attrs={"step": step},
                )
                requeue = recorder.record_span(
                    "requeue", kind="scheduler", trace_id=trace_id,
                    parent=save, t0=clock + params.ckpt_save_s,
                    t1=clock + params.ckpt_save_s, attrs={"step": step},
                )
                admit = recorder.record_span(
                    "shrink_admit", kind="admission", trace_id=trace_id,
                    parent=requeue, t0=clock + params.ckpt_save_s, t1=admit_end,
                    attrs={"step": step, "mesh": _usable(healthy, params)},
                )
                comp = recorder.record_span(
                    "compile", kind="compile", trace_id=trace_id,
                    parent=admit, t0=admit_end, t1=admit_end + compile_s,
                    attrs={"cache_hit": warm, "compile_s": compile_s,
                           "layout": _layout_key(_usable(healthy, params), params)},
                )
                chain_tail = recorder.record_span(
                    "resume", kind="supervisor", trace_id=trace_id,
                    parent=comp, t0=clock + down, t1=clock + down,
                    attrs={"from_step": step},
                )
            clock += down
            compile_s_total += compile_s
            warm_resumes += 1 if warm else 0
            cold_resumes += 0 if warm else 1
            mttrs.append(step_t + down)
            pending.append(clock + ev["recovery_s"])
            pending.sort()
    wall = clock
    if root is not None:
        root.end(t1=wall, faults=len(mttrs), grow_backs=grow_backs)
    return {
        "policy": "self-heal",
        "compile_index": compile_index is not None,
        "wall_s": round(wall, 1),
        "steps_run": params.total_steps,
        "lost_steps": 0,
        "faults": len(mttrs),
        "grow_backs": grow_backs,
        "degraded_step_s": round(degraded_s, 1),
        "warm_resumes": warm_resumes,
        "cold_resumes": cold_resumes,
        "compile_s_total": round(compile_s_total, 1),
        "mttr_mean_s": round(sum(mttrs) / len(mttrs), 2) if mttrs else 0.0,
        "mttr_max_s": round(max(mttrs), 2) if mttrs else 0.0,
        "goodput": round(params.total_steps * params.step_time_s / wall, 4),
    }


def replay_die_and_restart(
    events: List[dict], params: TrainTwinParams = TrainTwinParams()
) -> dict:
    """Die-and-restart policy: external poll detect, wait for the chip,
    cold restart from the last periodic checkpoint (steps lost)."""
    clock = 0.0
    step = 0
    last_ckpt = 0
    lost_steps = 0
    steps_run = 0
    mttrs: List[float] = []
    i = 0
    while step < params.total_steps:
        clock += params.step_time_s
        step += 1
        steps_run += 1
        if step % params.ckpt_interval_steps == 0:
            last_ckpt = step
            clock += params.ckpt_save_s
        if i < len(events) and step >= events[i]["step"]:
            ev = events[i]
            i += 1  # each fault fires once, even though step rolls back
            lost = step - last_ckpt
            lost_steps += lost
            # Nothing runs until the chip is replaced (full mesh required),
            # then a cold restart replays everything since the checkpoint.
            down = params.die_detect_s + ev["recovery_s"] + params.die_restart_s
            clock += down
            mttrs.append(down + lost * params.step_time_s)
            step = last_ckpt
    wall = clock
    return {
        "policy": "die-and-restart",
        "wall_s": round(wall, 1),
        "steps_run": steps_run,
        "lost_steps": lost_steps,
        "faults": len(mttrs),
        "grow_backs": 0,
        "degraded_step_s": 0.0,
        "mttr_mean_s": round(sum(mttrs) / len(mttrs), 2) if mttrs else 0.0,
        "mttr_max_s": round(max(mttrs), 2) if mttrs else 0.0,
        "goodput": round(params.total_steps * params.step_time_s / wall, 4),
    }


def goodput_lane(
    recorder: FlightRecorder,
    trace_id: str,
    wall: float,
    full_gang: int = 8,
    tenant: str = "chaos",
    goodput_target: float = 0.88,
    short_window_s: float = 120.0,
    long_window_s: float = 600.0,
    warning_burn: float = 1.5,
    page_burn: float = 3.0,
) -> dict:
    """Account a recorded training trace through the REAL goodput ledger
    (the same decomposition live submissions get), then replay the SLO
    burn-rate alerter over the run's virtual clock.

    Alert transitions land as ``slo_alert`` events on the recorder's
    ``fleet`` timeline and per-window counter samples as a Perfetto
    counter track — both ride the same Chrome-trace export as the
    recovery chains they explain."""
    ledger = GoodputLedger(clock=lambda: wall, bucket_s=60.0,
                           history_buckets=256)
    ledger.track(trace_id, tenant=tenant, workload="training",
                 full_gang=full_gang)
    d = ledger.finalize(recorder, trace_id, now=wall)
    assert d is not None
    cats = d["categories"]
    sum_error_pct = abs(sum(cats.values()) - d["wall_s"]) / d["wall_s"] * 100
    alerter = SLOBurnRateAlerter(
        ledger,
        goodput_target=goodput_target,
        short_window_s=short_window_s,
        long_window_s=long_window_s,
        warning_burn=warning_burn,
        page_burn=page_burn,
        recorder=recorder,
        clock=lambda: wall,
    )
    progression = ["ok"]
    t = 0.0
    while t <= wall + 60.0:
        out = alerter.evaluate(now=t)
        g = out["goodput"]
        if g["state"] != progression[-1]:
            progression.append(g["state"])
        recorder.counter(
            "goodput_burn",
            {
                "goodput_fraction_short": g["short_fraction"] or 1.0,
                "burn_short": g["short_burn"] or 0.0,
                "burn_long": g["long_burn"] or 0.0,
            },
            trace_id=trace_id,
            ts=t,
        )
        t += 60.0
    split = d.get("compile_split") or {}
    return {
        "breakdown_s": {c: round(cats[c], 2) for c in CATEGORIES},
        "breakdown_pct": {
            c: round(100.0 * cats[c] / d["wall_s"], 2) for c in CATEGORIES
        },
        "compile_split_s": {
            "warm_s": round(float(split.get("warm_s", 0.0)), 2),
            "cold_s": round(float(split.get("cold_s", 0.0)), 2),
        },
        "wall_s": round(d["wall_s"], 1),
        "goodput_fraction": round(d["goodput_fraction"], 4),
        "sum_error_pct": round(sum_error_pct, 6),
        "slo": {
            "target": alerter.goodput_target,
            "warning_burn": alerter.warning_burn,
            "page_burn": alerter.page_burn,
            "progression": progression,
            "alert_count": len(alerter.alerts),
            "alerts": list(alerter.alerts),
        },
    }


# -- heterogeneous sharding lane ----------------------------------------------


@dataclasses.dataclass(frozen=True)
class HeteroTwinParams:
    """Slow-host gang scenario: one host runs sustained-slow; the
    synchronous gang gates every step on it unless the heterogeneity
    plane reweights the per-process row assignment."""

    hosts: int = 8
    global_micro: int = 128
    steps: int = 400
    tail_steps: int = 100       # steady-state window: the last N steps
    check_every: int = 10       # rebalance consult cadence (steps)
    shrink_at_step: int = 25    # when the shrink policy evicts the slow host
    step_time_s: float = 0.5
    # Reported per-step stall while uniformly loaded; the slow host's true
    # rate is STEP/(STEP+stall) = 0.75 — the headline 25%-degraded host.
    slow_s: float = 0.5 / 3.0
    ckpt_save_s: float = 5.0
    resume_admit_s: float = 5.0
    cold_compile_s: float = 15.0


def host_slow_plan(
    seed: int, params: HeteroTwinParams = HeteroTwinParams()
) -> FaultPlan:
    """Sustained host-slow on one seeded host: fires every step."""
    host = random.Random(seed).randrange(params.hosts)
    return FaultPlan(seed=seed, specs=[
        FaultSpec(
            kind=FaultKind.HOST_SLOW, at_step=1, device_index=host,
            slow_s=round(params.slow_s, 6), count=params.steps,
        )
    ])


def replay_hetero(
    policy: str,
    plan: FaultPlan,
    params: HeteroTwinParams = HeteroTwinParams(),
    recorder: Optional[FlightRecorder] = None,
    trace_id: Optional[str] = None,
) -> dict:
    """Replay ``plan`` under one policy on the virtual clock.

    The injector is the only degradation source: a consumed HOST_SLOW spec
    both slows the simulated host (truth) and feeds the ThroughputTracker
    (signal) — exactly the supervisor's ``take_host_slow`` seam."""
    inj = FaultInjector(plan)
    inj.arm()
    rate = [1.0] * params.hosts        # ground-truth relative rates
    rows_u = params.global_micro // params.hosts
    vclock = 0.0
    tracker = hetero_mod.ThroughputTracker(params.hosts)
    reb = hetero_mod.HeteroRebalancer(
        tracker, params.global_micro, dry_run=False, cooldown_s=30.0,
        min_gain=0.01, clock=lambda: vclock,
        recorder=recorder, trace_id=trace_id,
    )
    assignment = list(reb.assignment)
    active = list(range(params.hosts))
    shrunk = False
    downtime_s = 0.0
    rebalance_step: Optional[int] = None
    ideal_wall = 0.0
    tail_wall = tail_ideal = 0.0
    for step in range(1, params.steps + 1):
        spec = inj.take_host_slow(step)
        if spec is not None:
            idx = int(spec.device_index or 0)
            rate[idx] = params.step_time_s / (params.step_time_s + float(spec.slow_s))
            tracker.note_host_slow(idx, float(spec.slow_s), params.step_time_s)
        if policy == "shrink" and not shrunk and step >= params.shrink_at_step:
            # Evict the slow host: emergency save + re-admit + cold compile,
            # then a smaller uniform gang carries the full global batch.
            shrunk = True
            slow_host = min(range(params.hosts), key=lambda h: rate[h])
            active = [h for h in range(params.hosts) if h != slow_host]
            assignment = hetero_mod.uniform_assignment(
                params.global_micro, len(active)
            )
            downtime_s = params.ckpt_save_s + params.resume_admit_s + params.cold_compile_s
            vclock += downtime_s
        # Synchronous gang: the step ends when the slowest member finishes
        # its rows; a host's nominal pace is rows_u rows per step_time_s.
        step_s = max(
            assignment[j] * params.step_time_s / (rows_u * rate[h])
            for j, h in enumerate(active)
        )
        ideal_s = params.global_micro * params.step_time_s / (rows_u * sum(rate))
        vclock += step_s
        ideal_wall += ideal_s
        tracker.observe_step(step_s)
        if policy == "rebalance-on" and step % params.check_every == 0:
            r_plan = reb.maybe_rebalance(step)
            if r_plan is not None:
                assignment = list(r_plan.assignment)
                if rebalance_step is None:
                    rebalance_step = step
        if step > params.steps - params.tail_steps:
            tail_wall += step_s
            tail_ideal += ideal_s
    return {
        "policy": policy,
        "wall_s": round(vclock, 1),
        "ideal_wall_s": round(ideal_wall, 1),
        "downtime_s": round(downtime_s, 1),
        "goodput": round(ideal_wall / vclock, 4),
        "steady_goodput": round(tail_ideal / tail_wall, 4),
        "assignment": list(assignment),
        "active_hosts": len(active),
        "rebalance_step": rebalance_step,
        "rebalancer": reb.stats() if policy == "rebalance-on" else None,
    }


def run_hetero_ab(
    seed: int = 0,
    params: HeteroTwinParams = HeteroTwinParams(),
    recorder: Optional[FlightRecorder] = None,
) -> dict:
    """Rebalance-on vs rebalance-off vs shrink on one seeded slow-host plan."""
    plan = host_slow_plan(seed, params)
    trace_id = recorder.new_trace_id() if recorder is not None else None
    on = replay_hetero("rebalance-on", plan, params, recorder=recorder,
                       trace_id=trace_id)
    off = replay_hetero("rebalance-off", plan, params)
    shrink = replay_hetero("shrink", plan, params)
    return {
        "seed": seed,
        "params": {
            "n_hosts": params.hosts,
            "global_micro": params.global_micro,
            "steps": params.steps,
            "slow_host_rate": round(
                params.step_time_s / (params.step_time_s + params.slow_s), 4
            ),
            "slow_host": int(plan.specs[0].device_index or 0),
            "check_every_steps": params.check_every,
        },
        "rebalance_on": on,
        "rebalance_off": off,
        "shrink": shrink,
        "steady_goodput_on": on["steady_goodput"],
        "steady_goodput_off": off["steady_goodput"],
        "steady_goodput_shrink": shrink["steady_goodput"],
        "goodput_recovered": round(
            on["steady_goodput"] - off["steady_goodput"], 4
        ),
    }


# -- serving lane: open-loop tick driver + autoscaled fleet -------------------


def percentile(vals: List[float], q: float) -> float:
    if not vals:
        return 0.0
    vals = sorted(vals)
    return vals[min(int(q * (len(vals) - 1)), len(vals) - 1)]


def run_open_loop(
    trace: List[dict],
    dt: float,
    duration_s: float,
    pending: Callable[[], Any],
    arrive: Callable[[dict], None],
    tick: Callable[[float], None],
    control: Optional[Callable[[float], None]] = None,
    control_period_s: float = 1.0,
    safety_factor: float = 3.0,
) -> float:
    """The shared open-loop discrete-event driver every serving scenario
    runs on: deliver arrivals due by ``t``, run the control-plane closure
    on its cadence, advance the world one ``dt`` tick — until the trace
    is exhausted AND ``pending()`` is falsy. ``safety_factor`` bounds a
    sim bug from spinning forever. Returns the final virtual time."""
    idx, t, next_control = 0, 0.0, 0.0
    while t < duration_s or pending():
        if t > duration_s * safety_factor:
            break
        while idx < len(trace) and trace[idx]["t"] <= t:
            arrive(trace[idx])
            idx += 1
        if control is not None and t >= next_control:
            next_control = t + control_period_s
            control(t)
        tick(t)
        t += dt
    return t


@dataclasses.dataclass(frozen=True)
class ServingTwinParams:
    """Autoscaled serving-fleet scenario knobs (defaults = the seeded
    benchmark; ``benchmarks/serving_fleet_sim.py`` re-exports them)."""

    duration_s: float = 600.0
    dt_s: float = 0.05
    control_period_s: float = 1.0
    slots: int = 8
    tokens_per_slot_s: float = 30.0
    degraded_fraction: float = 0.4
    prefill_s: float = 1.2
    prefill_hit_s: float = 0.15
    startup_delay_s: float = 25.0
    chips_per_replica: int = 1
    prefix_len: int = 32
    p99_slo_ms: float = 25_000.0
    warmup_s: float = 120.0


class SlotReplica:
    """Capacity model of one decode replica: a slot pool, a per-slot decode
    rate, and a prefix cache that skips prefill for resident prefixes."""

    def __init__(
        self,
        rid: str,
        rate_fraction: float,
        ready_at: float,
        params: ServingTwinParams = ServingTwinParams(),
    ):
        self.rid = rid
        self.params = params
        self.rate = params.tokens_per_slot_s * rate_fraction
        self.ready_at = ready_at
        self.active: List[dict] = []      # {req, prefill_left, tokens_left}
        self.prefix_cache: set = set()
        self.tokens_out = 0.0
        self.draining = False

    def ready(self, now: float) -> bool:
        return now >= self.ready_at

    def free_slots(self, now: float) -> int:
        if not self.ready(now) or self.draining:
            return 0
        return self.params.slots - len(self.active)

    def admit(self, req: dict) -> None:
        hit = req["prefix_id"] in self.prefix_cache
        self.prefix_cache.add(req["prefix_id"])
        self.active.append({
            "req": req,
            "prefill_left": self.params.prefill_hit_s if hit
            else self.params.prefill_s,
            "tokens_left": float(req["n_new"]),
            "hit": hit,
        })

    def step(self, now: float, dt: float, done: List[dict]) -> None:
        if not self.ready(now):
            return
        for sl in list(self.active):
            if sl["prefill_left"] > 0:
                sl["prefill_left"] -= dt
                continue
            produced = min(self.rate * dt, sl["tokens_left"])
            sl["tokens_left"] -= produced
            self.tokens_out += produced
            if sl["tokens_left"] <= 0:
                sl["req"]["done_at"] = now
                sl["req"]["replica"] = self.rid
                sl["req"]["prefix_hit"] = sl["hit"]
                done.append(sl["req"])
                self.active.remove(sl)

    def router_stats(self, now: float) -> dict:
        # tokens/sec the router would measure: rate × busy slots (plus a
        # trickle when idle so a fresh replica is not weight-zero).
        busy = sum(1 for s in self.active if s["prefill_left"] <= 0)
        return {
            "tokens_per_sec": self.rate * max(busy, 0.2),
            "free_slots": self.free_slots(now),
            "slots": self.params.slots,
        }


def replay_serving_fleet(
    trace: List[dict],
    autoscale: bool,
    autoscaler_cfg,
    params: ServingTwinParams = ServingTwinParams(),
) -> dict:
    """Autoscaled (or static-1) fleet over an open-loop trace, driven by
    the REAL FleetRouter + ReplicaAutoscaler on the twin's tick driver."""
    from tpu_engine.serving_fleet import FleetRouter, ReplicaAutoscaler

    router = FleetRouter(affinity_tokens=params.prefix_len)
    scaler = ReplicaAutoscaler(autoscaler_cfg)
    replicas: Dict[str, SlotReplica] = {
        # Replica 0 is the degraded host — present from t=0 in both modes;
        # in static mode it is the whole fleet.
        "r0": SlotReplica("r0", params.degraded_fraction, ready_at=0.0,
                          params=params)
    }
    state = {"next_rid": 1, "chip_seconds": 0.0}
    queue: List[dict] = []
    done: List[dict] = []
    replica_trace: List[tuple] = []

    def control(t: float) -> None:
        up = {
            r.rid: r.router_stats(t)
            for r in replicas.values()
            if r.ready(t) and not r.draining
        }
        router.update(up)
        ready_n = len(up)
        # Change-point trace: one entry per replica-count transition
        # keeps the bench JSON line readable.
        if not replica_trace or replica_trace[-1][1] != ready_n:
            replica_trace.append((round(t, 1), ready_n))
        if autoscale and ready_n > 0:
            lat = [(r["done_at"] - r["t"]) * 1000.0 for r in done[-256:]]
            desired = scaler.observe(
                t, len(queue), percentile(lat, 0.99) if lat else None, ready_n
            )
            booting = sum(
                1 for r in replicas.values()
                if not r.ready(t) and not r.draining
            )
            while desired > ready_n + booting:
                rid = f"r{state['next_rid']}"
                replicas[rid] = SlotReplica(
                    rid, 1.0, ready_at=t + params.startup_delay_s,
                    params=params,
                )
                state["next_rid"] += 1
                booting += 1
            if desired < ready_n:
                # Drain the emptiest ready replica (never the last one).
                cands = sorted(
                    (r for r in replicas.values()
                     if r.ready(t) and not r.draining and r.rid != "r0"),
                    key=lambda r: len(r.active),
                )
                for r in cands[: ready_n - desired]:
                    r.draining = True

    def tick(t: float) -> None:
        # Dispatch through the real router (affinity keys on the prefix).
        # Route only while the fleet has a free slot — an overloaded fleet
        # must queue, not spin the router on unplaceable requests.
        free_total = sum(r.free_slots(t) for r in replicas.values())
        placed = 0
        while queue and free_total > 0:
            req = queue[0]
            rid = router.route(req["prompt"])
            rep = replicas.get(rid) if rid else None
            if rep is not None and rep.free_slots(t) > 0:
                rep.admit(queue.pop(0))
                free_total -= 1
                placed += 1
            else:
                # Router picked a full/draining replica: stop this tick,
                # weights refresh at the next control period.
                break
            if placed > params.slots * len(replicas):
                break
        for r in list(replicas.values()):
            r.step(t, params.dt_s, done)
            if r.draining and not r.active:
                del replicas[r.rid]
        state["chip_seconds"] += params.dt_s * params.chips_per_replica * sum(
            1 for r in replicas.values() if r.ready(t)
        )

    run_open_loop(
        trace,
        dt=params.dt_s,
        duration_s=params.duration_s,
        pending=lambda: queue or any(r.active for r in replicas.values()),
        arrive=queue.append,
        tick=tick,
        control=control,
        control_period_s=params.control_period_s,
        safety_factor=3.0,
    )

    lat_ms = [
        (r["done_at"] - r["t"]) * 1000.0 for r in done
        if r["t"] >= params.warmup_s
    ]
    # Count tokens from completed requests, not replica counters — drained
    # replicas leave the dict and would take their counters with them.
    total_tokens = float(sum(req["n_new"] for req in done))
    makespan = max((r["done_at"] for r in done), default=params.dt_s)
    p99 = percentile(lat_ms, 0.99)
    return {
        "completed": len(done),
        "total_tokens": total_tokens,
        "tokens_per_sec": total_tokens / makespan,
        "tokens_per_sec_per_chip": total_tokens
        / max(state["chip_seconds"], params.dt_s),
        "p50_ms": round(percentile(lat_ms, 0.50), 1),
        "p99_ms": round(p99, 1),
        "p99_within_slo": p99 <= params.p99_slo_ms,
        "makespan_s": round(makespan, 1),
        "replica_trace": replica_trace,
        "max_replicas_used": max(n for _, n in replica_trace),
        "prefix_hit_rate": round(
            sum(1 for r in done if r.get("prefix_hit")) / max(len(done), 1), 3
        ),
        "router": router.stats(),
        "autoscaler": scaler.stats(),
    }


def serving_metrics(
    done: List[dict],
    ttfts: List[float],
    warmup_s: float = 120.0,
    total_chips: int = 8,
    dt_s: float = 0.05,
) -> dict:
    """Steady-state latency/TTFT percentiles + throughput of one serving
    run (the symmetric-vs-disagg A/B's shared report shape)."""
    lat_ms = [(r["done_at"] - r["t"]) * 1000.0 for r in done
              if r["t"] >= warmup_s]
    steady_ttfts = [
        (r["first_token_at"] - r["t"]) * 1000.0 for r in done
        if r["t"] >= warmup_s and "first_token_at" in r
    ]
    total_tokens = float(sum(r["n_new"] for r in done))
    makespan = max((r["done_at"] for r in done), default=dt_s)
    return {
        "completed": len(done),
        "total_tokens": total_tokens,
        "tokens_per_sec": round(total_tokens / makespan, 2),
        "tokens_per_sec_per_chip": round(
            total_tokens / (makespan * total_chips), 2),
        "ttft_p50_ms": round(percentile(steady_ttfts, 0.50), 1),
        "ttft_p99_ms": round(percentile(steady_ttfts, 0.99), 1),
        "p50_ms": round(percentile(lat_ms, 0.50), 1),
        "p99_ms": round(percentile(lat_ms, 0.99), 1),
        "makespan_s": round(makespan, 1),
    }


# -- warm-admission lane ------------------------------------------------------


def warm_admission_lane(
    jobs: List[Tuple[str, float]],
    prefer_warm: bool,
    cold_compile_s: float = 15.0,
    warm_compile_s: float = 1.5,
) -> dict:
    """Serve ``jobs`` (layout key, work seconds) through one slot.

    Every job's service time is compile + work; the compile leg consults a
    fresh :class:`CompileCacheIndex` — cold the first time a layout is
    seen, warm after. ``prefer_warm`` is the cache-aware admission policy:
    among queued jobs, the first whose layout the index says is warm is
    admitted ahead of the FIFO head (ties broken FIFO)."""
    index = CompileCacheIndex(path=None, default_cold_s=cold_compile_s)
    queue = list(range(len(jobs)))
    clock = 0.0
    waits: List[float] = []
    cold_compiles = 0
    while queue:
        pick = 0
        if prefer_warm:
            pick = next(
                (qi for qi, j in enumerate(queue)
                 if index.is_warm(jobs[j][0])),
                0,
            )
        j = queue.pop(pick)
        layout, work_s = jobs[j]
        waits.append(clock)
        if index.is_warm(layout):
            compile_s = warm_compile_s
            index.record(layout, compile_s, cache_hit=True, via="sim")
        else:
            compile_s = cold_compile_s
            cold_compiles += 1
            index.record(layout, compile_s, cache_hit=False,
                         label=layout.split("|", 1)[1], model="sim", via="sim")
        clock += compile_s + work_s
    return {
        "mean_wait_s": round(sum(waits) / len(waits), 2),
        "makespan_s": round(clock, 2),
        "cold_compiles": cold_compiles,
        "warm_hits": len(jobs) - cold_compiles,
    }


# -- A/B scorecard layer ------------------------------------------------------


def _flatten_numeric(d: Dict[str, Any]) -> Dict[str, float]:
    out: Dict[str, float] = {}
    for k, v in d.items():
        if isinstance(v, bool) or isinstance(v, (int, float)):
            out[k] = float(v)
    return out


def ab_scorecard(
    variants: Dict[str, Any],
    runner: Callable[[str, Any], Dict[str, Any]],
    label: str = "twin-ab",
) -> Dict[str, Any]:
    """Run ``runner(name, cfg)`` once per variant over the same ingested
    workload; the first variant is the baseline. One JSON artifact:
    per-variant metrics plus numeric deltas vs the baseline."""
    results: Dict[str, Dict[str, Any]] = {}
    cpu_s: Dict[str, float] = {}
    for name, cfg in variants.items():
        c0 = time.perf_counter()
        results[name] = runner(name, cfg)
        cpu_s[name] = round(time.perf_counter() - c0, 4)
    base_name = next(iter(results))
    base = _flatten_numeric(results[base_name])
    deltas: Dict[str, Dict[str, float]] = {}
    for name, res in results.items():
        if name == base_name:
            continue
        flat = _flatten_numeric(res)
        deltas[name] = {
            k: round(flat[k] - base[k], 6) for k in flat if k in base
        }
    _bump(ab_runs_total=1)
    return {
        "label": label,
        "baseline": base_name,
        "variants": results,
        "deltas_vs_baseline": deltas,
        "cpu_s": cpu_s,
    }


def default_policy_scorecard(seed: int = 0, n_faults: int = 12) -> dict:
    """A real policy question answered on one ingested fault timeline:
    checkpoint-interval 50/100/200 × compile-index on/off, each variant
    replayed through the full self-heal lane + goodput ledger + SLO
    alerter. The baseline is the shipped config (interval 100, index on)."""
    base = TrainTwinParams()
    events = chip_fault_timeline(seed, n_faults, base)
    variants: Dict[str, dict] = {
        "ckpt100_index_on": {"params": base, "compile_index": True},
        "ckpt50_index_on": {
            "params": dataclasses.replace(base, ckpt_interval_steps=50),
            "compile_index": True,
        },
        "ckpt200_index_on": {
            "params": dataclasses.replace(base, ckpt_interval_steps=200),
            "compile_index": True,
        },
        "ckpt100_index_off": {"params": base, "compile_index": False},
    }

    def runner(name: str, cfg: dict) -> dict:
        params: TrainTwinParams = cfg["params"]
        rec = FlightRecorder(
            max_spans=16384, max_events=16384, clock=lambda: 0.0,
            id_factory=deterministic_ids(name),
        )
        tid = rec.new_trace_id()
        index = None
        if cfg["compile_index"]:
            index = CompileCacheIndex(
                path=None, default_cold_s=params.cold_compile_s
            )
            seed_initial_compile(index, params)
        heal = replay_self_heal(
            events, params, recorder=rec, trace_id=tid, compile_index=index
        )
        gp = goodput_lane(rec, tid, heal["wall_s"], full_gang=params.n_chips)
        return {
            "ckpt_interval_steps": params.ckpt_interval_steps,
            "compile_index": cfg["compile_index"],
            "wall_s": heal["wall_s"],
            "goodput_fraction": gp["goodput_fraction"],
            "productive_pct": gp["breakdown_pct"]["productive"],
            "checkpoint_pct": gp["breakdown_pct"]["checkpoint_save"],
            "compile_pct": gp["breakdown_pct"]["compile"],
            "mttr_mean_s": heal["mttr_mean_s"],
            "warm_resumes": heal["warm_resumes"],
            "cold_resumes": heal["cold_resumes"],
            "slo_alerts": gp["slo"]["alert_count"],
        }

    card = ab_scorecard(
        variants, runner, label="chaos-ckpt-interval-x-compile-index"
    )
    card["seed"] = seed
    card["n_faults"] = n_faults
    return card


def admission_policy_scorecard(seed: int = 0, n_jobs: int = 16) -> dict:
    """Queue-wait A/B on one seeded job list: strict FIFO vs the
    cache-aware warm-preferring admission order."""
    rng = random.Random(seed)
    layouts = [f"sim|data{g}xfsdp2" for g in (1, 2, 4)]
    jobs = [
        (rng.choice(layouts), round(rng.uniform(4.0, 12.0), 2))
        for _ in range(n_jobs)
    ]
    return ab_scorecard(
        {"fifo": False, "warm_preferring": True},
        lambda name, prefer_warm: warm_admission_lane(jobs, prefer_warm),
        label="admission-fifo-vs-warm",
    )


# -- fidelity + bench wiring --------------------------------------------------


def replay_fidelity(seed: int = 0, n_faults: int = 12) -> dict:
    """The acceptance loop end to end: record a real self-heal run to
    JSONL, ingest it, replay it on the twin, and diff the replayed
    goodput decomposition against the source run's (per category, % of
    wall). Also measures replay throughput in simulated fleet-seconds
    per CPU-second."""
    params = TrainTwinParams()
    with tempfile.TemporaryDirectory(prefix="twin_fidelity_") as root:
        path = os.path.join(root, "trace.jsonl")
        rec = FlightRecorder(
            max_spans=16384, max_events=16384, clock=lambda: 0.0,
            persist_path=path, persist_max_bytes=64 * 1024 * 1024,
        )
        tid = rec.new_trace_id()
        index = CompileCacheIndex(path=None, default_cold_s=params.cold_compile_s)
        seed_initial_compile(index, params)
        events = chip_fault_timeline(seed, n_faults, params)
        heal = replay_self_heal(
            events, params, recorder=rec, trace_id=tid, compile_index=index
        )
        source = goodput_lane(rec, tid, heal["wall_s"], full_gang=params.n_chips)
        workload = ReplayWorkload.from_jsonl(path)
    engine = TwinEngine()
    out = engine.replay(workload)
    twin_side = out["traces"].get(tid) or {}
    diff = decomposition_diff(
        source["breakdown_s"], twin_side.get("categories") or {},
        source["wall_s"],
    )
    return {
        "seed": seed,
        "wall_s": source["wall_s"],
        "source_goodput_fraction": source["goodput_fraction"],
        "replay_goodput_fraction": round(
            float(twin_side.get("goodput_fraction") or 0.0), 4
        ),
        "per_category_error_pct": diff["per_category_pct"],
        "max_error_pct": diff["max_error_pct"],
        "spans_replayed": out["spans_replayed"],
        "events_replayed": out["events_replayed"],
        "ingest": out["ingest"],
        "fleet_seconds": out["fleet_seconds"],
        "cpu_seconds": out["cpu_seconds"],
        "fleet_seconds_per_cpu_second": out["fleet_seconds_per_cpu_second"],
    }


def twin_bench_line(seed: int = 0) -> dict:
    """The twin's deterministic bench line, shared by ``bench.py`` and
    ``tools/bench_sentinel.py``: replay fidelity vs the recorded source
    run, plus the two policy A/Bs' headline deltas."""
    fid = replay_fidelity(seed=seed)
    card = default_policy_scorecard(seed=seed)
    adm = admission_policy_scorecard(seed=seed)
    variants = card["variants"]
    gates = {
        "replay_within_1pct": fid["max_error_pct"] < 1.0,
        "replay_fast_enough": fid["fleet_seconds_per_cpu_second"] >= 1000.0,
        "policy_delta_measured": (
            variants["ckpt50_index_on"]["goodput_fraction"]
            != variants["ckpt200_index_on"]["goodput_fraction"]
        ),
        "warm_beats_fifo": (
            adm["variants"]["warm_preferring"]["mean_wait_s"]
            < adm["variants"]["fifo"]["mean_wait_s"]
        ),
    }
    return {
        "metric": "twin_replay_policy_ab",
        "value": fid["max_error_pct"],
        "unit": "max per-category replay error, % of wall",
        "replay_goodput_fraction": fid["replay_goodput_fraction"],
        "spans_replayed": fid["spans_replayed"],
        "ingest_skipped_lines": fid["ingest"].get("skipped", 0),
        "fleet_seconds_per_cpu_second": fid["fleet_seconds_per_cpu_second"],
        "variant_goodput": {
            name: v["goodput_fraction"] for name, v in variants.items()
        },
        "variant_mttr_s": {
            name: v["mttr_mean_s"] for name, v in variants.items()
        },
        "variant_ckpt_pct": {
            name: v["checkpoint_pct"] for name, v in variants.items()
        },
        "ab_wait_fifo_s": adm["variants"]["fifo"]["mean_wait_s"],
        "ab_wait_warm_s": adm["variants"]["warm_preferring"]["mean_wait_s"],
        "gates": gates,
        "ok": all(gates.values()),
    }


# -- historian lane ------------------------------------------------------------

_HISTORIAN_FIDELITY_AGGS = ("avg", "min", "max", "last", "sum")


def _fault_incidents(correlator: "historian_mod.IncidentCorrelator") -> List[dict]:
    return [
        i for i in correlator.incidents(limit=0) if i["trigger"] == "fault"
    ]


def _incident_chain_ok(inc: dict) -> bool:
    """detect → action → resolution, in timestamp order, resolved."""
    roles = [e["role"] for e in inc["timeline"]]
    if "detect" not in roles or "action" not in roles or "resolution" not in roles:
        return False
    t_detect = min(e["ts"] for e in inc["timeline"] if e["role"] == "detect")
    t_action = min(e["ts"] for e in inc["timeline"] if e["role"] == "action")
    t_resol = min(e["ts"] for e in inc["timeline"] if e["role"] == "resolution")
    return inc["state"] == "resolved" and t_detect <= t_action <= t_resol


def historian_lane(seed: int = 0, n_faults: int = 12) -> dict:
    """Record a chaos self-heal + goodput run to JSONL, build the live
    historian series and incident set from the in-memory recorder, then
    rebuild both from the persisted JSONL alone and diff — the
    acceptance loop for the historian: a replayed trace must yield the
    same metric history (per queried aggregate, within 1%) and the same
    causally-chained incidents the live run produced, and every injected
    fault must land in exactly one resolved detect→action→resolution
    incident."""
    params = TrainTwinParams()
    with tempfile.TemporaryDirectory(prefix="twin_historian_") as root:
        path = os.path.join(root, "trace.jsonl")
        rec = FlightRecorder(
            max_spans=16384, max_events=16384, clock=lambda: 0.0,
            persist_path=path, persist_max_bytes=64 * 1024 * 1024,
            id_factory=deterministic_ids("hist"),
        )
        tid = rec.new_trace_id()
        index = CompileCacheIndex(path=None, default_cold_s=params.cold_compile_s)
        seed_initial_compile(index, params)
        events = chip_fault_timeline(seed, n_faults, params)
        heal = replay_self_heal(
            events, params, recorder=rec, trace_id=tid, compile_index=index
        )
        gp = goodput_lane(rec, tid, heal["wall_s"], full_gang=params.n_chips)
        wall = heal["wall_s"]
        counter_events = rec.events(kind="counter", limit=0)
        live_hist = historian_mod.MetricHistorian(clock=lambda: 0.0)
        t_ingest = time.perf_counter()
        ingested = live_hist.ingest_counter_events(counter_events)
        ingest_s = max(time.perf_counter() - t_ingest, 1e-9)
        live_corr = historian_mod.IncidentCorrelator(
            clock=lambda: wall, stale_after_s=1e9,
        )
        live_corr.ingest(recorder=rec, now=wall)
        records, ingest_stats = read_recorder_jsonl(path)
    replay_hist = historian_mod.MetricHistorian(clock=lambda: 0.0)
    replay_hist.ingest_jsonl_records(records)
    replay_corr = historian_mod.IncidentCorrelator(
        clock=lambda: wall, stale_after_s=1e9,
    )
    replay_corr.ingest(records=records, now=wall)

    # Per-series, per-aggregate fidelity of the rebuilt store.
    max_err = 0.0
    n_queries = 0
    t_query = time.perf_counter()
    for info in live_hist.series_list():
        for agg in _HISTORIAN_FIDELITY_AGGS:
            live_q = live_hist.query(
                info["name"], t0=0.0, t1=wall + 120.0, agg=agg, tier="raw"
            )
            rep_q = replay_hist.query(
                info["name"], t0=0.0, t1=wall + 120.0, agg=agg, tier="raw"
            )
            n_queries += 2
            lv, rv = live_q["value"], rep_q["value"]
            if lv is None and rv is None:
                continue
            if lv is None or rv is None:
                max_err = float("inf")
                continue
            denom = max(abs(lv), 1e-9)
            max_err = max(max_err, abs(lv - rv) / denom * 100.0)
    query_s = max(time.perf_counter() - t_query, 1e-9)

    live_faults = _fault_incidents(live_corr)
    replay_faults = _fault_incidents(replay_corr)

    def _fault_keys(incs: List[dict]) -> set:
        keys = set()
        for inc in incs:
            detects = [e for e in inc["timeline"] if e["role"] == "detect"]
            step = detects[0]["attrs"].get("step") if detects else None
            keys.add((step, inc.get("device_index")))
        return keys

    # chip_fault_timeline dedups colliding steps, so the injected count
    # is len(events), not necessarily n_faults.
    injected = {(e["step"], e["device"]) for e in events}
    gates = {
        "series_within_1pct": max_err < 1.0,
        "every_fault_one_incident": (
            len(live_faults) == len(injected)
            and _fault_keys(live_faults) == injected
        ),
        "causal_chains": all(_incident_chain_ok(i) for i in live_faults),
        "replay_incidents_match": (
            replay_corr.stats()["opened_by_trigger"]
            == live_corr.stats()["opened_by_trigger"]
            and replay_corr.stats()["resolved_total"]
            == live_corr.stats()["resolved_total"]
            and _fault_keys(replay_faults) == _fault_keys(live_faults)
        ),
        "nothing_skipped": ingest_stats["skipped"] == 0,
    }
    return {
        "seed": seed,
        "wall_s": wall,
        "series": live_hist.stats()["series"],
        "samples": live_hist.stats()["samples_total"],
        "samples_ingested": ingested,
        "incidents": live_corr.stats()["opened_by_trigger"],
        "fault_incidents": len(live_faults),
        "resolved_incidents": live_corr.stats()["resolved_total"],
        "slo_progression": gp["slo"]["progression"][:3],
        "max_series_error_pct": round(max_err, 6),
        "ingest_samples_per_sec": round(ingested / ingest_s, 1),
        "query_avg_us": round(query_s / max(n_queries, 1) * 1e6, 1),
        "gates": gates,
        "ok": all(gates.values()),
    }


def historian_bench_line(seed: int = 0) -> dict:
    """The historian's deterministic bench line, shared by ``bench.py``
    and ``tools/bench_sentinel.py``: series fidelity and incident
    stitching on the seeded chaos trace, plus (noisy, ungated) ingest
    and query throughput."""
    lane = historian_lane(seed=seed)
    return {
        "metric": "historian_chaos_incidents",
        "value": lane["max_series_error_pct"],
        "unit": "max replayed-series error, % per queried aggregate",
        "series": lane["series"],
        "samples": lane["samples"],
        "fault_incidents": lane["fault_incidents"],
        "resolved_incidents": lane["resolved_incidents"],
        "incidents_by_trigger": lane["incidents"],
        "ingest_samples_per_sec": lane["ingest_samples_per_sec"],
        "query_avg_us": lane["query_avg_us"],
        "gates": lane["gates"],
        "ok": lane["ok"],
    }

# -- autopilot lane ------------------------------------------------------------


def replay_autopilot(
    mode: str,
    plan: FaultPlan,
    params: HeteroTwinParams = HeteroTwinParams(),
) -> dict:
    """Replay the seeded slow-host chaos plan under one autopilot mode on
    the virtual clock: ``"off"`` (no control loop — the uniform gang
    gates on the slow host forever), ``"armed"`` (the autopilot's
    drain-host rule sheds the blamed host after its hysteresis clears),
    or ``"dry-run"`` (the full decision stream, zero actuations).

    The injector is both truth and signal, as in :func:`replay_hetero`:
    each consumed HOST_SLOW spec slows the simulated host and is
    mirrored as a ``kind="fault"`` blame event on the lane recorder; the
    lane also retains per-step time and per-host health into its own
    historian, so every autopilot decision consults real range queries
    over the exact series a live fleet would have."""
    hosts = params.hosts
    rows_u = params.global_micro // hosts
    vclock = VirtualClock(0.0)
    rec = FlightRecorder(
        max_spans=8192, max_events=8192, clock=vclock,
        id_factory=deterministic_ids(f"ap-{mode}"),
    )
    hist = historian_mod.MetricHistorian(clock=vclock)
    # Sustained degradation is ONE incident: successive blame events land
    # well inside the widened merge window instead of opening per-step
    # incidents.
    corr = historian_mod.IncidentCorrelator(
        clock=vclock, merge_window_s=4.0 * params.step_time_s,
        stale_after_s=1e9,
    )
    inj = FaultInjector(plan)
    inj.arm()
    rate = [1.0] * hosts
    drained = [False] * hosts

    def drain_actuator(record) -> None:
        drained[int(record.action["params"]["device_index"])] = True

    autopilot = FleetAutopilot(
        AutopilotConfig(
            trend_window_s=60.0,
            sustain_consults=3,
            cooldown_s=120.0,
            max_actions_per_window=2,
            action_window_s=600.0,
            fault_blame_threshold=3,
            host_health_floor=0.9,
        ),
        dry_run=(mode == "dry-run"),
        historian=hist,
        correlator=corr,
        recorder=rec,
        actuators={} if mode == "off" else {"drain_host": drain_actuator},
        gauges_fn=lambda: {
            f"host_health_{h}": (0.0 if drained[h] else rate[h])
            for h in range(hosts)
        },
        clock=vclock,
        id_factory=deterministic_ids("apd"),
        trace_id="fleet",
    )
    downtime_s = 0.0
    ideal_wall = 0.0
    tail_wall = tail_ideal = 0.0
    for step in range(1, params.steps + 1):
        spec = inj.take_host_slow(step)
        if spec is not None:
            idx = int(spec.device_index or 0)
            if not drained[idx]:
                rate[idx] = params.step_time_s / (
                    params.step_time_s + float(spec.slow_s)
                )
                rec.event(
                    "host_slow", kind="fault", trace_id="fleet", ts=vclock.t,
                    attrs={"step": step, "device_index": idx,
                           "slow_s": float(spec.slow_s)},
                )
        active = [h for h in range(hosts) if not drained[h]]
        rows_h = params.global_micro / len(active)
        step_s = max(
            rows_h * params.step_time_s / (rows_u * rate[h]) for h in active
        )
        ideal_s = params.global_micro * params.step_time_s / (
            rows_u * sum(rate)
        )
        now = vclock.advance(step_s)
        ideal_wall += ideal_s
        hist.record("step_time_s", step_s, ts=now)
        for h in range(hosts):
            hist.record(
                "hetero_host_health", 0.0 if drained[h] else rate[h],
                ts=now, labels={"host": str(h)},
            )
        if mode != "off" and step % params.check_every == 0:
            before = sum(drained)
            autopilot.tick(now=now)
            if sum(drained) > before:
                # Shedding a host is an emergency save + re-admit + cold
                # compile, exactly the shrink path's price.
                downtime_s += (
                    params.ckpt_save_s + params.resume_admit_s
                    + params.cold_compile_s
                )
                vclock.advance(
                    params.ckpt_save_s + params.resume_admit_s
                    + params.cold_compile_s
                )
        if step > params.steps - params.tail_steps:
            tail_wall += step_s
            tail_ideal += ideal_s
    stats = autopilot.stats()
    return {
        "mode": mode,
        "wall_s": round(vclock.t, 1),
        "ideal_wall_s": round(ideal_wall, 1),
        "downtime_s": round(downtime_s, 1),
        "goodput": round(ideal_wall / vclock.t, 4),
        "steady_goodput": round(tail_ideal / tail_wall, 4),
        "drained_hosts": [h for h in range(hosts) if drained[h]],
        "autopilot": stats,
        "decisions": autopilot.decisions(limit=0),
        "incidents": corr.incidents(limit=0),
        "incident_stats": corr.stats(),
    }


def _autopilot_action_legs(incidents: List[dict]) -> List[dict]:
    return [
        e
        for inc in incidents
        for e in inc["timeline"]
        if e["role"] == "action" and e["kind"] == "autopilot"
    ]


def autopilot_lane(
    seed: int = 0, params: HeteroTwinParams = HeteroTwinParams()
) -> dict:
    """Chaos A/B for the autopilot: armed vs off vs dry-run on one seeded
    slow-host fault plan. Gates: the armed loop's steady-state goodput
    beats (or matches) the uncontrolled fleet; dry-run emits the decision
    stream with zero actuations; every decision carries historian query
    inputs and its incident link; and the correlator shows the decision
    as the incident's action leg with the right ``action_source``."""
    plan = host_slow_plan(seed, params)
    slow_host = int(plan.specs[0].device_index or 0)
    off = replay_autopilot("off", plan, params)
    on = replay_autopilot("armed", plan, params)
    dry = replay_autopilot("dry-run", plan, params)
    explained = [
        d
        for run in (on, dry)
        for d in run["decisions"]
    ]
    gates = {
        "autopilot_on_ge_off": on["steady_goodput"] >= off["steady_goodput"],
        "armed_drained_slow_host": on["drained_hosts"] == [slow_host],
        "dry_run_zero_actuations": (
            dry["autopilot"]["actuations_total"] == 0
            and dry["drained_hosts"] == []
        ),
        "dry_run_emits_decisions": (
            dry["autopilot"]["decisions_total"] > 0
            and dry["autopilot"]["fired_total"] > 0
        ),
        "every_decision_explainable": bool(explained) and all(
            d["inputs"]["queries"]
            and d["inputs"]["incidents"]
            and d["hysteresis"]["required"] >= 1
            for d in explained
        ),
        "action_leg_sourced": (
            all(
                leg["action_source"] == "autopilot"
                for leg in _autopilot_action_legs(on["incidents"])
            )
            and all(
                leg["action_source"] == "autopilot-dryrun"
                for leg in _autopilot_action_legs(dry["incidents"])
            )
            and bool(_autopilot_action_legs(on["incidents"]))
            and bool(_autopilot_action_legs(dry["incidents"]))
        ),
    }
    return {
        "seed": seed,
        "slow_host": slow_host,
        "steady_goodput_on": on["steady_goodput"],
        "steady_goodput_off": off["steady_goodput"],
        "steady_goodput_dry": dry["steady_goodput"],
        "goodput_recovered": round(
            on["steady_goodput"] - off["steady_goodput"], 4
        ),
        "armed": {
            k: on["autopilot"][k]
            for k in ("decisions_total", "fired_total", "suppressed_total",
                      "actuations_total", "suppressed_by_reason")
        },
        "dry_run": {
            k: dry["autopilot"][k]
            for k in ("decisions_total", "fired_total", "suppressed_total",
                      "actuations_total", "suppressed_by_reason")
        },
        "incidents_armed": on["incident_stats"]["opened_by_trigger"],
        "gates": gates,
        "ok": all(gates.values()),
    }


def autopilot_bench_line(seed: int = 0) -> dict:
    """The autopilot's deterministic bench line, shared by ``bench.py``
    and ``tools/bench_sentinel.py``: chaos goodput A/B (armed vs off vs
    shadow) plus the decision-stream accounting on the seeded slow-host
    plan."""
    lane = autopilot_lane(seed=seed)
    return {
        "metric": "autopilot_chaos_ab",
        "value": lane["steady_goodput_on"],
        "unit": "steady-state chaos goodput, autopilot armed",
        "steady_goodput_off": lane["steady_goodput_off"],
        "steady_goodput_dry": lane["steady_goodput_dry"],
        "goodput_recovered": lane["goodput_recovered"],
        "decisions_armed": lane["armed"]["decisions_total"],
        "actuations_armed": lane["armed"]["actuations_total"],
        "decisions_dry": lane["dry_run"]["decisions_total"],
        "actuations_dry": lane["dry_run"]["actuations_total"],
        "gates": lane["gates"],
        "ok": lane["ok"],
    }


# -- control-plane scale lane --------------------------------------------------
#
# 100k jobs / 1M serving requests as a *measured* regime: push the real
# FleetScheduler, FleetRouter, MetricHistorian and IncidentCorrelator
# through two phases under one VirtualClock, profile where the control
# seconds go, and gate that control overhead per simulated fleet-second
# stays flat as the fleet's job/request history grows 100x. Any control
# cost that scales with history (a ring scan, an unindexed _subs walk, a
# per-sample lock round-trip) shows up here as a rising ratio before it
# shows up as a stuck production scheduler.


@dataclasses.dataclass
class ScaleLaneParams:
    """One control-plane scale configuration.

    ``small()`` and ``big()`` differ ONLY in job/request counts: the
    per-simulated-second workload — submission chunking, job duration
    mix, serving arrival rate, control cadence, replica churn — is
    identical, so control overhead per simulated fleet-second is
    directly comparable between them. A flat ratio means no control-
    plane cost grows with how much history the fleet has accumulated."""

    n_jobs: int = 1_000
    n_requests: int = 10_000
    max_concurrent: int = 128
    submit_chunk: int = 1_000
    poll_dt_s: float = 5.0
    n_tenants: int = 8
    n_replicas: int = 8
    replica_slots: int = 16
    request_rate_hz: float = 1_000.0
    control_period_s: float = 1.0
    churn_period_s: float = 2.5
    scrape_every_polls: int = 16
    correlate_every_s: float = 10.0

    @staticmethod
    def small() -> "ScaleLaneParams":
        return ScaleLaneParams()

    @staticmethod
    def big() -> "ScaleLaneParams":
        return ScaleLaneParams(n_jobs=100_000, n_requests=1_000_000)


class _ScaleJob:
    """Virtual-clock stand-in for one training attempt: runs for a fixed
    number of simulated seconds, then completes. ``watcher = None`` marks
    it non-preemptible, so submit -> admit -> reap is the whole lifecycle
    — exactly the per-job control cost the lane measures — with zero
    threads."""

    __slots__ = (
        "_clock", "_sim_s", "_done_at", "_st", "status",
        "current_step", "watcher", "preemption_reason", "_stop",
    )

    def __init__(self, clock: Callable[[], float], sim_s: float, status_enum):
        self._clock = clock
        self._sim_s = float(sim_s)
        self._done_at = math.inf
        self._st = status_enum
        self.status = status_enum.PENDING
        self.current_step = 0
        self.watcher = None
        self.preemption_reason = None
        self._stop = threading.Event()

    def start(self) -> None:
        self._done_at = self._clock() + self._sim_s
        self.status = self._st.RUNNING

    @property
    def is_alive(self) -> bool:
        st = self._st
        if self.status == st.RUNNING and self._clock() >= self._done_at:
            self.status = st.STOPPED if self._stop.is_set() else st.COMPLETED
            self.current_step = int(self._sim_s)
        return self.status in (st.PENDING, st.RUNNING)

    def join(self, timeout: Optional[float] = None) -> None:
        return None

    def describe(self) -> Dict[str, Any]:
        return {
            "status": getattr(self.status, "value", str(self.status)),
            "step": self.current_step,
        }


def scale_lane(seed: int = 0, params: Optional[ScaleLaneParams] = None) -> dict:
    """Drive ONE scale configuration through the real control plane under
    the virtual clock and profile where the control seconds went.

    Two phases share one flight recorder / historian / goodput ledger
    (installed process-wide for the run via the singleton setters,
    restored after):

    - **training**: ``params.n_jobs`` submissions through the real
      :class:`~tpu_engine.scheduler.FleetScheduler`. Chunked submits
      keep a bounded standing queue; the background pump is disabled and
      ``poll()`` is driven manually, so the run is single-threaded and
      byte-deterministic. Every completion settles its goodput trace
      through the recorder's per-trace index (the O(trace) read this
      lane exists to keep honest — it used to copy the whole ring per
      reaped job).
    - **serving**: ``params.n_requests`` through the real
      :class:`~tpu_engine.serving_fleet.FleetRouter` over a slot-model
      replica fleet — periodic weight refreshes, replica kill/revive
      churn (fault + resume events the correlator must open and
      resolve), batched historian ingest of every latency sample, and
      bounded-window percentile reads each control tick.

    Returns per-phase timings, ``overhead_us_per_fleet_s`` (control CPU
    microseconds per simulated fleet-second — THE scale metric), ring
    bounds, and a ``deterministic`` dict of every count that must be
    byte-identical across two runs of the same config.

    All timings are ``time.process_time()`` — the lane is single-threaded,
    so CPU time IS the control cost, and it does not absorb the
    descheduling noise a wall clock picks up on a loaded host (on a
    1-core CI box wall-clock phase timings varied +-25% run to run; the
    flatness gate needs better than that). The cyclic GC is paused for
    the run (restored after): a gen-2 pass landing inside a sub-second
    phase window is a +-17% lump that has nothing to do with control-
    plane flatness — the lane instead proves the live set is bounded
    directly (``rings_bounded``, including the scheduler's finished-
    history bound), which is what keeps real GC pauses flat at depth."""
    import gc

    from tpu_engine import goodput as goodput_mod
    from tpu_engine import tracing as tracing_mod
    from tpu_engine.mesh_runtime import MeshConfig
    from tpu_engine.scheduler import FleetScheduler, JobPriority
    from tpu_engine.serving_fleet import FleetRouter, _PercentileWindow
    from tpu_engine.sharding import TPUTrainConfig
    from tpu_engine.supervisor import JobStatus

    p = params or ScaleLaneParams.small()
    vclock = VirtualClock(0.0)
    # Small rings on purpose: even the small config saturates them during
    # its training phase, so correlator ingest normalizes a FULL ring in
    # both configs and the overhead ratio compares steady states, not a
    # warm ring against a cold one.
    rec = FlightRecorder(
        max_spans=1024, max_events=1024, clock=vclock,
        id_factory=deterministic_ids("ctl"),
    )
    hist = historian_mod.MetricHistorian(clock=vclock)
    # max_tracked sized above the standing submission window so every
    # trace settles through the full finalize path, none via eviction.
    ledger = GoodputLedger(clock=vclock, max_tracked=2 * p.submit_chunk + 256)
    corr = historian_mod.IncidentCorrelator(clock=vclock, stale_after_s=1e9)

    old_rec = tracing_mod.get_recorder()
    old_hist = historian_mod.get_historian()
    old_ledger = goodput_mod.get_ledger()
    tracing_mod.set_recorder(rec)
    historian_mod.set_historian(hist)
    goodput_mod.set_ledger(ledger)
    gc_was_enabled = gc.isenabled()
    gc.collect()
    gc.disable()
    try:
        # ---- phase 1: n_jobs through the real scheduler ----------------------
        cfg = TPUTrainConfig(
            model_name="gpt-tiny", mesh=MeshConfig(data=1, fsdp=1),
            micro_batch_size=1, seq_len=32, precision="fp32",
            total_steps=5, activation_checkpointing=False,
        )
        jcount = iter(range(1 << 30))

        def make_job(sub) -> _ScaleJob:
            return _ScaleJob(vclock, 30.0 + 7.5 * (next(jcount) % 9), JobStatus)

        sched = FleetScheduler(
            max_concurrent_jobs=p.max_concurrent,
            # Backfill must see the whole admissible window, or admission
            # throttles to 4 jobs per poll regardless of free capacity.
            backfill_depth=p.max_concurrent,
            job_factory=make_job,
            poll_interval_s=3600.0,
            grow_back=False,
            hetero_rebalance=False,
            # Pin the finished-history bound to the same constant for every
            # config, low enough that BOTH configs evict at steady state:
            # the flatness claim is "bounded live state => flat control
            # cost", so both sides of the ratio must hold the same live
            # set AND pay the same per-job eviction/deallocation cost (a
            # bound the small config never fills shows up as a flat ~30us
            # per-job surcharge on the big side only).
            max_finished_history=256,
        )
        sched._ensure_thread = lambda: None  # the lane owns the poll cadence
        prios = (JobPriority.NORMAL, JobPriority.LOW, JobPriority.HIGH)

        submit_s = poll_s = scrape_s = 0.0
        polls = scrapes = submitted = 0
        max_polls = 1_000 + 40 * (p.n_jobs // max(p.max_concurrent, 1) + 1)
        t_train0 = time.process_time()
        sim_train0 = vclock.now()
        while sched.completed_total + sched.failed_total < p.n_jobs:
            if (
                submitted < p.n_jobs
                and submitted - sched.completed_total <= p.submit_chunk // 2
            ):
                k = min(p.submit_chunk, p.n_jobs - submitted)
                t0 = time.process_time()
                for i in range(submitted, submitted + k):
                    sched.submit(
                        cfg,
                        priority=prios[i % 3],
                        submitter=f"team-{i % p.n_tenants}",
                    )
                submit_s += time.process_time() - t0
                submitted += k
            t0 = time.process_time()
            sched.poll()
            poll_s += time.process_time() - t0
            polls += 1
            if polls % p.scrape_every_polls == 0:
                t0 = time.process_time()
                sched.stats()
                scrape_s += time.process_time() - t0
                scrapes += 1
            vclock.advance(p.poll_dt_s)
            if polls > max_polls:
                raise RuntimeError(
                    f"scale lane wedged: {sched.completed_total}/{p.n_jobs} "
                    f"completed after {polls} polls"
                )
        train_wall_s = time.process_time() - t_train0
        sim_train_s = vclock.now() - sim_train0
        sched_stats = sched.stats()
        sched.shutdown()

        # ---- phase 2: n_requests through the real router ---------------------
        router = FleetRouter()
        lat_win = _PercentileWindow(window=512)
        tps = {f"r{j}": 1500.0 + 137.0 * j for j in range(p.n_replicas)}
        busy = {rid: 0 for rid in tps}
        down: set = set()
        inflight: list = []  # (finish_ts, replica_id) min-heap
        # 64 distinct prompt prefixes: a deterministic affinity working set.
        prompts = [
            [(seed * 131 + g * 17 + k) % 5003 for k in range(40)]
            for g in range(64)
        ]

        def _snapshot() -> Dict[str, Dict[str, Any]]:
            return {
                rid: {
                    "tokens_per_sec": tps[rid],
                    "free_slots": max(p.replica_slots - busy[rid], 0),
                    "slots": p.replica_slots,
                }
                for rid in tps if rid not in down
            }

        dt = 1.0 / p.request_rate_hz
        serve_t0 = vclock.now()
        next_control = serve_t0
        next_churn = serve_t0 + p.churn_period_s
        next_corr = serve_t0 + p.correlate_every_s
        churn_events = routed = misrouted = control_ticks = 0
        ingest_s = correlate_s = pct_s = 0.0
        lat_batch: list = []
        p50 = p99 = None
        router.update(_snapshot())
        t_serve0 = time.process_time()
        for i in range(p.n_requests):
            now = serve_t0 + i * dt
            vclock.set(now)
            while inflight and inflight[0][0] <= now:
                busy[heapq.heappop(inflight)[1]] -= 1
            if now >= next_churn:
                j = (churn_events // 2) % p.n_replicas
                rid = f"r{j}"
                if churn_events % 2 == 0:
                    down.add(rid)
                    rec.event(
                        "replica_down", kind="fault",
                        trace_id=f"srv-{churn_events // 2}", ts=now,
                        attrs={"replica": rid},
                    )
                else:
                    down.discard(rid)
                    rec.event(
                        "replica_resume", kind="supervisor",
                        trace_id=f"srv-{churn_events // 2}", ts=now,
                        attrs={"replica": rid},
                    )
                churn_events += 1
                next_churn += p.churn_period_s
            if now >= next_control:
                control_ticks += 1
                router.update(_snapshot())
                t0 = time.process_time()
                p50, p99 = lat_win.percentiles((0.50, 0.99))
                pct_s += time.process_time() - t0
                lat_batch.append(("serving_inflight", float(len(inflight))))
                if p99 is not None:
                    lat_batch.append(("serving_p99_ms", p99))
                t0 = time.process_time()
                hist.observe_batch(lat_batch, ts=now)
                ingest_s += time.process_time() - t0
                lat_batch = []
                next_control += p.control_period_s
            if now >= next_corr:
                t0 = time.process_time()
                corr.ingest(recorder=rec, now=now)
                correlate_s += time.process_time() - t0
                next_corr += p.correlate_every_s
            rid = router.route(prompts[(i * 7) % 64])
            if rid is None or rid in down:
                misrouted += 1
                continue
            routed += 1
            service_s = (40 + (i % 160)) / tps[rid]
            over = busy[rid] - p.replica_slots
            if over >= 0:
                service_s *= 1.0 + 0.1 * (over + 1)
            busy[rid] += 1
            heapq.heappush(inflight, (now + service_s, rid))
            lat_win.add(service_s * 1000.0)
            lat_batch.append(("serving_latency_ms", service_s * 1000.0))
        # Drain the tail, then settle the final tick / ingest / read.
        while inflight:
            ts_f, rid = heapq.heappop(inflight)
            busy[rid] -= 1
            if ts_f > vclock.now():
                vclock.set(ts_f)
        router.update(_snapshot())
        if lat_batch:
            t0 = time.process_time()
            hist.observe_batch(lat_batch, ts=vclock.now())
            ingest_s += time.process_time() - t0
        t0 = time.process_time()
        p50, p99 = lat_win.percentiles((0.50, 0.99))
        pct_s += time.process_time() - t0
        t0 = time.process_time()
        corr.ingest(recorder=rec, now=vclock.now())
        correlate_s += time.process_time() - t0
        serve_wall_s = time.process_time() - t_serve0
        sim_serve_s = vclock.now() - serve_t0
        route_s = max(serve_wall_s - ingest_s - correlate_s - pct_s, 0.0)

        # ---- accounting ------------------------------------------------------
        rec_stats = rec.stats()
        hist_stats = hist.stats()
        corr_stats = corr.stats()
        rings = {
            "recorder_spans": len(rec.spans(limit=0)),
            "recorder_events": len(rec.events(limit=0)),
            "recorder_open_spans": rec_stats["open_spans"],
            "recorder_trace_index": rec_stats["trace_index"],
            "historian_raw_samples": hist_stats["raw_samples"],
            "incidents_retained": len(corr.incidents(limit=0)),
            "scheduler_history": len(sched._subs),
        }
        rings_bounded = (
            rings["recorder_spans"] <= rec.max_spans
            and rings["recorder_events"] <= rec.max_events
            and rings["recorder_open_spans"] == 0
            and rings["recorder_trace_index"] <= rec.max_spans
            and rings["historian_raw_samples"]
                <= hist_stats["series"] * hist.raw_capacity
            and rings["incidents_retained"] <= corr.max_incidents
            and rings["scheduler_history"] <= sched.max_finished_history
        )
        ctl_s = (
            submit_s + poll_s + scrape_s
            + route_s + ingest_s + correlate_s + pct_s
        )
        sim_s = sim_train_s + sim_serve_s
        # Overhead is normalized by *delivered* fleet-seconds (job-seconds
        # at peak concurrency plus request-seconds at the offered rate),
        # not the measured virtual wall: the 1k-job run spends a far
        # larger fraction of its wall in ramp/drain tails where the fleet
        # is part-empty, which dilutes the small denominator and fakes a
        # 100x-scale slowdown that per-job costs do not show.
        work_s = (
            sum(30.0 + 7.5 * (i % 9) for i in range(p.n_jobs))
            / max(p.max_concurrent, 1)
            + p.n_requests / p.request_rate_hz
        )
        det = {
            "jobs": {
                "submitted": sched.submitted_total,
                "admitted": sched.admitted_total,
                "completed": sched.completed_total,
                "failed": sched.failed_total,
                "requeues": sched.requeues_total,
                "preemptions": sched.preemptions_total,
                "queue_depth_end": sched_stats["queue_depth"],
                "history_evicted": sched.finished_evicted_total,
                "polls": polls,
            },
            "serving": {
                "routed": routed,
                "misrouted": misrouted,
                "router_routed_total": router.routed_total,
                "affinity_hits": router.affinity_hits,
                "control_ticks": control_ticks,
                "churn_events": churn_events,
                "p50_ms": None if p50 is None else round(p50, 6),
                "p99_ms": None if p99 is None else round(p99, 6),
            },
            "historian": {
                "samples_total": hist_stats["samples_total"],
                "batches": hist_stats["ingest_batch_total"],
                "batched_samples": hist_stats["ingest_batched_samples_total"],
            },
            "recorder": {
                "spans_total": rec_stats["spans_total"],
                "events_total": rec_stats["events_total"],
                "spans_dropped": rec_stats["spans_dropped"],
                "events_dropped": rec_stats["events_dropped"],
            },
            "incidents": {
                "opened": corr_stats["opened_total"],
                "resolved": corr_stats["resolved_total"],
                "correlated": corr_stats["correlated_total"],
                "ignored": corr_stats["ignored_total"],
            },
        }
        return {
            "params": dataclasses.asdict(p),
            "phases": {
                "submit_s": round(submit_s, 4),
                "sched_poll_s": round(poll_s, 4),
                "scrape_s": round(scrape_s, 4),
                "route_s": round(route_s, 4),
                "historian_ingest_s": round(ingest_s, 4),
                "correlate_s": round(correlate_s, 4),
                "percentile_s": round(pct_s, 4),
                "train_wall_s": round(train_wall_s, 4),
                "serve_wall_s": round(serve_wall_s, 4),
            },
            "scrapes": scrapes,
            "control_s": round(ctl_s, 4),
            "sim_fleet_s": round(sim_s, 3),
            "work_fleet_s": round(work_s, 3),
            "overhead_us_per_fleet_s": round(ctl_s / max(work_s, 1e-9) * 1e6, 3),
            # Marginal control cost per unit of work — the saturation-
            # independent flatness signal (the 1k config spends a large
            # share of its polls in half-empty ramp/drain tails, which
            # shifts any wall-clock-per-fleet-second ratio without any
            # per-job cost changing).
            "control_us_per_job": round(
                (submit_s + poll_s + scrape_s) / max(p.n_jobs, 1) * 1e6, 3
            ),
            "control_us_per_request": round(
                (route_s + ingest_s + correlate_s + pct_s)
                / max(p.n_requests, 1) * 1e6, 3
            ),
            "rings": rings,
            "rings_bounded": rings_bounded,
            "deterministic": det,
        }
    finally:
        if gc_was_enabled:
            gc.enable()
            gc.collect()
        tracing_mod.set_recorder(old_rec)
        historian_mod.set_historian(old_hist)
        goodput_mod.set_ledger(old_ledger)


def ctl_scale_profile(
    seed: int = 0,
    small: Optional[ScaleLaneParams] = None,
    big: Optional[ScaleLaneParams] = None,
) -> dict:
    """The scale lane's exit gate: run the small (1k-job / 10k-request)
    configuration five times — every run's ``deterministic`` dict must be
    byte-identical, and the median marginal cost is the denominator —
    then the big (100k-job / 1M-request) configuration twice, gating that
    the control cost per job and per request stays flat (<= 1.25x) as
    job/request volume grows 100x."""
    small = small or ScaleLaneParams.small()
    big = big or ScaleLaneParams.big()
    # Warmup (discarded): the first lane run in a process pays one-time
    # import/alloc/branch-warming costs that would land entirely on the
    # small side of the ratio.
    scale_lane(seed=seed, params=ScaleLaneParams(n_jobs=100, n_requests=1_000))
    # The small config is sub-second, so any single run is at the mercy
    # of allocator/cpufreq lumps: take the median of five, and require
    # every run's deterministic counts to be byte-identical.
    small_runs = [scale_lane(seed=seed, params=small) for _ in range(5)]
    digests = {
        json.dumps(r["deterministic"], sort_keys=True) for r in small_runs
    }
    overheads = sorted(r["overhead_us_per_fleet_s"] for r in small_runs)
    overhead_small = overheads[len(overheads) // 2]
    run_small = small_runs[0]
    # The big config runs twice: the deterministic counts must agree at
    # depth too, and the flatness numerator takes the cheaper run — a
    # shared-host tenant polluting the cache for one 20-second window
    # must not read as superlinear control cost, while a real
    # superlinearity (an unbounded index, an O(history) scan) inflates
    # even the best of two runs.
    big_runs = [scale_lane(seed=seed, params=big) for _ in range(2)]
    big_digests = {
        json.dumps(r["deterministic"], sort_keys=True) for r in big_runs
    }
    run_big = big_runs[0]
    overhead_big = min(r["overhead_us_per_fleet_s"] for r in big_runs)

    def _median(key: str) -> float:
        vals = sorted(r[key] for r in small_runs)
        return vals[len(vals) // 2]

    # Flatness is gated on marginal control cost per job and per request:
    # that is the statement "100x more jobs costs 100x more control work,
    # not more" with the small config's ramp-tail share factored out. The
    # per-fleet-second overheads are reported alongside for the capacity
    # framing (what fraction of a core one fleet-second of control takes).
    big_per_job = min(r["control_us_per_job"] for r in big_runs)
    big_per_req = min(r["control_us_per_request"] for r in big_runs)
    ratio_job = big_per_job / max(_median("control_us_per_job"), 1e-9)
    ratio_req = big_per_req / max(_median("control_us_per_request"), 1e-9)
    ratio = max(ratio_job, ratio_req)
    served_frac = (
        run_big["deterministic"]["serving"]["routed"] / max(big.n_requests, 1)
    )
    gates = {
        "deterministic": len(digests) == 1 and len(big_digests) == 1,
        "overhead_flat_1k_to_100k": ratio <= 1.25,
        "all_jobs_completed": (
            run_small["deterministic"]["jobs"]["completed"] == small.n_jobs
            and run_big["deterministic"]["jobs"]["completed"] == big.n_jobs
        ),
        "requests_routed_98pct": served_frac >= 0.98,
        "rings_bounded": run_small["rings_bounded"] and run_big["rings_bounded"],
    }
    return {
        "small": run_small,
        "big": run_big,
        "overhead_small_us_per_fleet_s": overhead_small,
        "overhead_small_spread_us": [overheads[0], overheads[-1]],
        "overhead_big_us_per_fleet_s": overhead_big,
        "per_job_us": {
            "small": _median("control_us_per_job"),
            "big": big_per_job,
            "ratio": round(ratio_job, 4),
        },
        "per_request_us": {
            "small": _median("control_us_per_request"),
            "big": big_per_req,
            "ratio": round(ratio_req, 4),
        },
        "overhead_ratio": round(ratio, 4),
        "gates": gates,
        "ok": all(gates.values()),
    }


def ctl_scale_bench_line(seed: int = 0, profile: Optional[dict] = None) -> dict:
    """Control-plane scale bench line shared by ``bench.py`` and
    ``tools/bench_sentinel.py``. The gated value and counters are the
    deterministic job/request totals; the overhead ratio and per-phase
    wall profile ride along under timing keys the sentinel treats as
    noisy. The flatness and determinism regressions are caught through
    the ``gates`` booleans. Pass ``profile`` (a :func:`ctl_scale_profile`
    result) to reuse an already-computed run."""
    prof = profile if profile is not None else ctl_scale_profile(seed=seed)
    big = prof["big"]["deterministic"]
    return {
        "metric": "ctl_scale",
        "value": float(big["jobs"]["completed"]),
        "unit": "jobs completed through the real scheduler, big config",
        "requests_routed": big["serving"]["routed"],
        "historian_samples": big["historian"]["samples_total"],
        "incidents_opened": big["incidents"]["opened"],
        "incidents_resolved": big["incidents"]["resolved"],
        "overhead": {
            "small_us_per_fleet_s": prof["overhead_small_us_per_fleet_s"],
            "big_us_per_fleet_s": prof["overhead_big_us_per_fleet_s"],
            "per_job_us_small": prof["per_job_us"]["small"],
            "per_job_us_big": prof["per_job_us"]["big"],
            "per_request_us_small": prof["per_request_us"]["small"],
            "per_request_us_big": prof["per_request_us"]["big"],
            "ratio": prof["overhead_ratio"],
        },
        "phases": prof["big"]["phases"],
        "gates": prof["gates"],
        "ok": prof["ok"],
    }


# -- fleet prefix plane lane ---------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PrefixPlaneLaneParams:
    """Many-tenant shared-prefix serving scenario: more hot system
    prompts than any one replica's prefix cache can retain, so the
    fleet's TTFT is set by how prefix residency is managed — per-replica
    LRU (baseline) vs the fleet prefix plane (radix index routing +
    host-RAM tier)."""

    duration_s: float = 480.0
    dt_s: float = 0.05
    control_period_s: float = 1.0
    n_replicas: int = 4
    slots: int = 8
    tokens_per_slot_s: float = 30.0
    chips_per_replica: int = 1
    # Prefill legs: full prompt (cold), resident-prefix tail, and
    # host-tier rehydration (host->HBM copy + tail) — between the two.
    prefill_s: float = 1.2
    prefill_hit_s: float = 0.15
    prefill_host_s: float = 0.35
    # 32 hot tenants vs 4 replicas x 4 resident prefixes: half the
    # working set cannot be device-resident anywhere.
    n_prefixes: int = 32
    prefix_len: int = 32
    replica_cache_prefixes: int = 4
    # Host tier capacity model: one int8 KVHandoff wire payload per
    # prefix (a 32-token llama-1b prefix is ~0.2 MiB; 1 MiB is a round
    # conservative stand-in), budget big enough to absorb the overflow.
    host_entry_bytes: int = 1 << 20
    host_budget_entries: int = 64
    base_rps: float = 4.0
    burst_rps: float = 10.0
    burst_every_s: float = 120.0
    burst_len_s: float = 30.0
    mean_new_tokens: float = 48.0
    min_new_tokens: int = 8
    warmup_s: float = 60.0


class _PrefixLaneReplica:
    """Capacity model of one decode replica for the prefix-plane lane.

    The lane's dispatch loop decides each admission's prefill leg
    (cold / resident / host-rehydrated) — in baseline mode from this
    replica's own bounded LRU, in plane mode from
    ``PrefixPlane.observe_admit`` — so the replica itself only runs
    slots and stamps ``first_token_at`` when prefill drains."""

    def __init__(self, rid: str, params: PrefixPlaneLaneParams):
        self.rid = rid
        self.params = params
        self.rate = params.tokens_per_slot_s
        self.active: List[dict] = []
        # Baseline per-replica residency: LRU over prefix ids, capped at
        # what the replica's device cache could actually hold.
        self.cache: "collections.OrderedDict[int, None]" = (
            collections.OrderedDict()
        )
        self.tokens_out = 0.0

    def free_slots(self) -> int:
        return self.params.slots - len(self.active)

    def touch(self, pid: int) -> bool:
        """Baseline residency: True on hit; a miss inserts and LRU-evicts
        past the per-replica budget (the eviction is silent — per-replica
        LRU has nowhere to put the overflow, which is the point)."""
        if pid in self.cache:
            self.cache.move_to_end(pid)
            return True
        self.cache[pid] = None
        while len(self.cache) > self.params.replica_cache_prefixes:
            self.cache.popitem(last=False)
        return False

    def admit(self, req: dict, prefill_s: float) -> None:
        self.active.append({
            "req": req,
            "prefill_left": float(prefill_s),
            "tokens_left": float(req["n_new"]),
        })

    def step(self, now: float, dt: float, done: List[dict]) -> None:
        for sl in list(self.active):
            if sl["prefill_left"] > 0:
                sl["prefill_left"] -= dt
                if sl["prefill_left"] <= 0:
                    # First token lands as prefill drains (the prefill
                    # logits seed it) — the TTFT stamp the A/B gates on.
                    sl["req"]["first_token_at"] = now
                continue
            produced = min(self.rate * dt, sl["tokens_left"])
            sl["tokens_left"] -= produced
            self.tokens_out += produced
            if sl["tokens_left"] <= 0:
                sl["req"]["done_at"] = now
                sl["req"]["replica"] = self.rid
                done.append(sl["req"])
                self.active.remove(sl)

    def router_stats(self) -> dict:
        busy = sum(1 for s in self.active if s["prefill_left"] <= 0)
        return {
            "tokens_per_sec": self.rate * max(busy, 0.2),
            "free_slots": self.free_slots(),
            "slots": self.params.slots,
        }


def prefix_plane_lane(
    seed: int,
    plane: bool,
    params: PrefixPlaneLaneParams = PrefixPlaneLaneParams(),
) -> dict:
    """One seeded many-tenant shared-prefix run at fixed chips, through
    the REAL :class:`~tpu_engine.serving_fleet.FleetRouter` — baseline
    (``plane=False``: affinity pinning + per-replica LRU residency) or
    with a real :class:`~tpu_engine.prefix_plane.PrefixPlane` attached
    (radix-index routing, host-tier absorption of replica-cache
    overflow, rehydration on host hits). Fully virtual-clock: same seed
    and mode give a byte-identical report."""
    from tpu_engine.prefix_plane import HostKVTier, PrefixPlane
    from tpu_engine.serving_fleet import FleetRouter

    clock = VirtualClock(0.0)
    pplane = None
    if plane:
        hist = historian_mod.MetricHistorian()
        host = HostKVTier(
            budget_bytes=params.host_budget_entries * params.host_entry_bytes,
            historian=hist, clock=clock, reuse_window_s=params.duration_s,
        )
        pplane = PrefixPlane(
            prefix_tokens=params.prefix_len,
            replica_prefix_budget=params.replica_cache_prefixes,
            host=host, historian=hist, clock=clock,
            # Capacity-model spill: the evicted entry's modeled wire bytes.
            spill=lambda prefix, rid: params.host_entry_bytes,
        )
    router = FleetRouter(affinity_tokens=params.prefix_len,
                         prefix_plane=pplane)
    replicas = {
        f"r{i}": _PrefixLaneReplica(f"r{i}", params)
        for i in range(params.n_replicas)
    }
    trace = bursty_arrivals(
        seed,
        duration_s=params.duration_s,
        base_rps=params.base_rps,
        burst_rps=params.burst_rps,
        burst_every_s=params.burst_every_s,
        burst_len_s=params.burst_len_s,
        n_prefixes=params.n_prefixes,
        prefix_len=params.prefix_len,
        mean_new_tokens=params.mean_new_tokens,
        min_new_tokens=params.min_new_tokens,
    )
    queue: List[dict] = []
    done: List[dict] = []
    kinds = {"replica": 0, "host": 0, "cold": 0}

    def control(t: float) -> None:
        router.update({r.rid: r.router_stats() for r in replicas.values()})

    def tick(t: float) -> None:
        clock.set(t)
        free_total = sum(r.free_slots() for r in replicas.values())
        while queue and free_total > 0:
            req = queue[0]
            rid = router.route(req["prompt"])
            rep = replicas.get(rid) if rid else None
            if rep is None or rep.free_slots() <= 0:
                break  # full pick: weights refresh next control period
            queue.pop(0)
            free_total -= 1
            if pplane is not None:
                obs = pplane.observe_admit(req["prompt"], rid, now=t)
                kinds[obs["kind"]] += 1
                prefill = {
                    "replica": params.prefill_hit_s,
                    "host": params.prefill_host_s,
                    "cold": params.prefill_s,
                }[obs["kind"]]
            else:
                hit = rep.touch(req["prefix_id"])
                kinds["replica" if hit else "cold"] += 1
                prefill = params.prefill_hit_s if hit else params.prefill_s
            rep.admit(req, prefill)
        for r in replicas.values():
            r.step(t, params.dt_s, done)

    run_open_loop(
        trace,
        dt=params.dt_s,
        duration_s=params.duration_s,
        pending=lambda: queue or any(r.active for r in replicas.values()),
        arrive=queue.append,
        tick=tick,
        control=control,
        control_period_s=params.control_period_s,
        safety_factor=3.0,
    )

    total_chips = params.n_replicas * params.chips_per_replica
    metrics = serving_metrics(done, [], warmup_s=params.warmup_s,
                              total_chips=total_chips, dt_s=params.dt_s)
    out = {
        "mode": "plane" if plane else "baseline",
        "metrics": metrics,
        "admission_kinds": dict(kinds),
        "router": {
            k: v for k, v in router.stats().items() if k != "prefix_plane"
        },
    }
    if pplane is not None:
        st = pplane.stats()
        out["plane"] = st
        out["host_occupancy"] = st["host"]["occupancy"]
    return out


def prefix_plane_ab(
    seed: int = 0,
    params: PrefixPlaneLaneParams = PrefixPlaneLaneParams(),
) -> dict:
    """The prefix-plane exit gate: baseline vs plane at EQUAL chips on
    the same seeded trace, a byte-identical plane repeat (determinism),
    and the estimator's structured host-budget rejection."""
    from tpu_engine.hbm_estimate import HostBudgetExceeded, estimate_serving_hbm

    base = prefix_plane_lane(seed, plane=False, params=params)
    plane = prefix_plane_lane(seed, plane=True, params=params)
    repeat = prefix_plane_lane(seed, plane=True, params=params)

    b, p = base["metrics"], plane["metrics"]
    improvement = round(b["ttft_p99_ms"] / max(p["ttft_p99_ms"], 1e-9), 2)
    tps_ratio = round(p["tokens_per_sec"] / max(b["tokens_per_sec"], 1e-9), 4)

    # Admission honesty: a sane host tier budgets through the estimator;
    # an oversubscribed one is refused with a structured reason.
    est = estimate_serving_hbm(
        "llama-1b", params.slots, 2048,
        host_prefix_tokens=params.host_budget_entries * params.prefix_len,
        host_budget_gib=4.0,
    )
    rejection = None
    try:
        estimate_serving_hbm(
            "llama-1b", params.slots, 2048,
            host_prefix_tokens=1 << 30, host_budget_gib=1.0,
        )
    except HostBudgetExceeded as e:
        rejection = e.reason

    gates = {
        "plane_beats_baseline_p99_ttft_2x": improvement >= 2.0,
        "tokens_per_sec_no_worse": tps_ratio >= 0.99,
        "deterministic_repeat": plane == repeat,
        "host_tier_absorbs_overflow": (
            plane.get("plane", {}).get("host", {}).get("stores", 0) > 0
            and plane.get("plane", {}).get("host_rehydrations", 0) > 0
        ),
        "host_budget_rejected": (
            rejection is not None
            and rejection.get("kind") == "host_budget_exceeded"
            and est is not None and est.host_gib > 0
        ),
    }
    return {
        "baseline": base,
        "plane": plane,
        "ttft_p99_improvement": improvement,
        "tokens_per_sec_ratio": tps_ratio,
        "host_tier_gib": None if est is None else est.host_gib,
        "host_budget_rejection": rejection,
        "gates": gates,
        "ok": all(gates.values()),
    }


def prefix_plane_bench_line(seed: int = 0, ab: Optional[dict] = None) -> dict:
    """The prefix plane's deterministic bench line, shared by ``bench.py``
    and ``tools/bench_sentinel.py``. The gated value is the baseline/plane
    p99 TTFT ratio on the seeded shared-prefix trace — deterministic under
    the virtual clock, so the sentinel gates it like the disagg A/B."""
    res = ab if ab is not None else prefix_plane_ab(seed=seed)
    plane = res["plane"]
    return {
        "metric": "prefix_plane",
        "value": res["ttft_p99_improvement"],
        "unit": "baseline/plane p99 TTFT ratio, shared-prefix trace",
        "baseline_ttft_p99_ms": res["baseline"]["metrics"]["ttft_p99_ms"],
        "plane_ttft_p99_ms": plane["metrics"]["ttft_p99_ms"],
        "tokens_per_sec_ratio": res["tokens_per_sec_ratio"],
        "host_occupancy": plane.get("host_occupancy", 0.0),
        "host_stores": plane.get("plane", {}).get("host", {}).get("stores", 0),
        "host_rehydrations": plane.get("plane", {}).get("host_rehydrations", 0),
        "admission_kinds": plane["admission_kinds"],
        "host_tier_gib": res["host_tier_gib"],
        "gates": res["gates"],
        "ok": res["ok"],
    }


# -- reshard lane: topology-changing resume vs topology-locked restart --------


@dataclasses.dataclass(frozen=True)
class ReshardLaneParams:
    """The reshard exit-gate scenario knobs. ``state_bytes`` prices the
    remap leg through :func:`tpu_engine.reshard.reshard_cost_s` — the
    default is a ~1B-param job (fp32 master + two Adam moments); the
    MTTR budget is the ratio against the same-trace same-topology warm
    self-heal mean (PR 10's number re-derived in-process)."""

    train: TrainTwinParams = TrainTwinParams(layout_prefix="reshard")
    n_faults: int = 12
    state_bytes: int = 12_000_000_000
    mttr_budget_ratio: float = 1.5


def _reshard_layout_key(use: int, flipped: bool, params: TrainTwinParams) -> str:
    """Layout key for ``use`` chips under one of its two factorizations:
    canonical ``data(use/model_axis)×fsdp(model_axis)`` or the flipped
    alternate — the topology change every reshard resume bridges."""
    d, m = use // params.model_axis, params.model_axis
    if flipped:
        d, m = m, d
    return f"{params.layout_prefix}|data{d}xfsdp{m}"


def _keyed_compile(
    index: Optional[CompileCacheIndex],
    key: str,
    params: TrainTwinParams,
    precompile: bool,
) -> Tuple[float, bool]:
    """Compile leg for an explicit layout key. With ``precompile`` the
    scheduler compiled the target layout in the background before the
    cutover (the grow-back discipline), so only the warm relink lands on
    the critical path."""
    if index is None:
        return params.cold_compile_s, False
    if precompile and not index.is_warm(key):
        index.record(key, params.cold_compile_s, cache_hit=False,
                     label=key.split("|", 1)[1], model=params.layout_prefix,
                     via="precompile")
    if index.is_warm(key):
        index.record(key, params.warm_compile_s, cache_hit=True,
                     via=params.layout_prefix)
        return params.warm_compile_s, True
    index.record(key, params.cold_compile_s, cache_hit=False,
                 label=key.split("|", 1)[1], model=params.layout_prefix,
                 via=params.layout_prefix)
    return params.cold_compile_s, False


def replay_reshard_resume(
    events: List[dict],
    params: TrainTwinParams = TrainTwinParams(layout_prefix="reshard"),
    state_bytes: int = 12_000_000_000,
    compile_index: Optional[CompileCacheIndex] = None,
) -> dict:
    """Self-heal where every resume lands on a *different factorization*
    of the surviving chips (data4×fsdp2 → data2×fsdp4 and back), so each
    recovery pays the reshard plane's remap leg
    (:func:`tpu_engine.reshard.reshard_cost_s` over ``state_bytes``) on
    top of save + admit + compile. Zero lost steps, like
    :func:`replay_self_heal`; the A/B against that lane isolates what
    topology freedom costs."""
    from tpu_engine import reshard as reshard_mod

    reshard_s_per = reshard_mod.reshard_cost_s(state_bytes)
    clock = 0.0
    healthy = params.n_chips
    flipped = False  # which factorization the job currently runs under
    pending: List[float] = []
    mttrs: List[float] = []
    grow_backs = 0
    degraded_s = 0.0
    warm_resumes = 0
    cold_resumes = 0
    compile_s_total = 0.0
    reshard_s_total = 0.0
    topology_changes = 0
    i = 0
    for step in range(1, params.total_steps + 1):
        # Grow back onto the canonical factorization of the larger mesh —
        # a topology change too, so the remap leg rides the cutover.
        while pending and pending[0] <= clock and healthy < params.n_chips:
            pending.pop(0)
            healthy += 1
            if _usable(healthy, params) > _usable(healthy - 1, params):
                key = _reshard_layout_key(_usable(healthy, params), False, params)
                g_compile_s, g_warm = _keyed_compile(
                    compile_index, key, params, precompile=True
                )
                clock += (params.ckpt_save_s + params.resume_admit_s
                          + g_compile_s + reshard_s_per)
                compile_s_total += g_compile_s
                reshard_s_total += reshard_s_per
                topology_changes += 1
                flipped = False
                warm_resumes += 1 if g_warm else 0
                cold_resumes += 0 if g_warm else 1
                grow_backs += 1
        use = _usable(healthy, params)
        step_t = params.step_time_s * params.n_chips / use
        clock += step_t
        if use < params.n_chips:
            degraded_s += step_t
        if step % params.ckpt_interval_steps == 0:
            clock += params.ckpt_save_s
        if i < len(events) and step >= events[i]["step"]:
            i += 1
            healthy -= 1
            # Shrink-resume onto the ALTERNATE factorization of what
            # survives: emergency save, re-admit, compile (warm iff the
            # index has seen that layout), then the state remap.
            flipped = not flipped
            key = _reshard_layout_key(_usable(healthy, params), flipped, params)
            compile_s, warm = _keyed_compile(
                compile_index, key, params, precompile=False
            )
            down = (params.ckpt_save_s + params.resume_admit_s
                    + compile_s + reshard_s_per)
            clock += down
            compile_s_total += compile_s
            reshard_s_total += reshard_s_per
            topology_changes += 1
            warm_resumes += 1 if warm else 0
            cold_resumes += 0 if warm else 1
            mttrs.append(step_t + down)
            pending.append(clock + events[i - 1]["recovery_s"])
            pending.sort()
    wall = clock
    return {
        "policy": "reshard-resume",
        "compile_index": compile_index is not None,
        "wall_s": round(wall, 1),
        "steps_run": params.total_steps,
        "lost_steps": 0,
        "faults": len(mttrs),
        "grow_backs": grow_backs,
        "topology_changes": topology_changes,
        "reshard_s_per_resume": round(reshard_s_per, 2),
        "reshard_s_total": round(reshard_s_total, 1),
        "degraded_step_s": round(degraded_s, 1),
        "warm_resumes": warm_resumes,
        "cold_resumes": cold_resumes,
        "compile_s_total": round(compile_s_total, 1),
        "mttr_mean_s": round(sum(mttrs) / len(mttrs), 2) if mttrs else 0.0,
        "mttr_max_s": round(max(mttrs), 2) if mttrs else 0.0,
        "goodput": round(params.total_steps * params.step_time_s / wall, 4),
    }


def reshard_roundtrip_report(seed: int = 0) -> dict:
    """REAL-executor reshard round trip on the host-platform device grid:
    a train-style sharded pytree saved under ``data4×fsdp2`` through the
    real Orbax manager restores — via
    :func:`tpu_engine.reshard.restore_resharded` — onto ``data2×fsdp4``
    and a *shrunk* 6-chip ``data3×fsdp2`` mesh, byte-parity-gated leaf
    by leaf against the source bytes."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from tpu_engine import reshard as reshard_mod
    from tpu_engine.checkpoint import TrainCheckpointManager

    devs = jax.devices()
    if len(devs) < 8:
        return {"skipped": f"needs 8 devices, have {len(devs)}", "ok": False}
    rng = np.random.default_rng(seed)
    host = {
        "params": {
            "w": rng.standard_normal((16, 8)).astype(np.float32),
            "b": rng.standard_normal((8,)).astype(np.float32),
        },
        "opt": {
            "mu": rng.standard_normal((16, 8)).astype(np.float32),
            "nu": rng.standard_normal((16, 8)).astype(np.float32),
        },
    }
    specs = {
        "params": {"w": PartitionSpec("fsdp"), "b": PartitionSpec("fsdp")},
        "opt": {"mu": PartitionSpec("fsdp"), "nu": PartitionSpec("fsdp")},
    }
    want = reshard_mod.leaf_checksums(host)

    def mesh_for(data: int, fsdp: int) -> Mesh:
        grid = np.array(devs[: data * fsdp]).reshape(data, fsdp)
        return Mesh(grid, ("data", "fsdp"))

    src_mesh = mesh_for(4, 2)
    placed = jax.tree.map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(src_mesh, spec)),
        host, specs,
    )
    out: dict = {"targets": []}
    with tempfile.TemporaryDirectory() as tmp:
        mgr = TrainCheckpointManager(tmp, async_save=False)
        saved = mgr.save(100, placed, wait=True)
        reshard_mod.write_topology(tmp, reshard_mod.mesh_topology(src_mesh))
        out["saved"] = bool(saved)
        out["saved_topology"] = reshard_mod.read_topology(tmp)
        for d, f in ((2, 4), (3, 2)):
            tgt_mesh = mesh_for(d, f)
            abstract = jax.tree.map(
                lambda leaf, spec: jax.ShapeDtypeStruct(
                    leaf.shape, leaf.dtype,
                    sharding=NamedSharding(tgt_mesh, spec),
                ),
                host, specs,
            )
            _s, state, report = reshard_mod.restore_resharded(
                mgr, abstract, saved_topology=out["saved_topology"]
            )
            got = reshard_mod.leaf_checksums(state) if state is not None else {}
            out["targets"].append({
                "topology": reshard_mod.mesh_topology(tgt_mesh),
                "step": report.get("step"),
                "parity_ok": bool(report.get("parity_ok")),
                "leaves": report.get("leaves"),
                "bytes_remapped": report.get("bytes_remapped"),
                "byte_parity_vs_source": got == want,
            })
    out["ok"] = bool(out["targets"]) and all(
        t["parity_ok"] and t["byte_parity_vs_source"] and t["step"] == 100
        for t in out["targets"]
    )
    return out


def _pump_until_done(engine: Any, rids: List[int], steps: int = 600) -> List[list]:
    for _ in range(steps):
        if all(engine.result(r)["status"] == "done" for r in rids):
            break
        engine.step()
    return [engine.result(r)["tokens"] for r in rids]


def reshard_migration_report(seed: int = 0) -> dict:
    """REAL gpt-tiny pool migration: a source replica holding live
    ``hold_kv`` requests and a resident shared prefix drains onto a
    destination pool of *different* chunk/lane geometry and int8 storage
    via :func:`tpu_engine.reshard.migrate_held_requests`. Every held
    request must complete on the destination (stitched streams within
    the documented one-token int8 bound of the unified baseline), and
    the prefix payload must cross both replica→replica and host-tier
    legs. Engines are caller-stepped; same seed → same weights → a
    deterministic report (the virtual migration MTTR is the cost model
    over the actual wire bytes, not wall clock)."""
    import numpy as np

    from tpu_engine import reshard as reshard_mod
    from tpu_engine.prefix_plane import HostKVTier
    from tpu_engine.serving_fleet import ServingReplicaSpec, build_replica_engine

    prompts = [[11, 7, 23, 42, 5], [3, 1, 4, 15, 9, 2]]
    max_new = 8
    src = build_replica_engine(ServingReplicaSpec(
        model_name="gpt-tiny", max_slots=4, max_len=96, prefill_chunk=16,
        prefix_cache_tokens=256,
    ))
    dst = build_replica_engine(ServingReplicaSpec(
        model_name="gpt-tiny", max_slots=4, max_len=128, prefill_chunk=32,
        kv_quant=True, prefix_cache_tokens=256,
    ))
    ref = build_replica_engine(ServingReplicaSpec(
        model_name="gpt-tiny", max_slots=2, max_len=96, prefill_chunk=16,
    ))

    # Unified baseline: the whole request on one replica.
    refs = [
        _pump_until_done(ref, [ref.submit(p, max_new_tokens=max_new)])[0]
        for p in prompts
    ]

    # Live requests: first token on the source, KV held for migration.
    first: List[int] = []
    for p in prompts:
        rid = src.submit(p, max_new_tokens=1, hold_kv=True)
        first.append(_pump_until_done(src, [rid])[0][0])

    # A shared prefix resident in the source cache (and spilled to the
    # host tier) — the prefix-plane payloads a drain must carry along.
    sys_tokens = np.random.default_rng(seed + 1).integers(1, 250, 64).tolist()
    _pump_until_done(src, [
        src.submit(sys_tokens + [9, 9], max_new_tokens=2),
        src.submit(sys_tokens + [8, 8], max_new_tokens=2),
    ])
    key = max(src._prefix_cache._entries, key=len)
    tier = HostKVTier(budget_bytes=64 << 20,
                      historian=historian_mod.MetricHistorian(),
                      clock=VirtualClock(0.0))
    tier.put(key, handoff=src.export_prefix(list(key)), now=0.0)

    migration = reshard_mod.migrate_held_requests(
        src, dst, max_new_tokens=max_new - 1
    )
    prefix_replica = reshard_mod.migrate_prefix(src, dst, list(key))
    prefix_host = reshard_mod.rehydrate_from_host(tier, list(key), dst, now=1.0)

    dst_tokens = _pump_until_done(dst, list(migration["mapping"].values()))
    completed = sum(1 for t in dst_tokens if len(t) == max_new - 1)
    reshard_mod.note_migrated_completions(completed)
    mismatches = sum(
        a != b
        for f0, tail, want in zip(first, dst_tokens, refs)
        for a, b in zip([f0, *tail], want)
    )
    return {
        "migrated": int(migration["migrated"]),
        "completed": int(completed),
        "held_left_on_src": len(src.held_requests()),
        "wire_bytes": int(migration["wire_bytes"]),
        "migration_mttr_s": round(
            reshard_mod.reshard_cost_s(migration["wire_bytes"]), 3
        ),
        "parity_mismatches": int(mismatches),
        "parity_tokens": sum(len(r) for r in refs),
        "prefix_replica_migrated": bool(prefix_replica),
        "prefix_host_rehydrated": bool(prefix_host),
        "prefix_tokens": len(key),
        "dst_kv_quant": True,
    }


def reshard_ab(
    seed: int = 0, params: ReshardLaneParams = ReshardLaneParams()
) -> dict:
    """The reshard exit gate: same seeded chip-fault trace through (a)
    same-topology warm self-heal (PR 10's MTTR reference, re-derived
    in-process), (b) topology-changing reshard resume, (c) the
    topology-locked die-and-restart baseline that loses steps waiting
    for the exact mesh — plus the real-executor restore round trip and
    the real-engine KV/prefix migration, and a byte-identical repeat."""
    events = chip_fault_timeline(seed, n_faults=params.n_faults,
                                 params=params.train)

    idx_same = CompileCacheIndex()
    seed_initial_compile(idx_same, params.train)
    same = replay_self_heal(events, params.train, compile_index=idx_same)

    idx_rs = CompileCacheIndex()
    seed_initial_compile(idx_rs, params.train)
    rs = replay_reshard_resume(events, params.train,
                               state_bytes=params.state_bytes,
                               compile_index=idx_rs)
    idx_rep = CompileCacheIndex()
    seed_initial_compile(idx_rep, params.train)
    repeat = replay_reshard_resume(events, params.train,
                                   state_bytes=params.state_bytes,
                                   compile_index=idx_rep)

    locked = replay_die_and_restart(events, params.train)
    roundtrip = reshard_roundtrip_report(seed)
    migration = reshard_migration_report(seed)

    budget = round(params.mttr_budget_ratio * same["mttr_mean_s"], 2)
    ratio = round(rs["mttr_mean_s"] / max(same["mttr_mean_s"], 1e-9), 3)
    gates = {
        "zero_lost_steps": rs["lost_steps"] == 0,
        "mttr_within_budget": rs["mttr_mean_s"] <= budget,
        "beats_topology_locked": (
            rs["wall_s"] < locked["wall_s"] and locked["lost_steps"] > 0
        ),
        "roundtrip_byte_parity": bool(roundtrip.get("ok")),
        "held_requests_complete": (
            migration["completed"] == migration["migrated"] > 0
            and migration["held_left_on_src"] == 0
        ),
        "int8_parity_within_bound": (
            migration["parity_mismatches"] <= migration["migrated"]
        ),
        "prefix_migrates_both_paths": (
            migration["prefix_replica_migrated"]
            and migration["prefix_host_rehydrated"]
        ),
        "deterministic_repeat": rs == repeat,
    }
    return {
        "same_topology": same,
        "reshard": rs,
        "topology_locked": locked,
        "roundtrip": roundtrip,
        "migration": migration,
        "mttr_ratio": ratio,
        "mttr_budget_s": budget,
        "gates": gates,
        "ok": all(gates.values()),
    }


def reshard_bench_line(seed: int = 0, ab: Optional[dict] = None) -> dict:
    """The reshard plane's deterministic bench line, shared by ``bench.py``
    and ``tools/bench_sentinel.py``. The gated value is the
    topology-changing / same-topology-warm MTTR ratio on the seeded
    chip-fault trace — the exit criterion is that topology freedom costs
    at most 1.5× the warm same-topology recovery, with zero lost steps
    and every held serving request completing."""
    res = ab if ab is not None else reshard_ab(seed=seed)
    rs = res["reshard"]
    return {
        "metric": "reshard",
        "value": res["mttr_ratio"],
        "unit": "topology-changing / same-topology warm MTTR ratio",
        "reshard_mttr_mean_s": rs["mttr_mean_s"],
        "same_topology_mttr_mean_s": res["same_topology"]["mttr_mean_s"],
        "mttr_budget_s": res["mttr_budget_s"],
        "lost_steps": rs["lost_steps"],
        "locked_lost_steps": res["topology_locked"]["lost_steps"],
        "topology_changes": rs["topology_changes"],
        "reshard_s_per_resume": rs["reshard_s_per_resume"],
        "roundtrip_targets": len(res["roundtrip"].get("targets", [])),
        "held_migrated": res["migration"]["migrated"],
        "held_completed": res["migration"]["completed"],
        "parity_mismatches": res["migration"]["parity_mismatches"],
        "gates": res["gates"],
        "ok": res["ok"],
    }


# -- fleet speculative decoding pool lane --------------------------------------


@dataclasses.dataclass(frozen=True)
class SpecPoolLaneParams:
    """Multi-tenant speculative serving scenario at EQUAL chips: the same
    verify pool serves every request, drafts colocate in the fragmented
    HBM headroom (validated by the estimator in the A/B, costing zero
    extra chips), and each tenant's draft quality — its true acceptance
    rate α — sets how much faster its slots decode. One tenant's draft is
    junk (α far below the floor): without the spill rule it makes serving
    SLOWER than plain decode; with it, the sustained-α consult spills the
    tenant back to plain chunked decode and the fleet keeps the win."""

    duration_s: float = 480.0
    dt_s: float = 0.05
    control_period_s: float = 1.0
    n_replicas: int = 4
    slots: int = 8
    tokens_per_slot_s: float = 30.0
    chips_per_replica: int = 1
    prefill_s: float = 0.5
    # Propose leg: gamma sequential draft steps through the draft pool
    # (plan_serving_pool's predicted_propose_s axis) — a TTFT adder.
    draft_leg_s: float = 0.1
    spec_gamma: int = 4
    # Draft step cost as a fraction of a target step: the standard
    # speculative speedup model α(γ+1)/(1+γd) tokens per target-step.
    draft_cost_frac: float = 0.15
    # Four tenants, one with a junk draft (α = 0.06 → 0.19× plain speed
    # until spilled — strictly worse than not speculating).
    tenant_alphas: Tuple[float, ...] = (0.72, 0.65, 0.58, 0.06)
    alpha_jitter: float = 0.06
    # Offered load sits ~1.35x the plain pool's effective capacity (the
    # speculative pools' remains comfortably above it): plain decode
    # saturates and its makespan stretches, which IS the fleet-level
    # tokens/sec/chip gap the A/B gates on at equal chips.
    base_rps: float = 9.0
    burst_rps: float = 20.0
    burst_every_s: float = 120.0
    burst_len_s: float = 30.0
    mean_new_tokens: float = 96.0
    min_new_tokens: int = 8
    warmup_s: float = 120.0
    ema_beta: float = 0.25
    # Spill rule (SpecSpillConfig): floors/hysteresis tuned so the junk
    # tenant spills well inside warmup and a hovering tenant cannot flap.
    accept_floor: float = 0.35
    recover_margin: float = 0.15
    spill_window_s: float = 20.0
    sustain_consults: int = 3
    cooldown_s: float = 60.0
    canary_every: int = 8


class _SpecLaneReplica:
    """Capacity model of one verify replica for the spec-pool lane: a
    slot pool where each admission carries its own decode-rate multiple
    (the speculative speedup of its tenant's draft, or 1.0 for plain /
    spilled / canary legs)."""

    def __init__(self, rid: str, params: SpecPoolLaneParams):
        self.rid = rid
        self.params = params
        self.rate = params.tokens_per_slot_s
        self.active: List[dict] = []
        self.tokens_out = 0.0

    def free_slots(self) -> int:
        return self.params.slots - len(self.active)

    def admit(self, req: dict, prefill_s: float, rate_mult: float) -> None:
        self.active.append({
            "req": req,
            "prefill_left": float(prefill_s),
            "tokens_left": float(req["n_new"]),
            "rate_mult": float(rate_mult),
        })

    def step(self, now: float, dt: float, done: List[dict]) -> None:
        for sl in list(self.active):
            if sl["prefill_left"] > 0:
                sl["prefill_left"] -= dt
                if sl["prefill_left"] <= 0:
                    sl["req"]["first_token_at"] = now
                continue
            produced = min(self.rate * sl["rate_mult"] * dt,
                           sl["tokens_left"])
            sl["tokens_left"] -= produced
            self.tokens_out += produced
            if sl["tokens_left"] <= 0:
                sl["req"]["done_at"] = now
                sl["req"]["replica"] = self.rid
                done.append(sl["req"])
                self.active.remove(sl)

    def router_stats(self) -> dict:
        busy = sum(1 for s in self.active if s["prefill_left"] <= 0)
        return {
            "tokens_per_sec": self.rate * max(busy, 0.2),
            "free_slots": self.free_slots(),
            "slots": self.params.slots,
        }


def spec_pool_lane(
    seed: int,
    spec: bool,
    params: SpecPoolLaneParams = SpecPoolLaneParams(),
) -> dict:
    """One seeded multi-tenant run at fixed chips through the REAL
    :class:`~tpu_engine.serving_fleet.FleetRouter` — plain chunked decode
    (``spec=False``) or speculative pools (``spec=True``) with a real
    :class:`~tpu_engine.historian.MetricHistorian` carrying the
    ``serving.spec.accept_rate`` series and a real
    :class:`~tpu_engine.spec_pool.SpecSpillController` consulting it on
    the control cadence. Fully virtual-clock: same seed and mode give a
    byte-identical report."""
    from tpu_engine.serving_fleet import FleetRouter
    from tpu_engine.spec_pool import SpecSpillConfig, SpecSpillController

    clock = VirtualClock(0.0)
    rng = random.Random(seed + 7)
    n_tenants = len(params.tenant_alphas)
    spill = None
    hist = historian_mod.MetricHistorian(clock=clock)
    if spec:
        spill = SpecSpillController(
            hist,
            SpecSpillConfig(
                accept_floor=params.accept_floor,
                recover_margin=params.recover_margin,
                window_s=params.spill_window_s,
                sustain_consults=params.sustain_consults,
                cooldown_s=params.cooldown_s,
                canary_every=params.canary_every,
            ),
            clock=clock,
        )
    router = FleetRouter()
    replicas = {
        f"r{i}": _SpecLaneReplica(f"r{i}", params)
        for i in range(params.n_replicas)
    }
    trace = bursty_arrivals(
        seed,
        duration_s=params.duration_s,
        base_rps=params.base_rps,
        burst_rps=params.burst_rps,
        burst_every_s=params.burst_every_s,
        burst_len_s=params.burst_len_s,
        n_prefixes=n_tenants,  # prefix id IS the tenant id
        prefix_len=32,
        mean_new_tokens=params.mean_new_tokens,
        min_new_tokens=params.min_new_tokens,
    )
    speedup = {
        f"t{i}": a * (params.spec_gamma + 1)
        / (1.0 + params.spec_gamma * params.draft_cost_frac)
        for i, a in enumerate(params.tenant_alphas)
    }
    true_alpha = {f"t{i}": a for i, a in enumerate(params.tenant_alphas)}
    emas: Dict[str, float] = {}
    canary_seq: Dict[str, int] = {}
    legs = {"draft": 0, "plain": 0, "canary": 0}
    queue: List[dict] = []
    done: List[dict] = []
    scored = 0

    def control(t: float) -> None:
        clock.set(t)
        router.update({r.rid: r.router_stats() for r in replicas.values()})
        if spill is not None:
            spill.consult(sorted(emas), now=t)

    def tick(t: float) -> None:
        nonlocal scored
        clock.set(t)
        free_total = sum(r.free_slots() for r in replicas.values())
        while queue and free_total > 0:
            req = queue[0]
            rid = router.route(req["prompt"])
            rep = replicas.get(rid) if rid else None
            if rep is None or rep.free_slots() <= 0:
                break  # full pick: weights refresh next control period
            queue.pop(0)
            free_total -= 1
            tenant = f"t{req['prefix_id']}"
            req["tenant"] = tenant
            if not spec:
                rep.admit(req, params.prefill_s, 1.0)
                continue
            spilled = spill.is_spilled(tenant)
            canary = False
            if spilled:
                canary_seq[tenant] = canary_seq.get(tenant, 0) + 1
                canary = canary_seq[tenant] % params.canary_every == 0
            if not spilled:
                # Full speculative request: draft-propose leg then the
                # verify stream at the tenant's α-speedup.
                legs["draft"] += 1
                req["speculated"] = True
                rep.admit(req, params.prefill_s + params.draft_leg_s,
                          speedup[tenant])
            elif canary:
                # Canary probe: a few speculative rounds re-measure α
                # (the sample below), the bulk decodes plain.
                legs["canary"] += 1
                req["speculated"] = True
                rep.admit(req, params.prefill_s + params.draft_leg_s, 1.0)
            else:
                legs["plain"] += 1
                req["speculated"] = False
                rep.admit(req, params.prefill_s, 1.0)
        for r in replicas.values():
            r.step(t, params.dt_s, done)
        # Score newly-completed speculative legs: a jittered draw around
        # the tenant's true α, folded into its EMA and recorded as the
        # historian series the spill controller consults.
        while scored < len(done):
            req = done[scored]
            scored += 1
            if not spec or not req.get("speculated"):
                continue
            tenant = req["tenant"]
            a = true_alpha[tenant] + params.alpha_jitter * (rng.random() - 0.5)
            a = min(max(a, 0.0), 1.0)
            prev = emas.get(tenant)
            emas[tenant] = a if prev is None else (
                params.ema_beta * a + (1.0 - params.ema_beta) * prev)
            hist.record("serving.spec.accept_rate", round(emas[tenant], 6),
                        ts=t, labels={"tenant": tenant})

    run_open_loop(
        trace,
        dt=params.dt_s,
        duration_s=params.duration_s,
        pending=lambda: queue or any(r.active for r in replicas.values()),
        arrive=queue.append,
        tick=tick,
        control=control,
        control_period_s=params.control_period_s,
        safety_factor=3.0,
    )

    total_chips = params.n_replicas * params.chips_per_replica
    metrics = serving_metrics(done, [], warmup_s=params.warmup_s,
                              total_chips=total_chips, dt_s=params.dt_s)
    per_tenant: Dict[str, dict] = {}
    for tenant in sorted(true_alpha):
        lat = [(r["done_at"] - r["t"]) * 1000.0 for r in done
               if r["tenant"] == tenant and r["t"] >= params.warmup_s]
        per_tenant[tenant] = {
            "completed": len(lat),
            "p99_ms": round(percentile(lat, 0.99), 1),
            "accept_ema": (None if tenant not in emas
                           else round(emas[tenant], 4)),
        }
    out = {
        "mode": "spec" if spec else "plain",
        "total_chips": total_chips,
        "metrics": metrics,
        "legs": dict(legs),
        "tenants": per_tenant,
        "router": router.stats(),
    }
    if spill is not None:
        out["spill"] = spill.status()
        out["spill_decisions_fired"] = [
            {"rule": d.rule, "target": d.target,
             "ts": d.ts, "action": d.action}
            for d in spill.decisions if d.outcome == "fired"
        ]
        out["accept_series_samples"] = hist.samples_total
    return out


def spec_pool_ab(
    seed: int = 0,
    params: SpecPoolLaneParams = SpecPoolLaneParams(),
) -> dict:
    """The spec-pool exit gate: plain chunked decode vs speculative pools
    at EQUAL chips on the same seeded bursty trace, a byte-identical spec
    repeat (determinism), the sustained-α spill of the junk-draft tenant
    (audited DecisionRecord, fleet never below the plain baseline), and
    the estimator's structured draft-HBM rejection + the draft-role
    placement plan that backfills fragmented headroom."""
    from tpu_engine.hbm_estimate import (
        SpecHBMOversubscribed,
        estimate_serving_hbm,
    )
    from tpu_engine.placement import plan_serving_pool

    plain = spec_pool_lane(seed, spec=False, params=params)
    pool = spec_pool_lane(seed, spec=True, params=params)
    repeat = spec_pool_lane(seed, spec=True, params=params)

    p, s = plain["metrics"], pool["metrics"]
    tpsc_ratio = round(
        s["tokens_per_sec_per_chip"] / max(p["tokens_per_sec_per_chip"], 1e-9),
        4)
    p99_ratio = round(s["p99_ms"] / max(p["p99_ms"], 1e-9), 4)
    low_tenant = f"t{len(params.tenant_alphas) - 1}"
    t_low_ratio = round(
        pool["tenants"][low_tenant]["p99_ms"]
        / max(plain["tenants"][low_tenant]["p99_ms"], 1e-9), 4)
    spill_fired = [
        d for d in pool.get("spill_decisions_fired", [])
        if d["rule"] == "spill_low_acceptance" and d["target"] == low_tenant
    ]

    # Admission honesty: a draft that fits the verify pool's fragmented
    # headroom estimates cleanly (with the colocated-draft terms); one
    # that oversubscribes is refused with a structured reason.
    est = estimate_serving_hbm(
        "llama-1b", params.slots, 2048,
        draft_model_name="gpt-tiny", device_budget_gib=16.0,
    )
    rejection = None
    try:
        estimate_serving_hbm(
            "llama-1b", params.slots, 2048,
            draft_model_name="gpt-tiny", device_budget_gib=0.5,
        )
    except SpecHBMOversubscribed as e:
        rejection = e.reason
    # Placement: the draft role ranks by propose latency and deliberately
    # fits inside small fragmented headroom (2 GiB here).
    draft_plans = plan_serving_pool(
        "gpt-tiny", "draft", params.n_replicas, hbm_free_gib=2.0,
        max_len=2048, spec_gamma=params.spec_gamma,
    )

    gates = {
        "spec_beats_plain_tokens_per_chip": tpsc_ratio >= 1.2,
        "p99_no_worse": p99_ratio <= 1.02,
        "low_alpha_tenant_spilled": (
            low_tenant in pool.get("spill", {}).get("spilled", [])
            and len(spill_fired) > 0
        ),
        "spilled_tenant_not_below_plain_baseline": t_low_ratio <= 1.10,
        "deterministic_repeat": pool == repeat,
        "draft_hbm_rejected": (
            rejection is not None
            and rejection.get("kind") == "spec_hbm_oversubscribed"
            and est is not None and est.device_total_gib > 0
        ),
        "draft_plan_feasible": (
            len(draft_plans) > 0 and draft_plans[0].feasible
            and draft_plans[0].predicted_propose_s > 0
        ),
    }
    return {
        "plain": plain,
        "spec": pool,
        "tokens_per_sec_per_chip_ratio": tpsc_ratio,
        "p99_ratio": p99_ratio,
        "low_alpha_tenant": low_tenant,
        "low_alpha_tenant_p99_ratio": t_low_ratio,
        "spill_decisions_fired": pool.get("spill_decisions_fired", []),
        "draft_hbm_rejection": rejection,
        "spec_replica_gib": None if est is None else est.device_total_gib,
        "draft_plan_label": (
            draft_plans[0].label if draft_plans else None),
        "gates": gates,
        "ok": all(gates.values()),
    }


def spec_pool_bench_line(seed: int = 0, ab: Optional[dict] = None) -> dict:
    """The spec pool's deterministic bench line, shared by ``bench.py``
    and ``tools/bench_sentinel.py``. The gated value is the spec/plain
    tokens-per-sec-per-chip ratio at equal chips on the seeded bursty
    trace — the headline fleet-level speculative win, with the junk-draft
    tenant provably spilled by the sustained-α rule."""
    res = ab if ab is not None else spec_pool_ab(seed=seed)
    pool = res["spec"]
    return {
        "metric": "spec_pool",
        "value": res["tokens_per_sec_per_chip_ratio"],
        "unit": "spec/plain tokens-per-sec-per-chip ratio, equal chips",
        "plain_tokens_per_sec_per_chip": (
            res["plain"]["metrics"]["tokens_per_sec_per_chip"]),
        "spec_tokens_per_sec_per_chip": (
            pool["metrics"]["tokens_per_sec_per_chip"]),
        "p99_ratio": res["p99_ratio"],
        "low_alpha_tenant": res["low_alpha_tenant"],
        "low_alpha_tenant_p99_ratio": res["low_alpha_tenant_p99_ratio"],
        "tenants_spilled": pool.get("spill", {}).get("spilled", []),
        "spill_decisions_fired": len(res["spill_decisions_fired"]),
        "legs": pool["legs"],
        "draft_plan_label": res["draft_plan_label"],
        "spec_replica_gib": res["spec_replica_gib"],
        "gates": res["gates"],
        "ok": res["ok"],
    }


# -- durable control plane: crash / restore / re-adoption lane -----------------


@dataclasses.dataclass(frozen=True)
class CtlCrashLaneParams:
    """One control-plane crash scenario: a storm of training submissions,
    chaos preemptions and serving traffic, with the scheduler/fleet host
    killed mid-storm (``crash_at_poll``) and rebuilt from its write-ahead
    journal. The no-crash run of the SAME workload, measured from the
    same poll, is the MTTR reference the 1.5× budget gates against."""

    n_train_jobs: int = 24
    n_requests: int = 36
    n_replicas: int = 2
    max_concurrent: int = 8
    submit_chunk: int = 6
    requests_per_poll: int = 3
    poll_dt_s: float = 2.0
    snapshot_every_polls: int = 8
    n_chaos_faults: int = 8
    crash_at_poll: int = 10
    job_base_s: float = 20.0
    job_spread_s: float = 6.0
    job_spread_mod: int = 7
    # Offered decode load (requests_per_poll × tokens_per_request) runs
    # ~2× the fleet's per-poll token capacity, so a standing backlog of
    # held/pending requests exists at the kill point — the crash must
    # catch requests in every phase: done, in-flight, and still queued.
    tokens_per_request: int = 40
    engine_tokens_per_poll: int = 32
    replica_slots: int = 8
    mttr_budget_ratio: float = 1.5


class _CtlTrainJob(_ScaleJob):
    """:class:`_ScaleJob` plus the chaos seam the storm needs: a running
    attempt can be preempted (the scheduler then requeues it at its
    original seq) or simply vanish with the crashed control-plane host."""

    __slots__ = ()

    def preempt(self, reason: str = "chaos-storm") -> None:
        if self.status == self._st.RUNNING:
            self.status = self._st.PREEMPTED
            self.preemption_reason = reason
            self.current_step = max(
                0, int(self._sim_s - max(self._done_at - self._clock(), 0.0))
            )


class _CtlLaneEngine:
    """Slot-model decode engine for the crash lane: each control poll
    grants it a token budget, spread round-robin over active requests —
    deterministic, thread-free, and it survives its control plane (the
    whole point: the data plane keeps decoding while the brain is dead)."""

    def __init__(self, slots: int):
        self.slots = int(slots)
        self._reqs: Dict[int, dict] = {}
        self._seq = 0

    def submit(self, prompt: Any, max_new_tokens: int = 64,
               temperature: float = 0.0) -> int:
        self._seq += 1
        self._reqs[self._seq] = {"need": int(max_new_tokens), "tokens": []}
        return self._seq

    def step(self, budget: int) -> None:
        active = [r for r in self._reqs.values()
                  if len(r["tokens"]) < r["need"]]
        while budget > 0 and active:
            for r in list(active):
                if budget <= 0:
                    break
                r["tokens"].append(1)
                budget -= 1
                if len(r["tokens"]) >= r["need"]:
                    active.remove(r)

    def result(self, rid: int) -> dict:
        r = self._reqs[rid]  # KeyError IS the fleet's redispatch signal
        done = len(r["tokens"]) >= r["need"]
        return {"status": "done" if done else "running",
                "tokens": list(r["tokens"])}

    def stats(self) -> dict:
        active = sum(1 for r in self._reqs.values()
                     if len(r["tokens"]) < r["need"])
        return {"slots": self.slots, "active_slots": active, "prefilling": 0,
                "queued": 0, "queued_handoffs": 0,
                "tokens_per_sec_recent": 100.0}


class _CtlReplicaJob:
    """Thread-free serving replica job: the engine is built synchronously
    and ready the moment the scheduler admits the replica."""

    __slots__ = ("_st", "status", "engine", "engine_ready", "current_step",
                 "watcher", "preemption_reason", "_stop")

    def __init__(self, slots: int, status_enum):
        self._st = status_enum
        self.status = status_enum.PENDING
        self.engine = _CtlLaneEngine(slots)
        self.engine_ready = threading.Event()
        self.current_step = 0
        self.watcher = None
        self.preemption_reason = None
        self._stop = threading.Event()

    def start(self) -> None:
        self.status = self._st.RUNNING
        self.engine_ready.set()

    @property
    def is_alive(self) -> bool:
        if self.status == self._st.RUNNING and self._stop.is_set():
            self.status = self._st.STOPPED
        return self.status in (self._st.PENDING, self._st.RUNNING)

    def join(self, timeout: Optional[float] = None) -> None:
        return None

    def describe(self) -> dict:
        return {"status": getattr(self.status, "value", str(self.status)),
                "step": self.current_step}


def ctl_crash_lane(
    seed: int,
    crash: bool,
    params: CtlCrashLaneParams = CtlCrashLaneParams(),
) -> dict:
    """One seeded storm through the REAL control plane — FleetScheduler +
    ServingFleet journaling every state change to a
    :class:`~tpu_engine.journal.ControlPlaneJournal` — with chaos
    preemptions drawn from ``FaultPlan.random(seed)`` and, when ``crash``
    is set, a ``FaultKind.CONTROLPLANE_CRASH`` consumed mid-storm via the
    injector seam.

    The crash drops the scheduler and fleet objects on the floor (no
    shutdown — the host died), leaves a torn half-written line on the
    live journal file, and lets live reality diverge from the journal:
    every third running training job and the first serving replica die
    with the host, the rest keep running orphaned. Recovery builds fresh
    objects and runs ``restore`` + ``re_adopt`` against a fresh journal
    handle — twice, from the same bytes, to prove the rebuild is
    byte-identical (``snapshot_state`` digests) — then drives the storm
    to completion. MTTR is virtual-clock time from the kill to the last
    journaled obligation (every training job completed, every accepted
    request answered)."""
    import gc

    from tpu_engine import goodput as goodput_mod
    from tpu_engine import journal as journal_mod
    from tpu_engine import tracing as tracing_mod
    from tpu_engine.mesh_runtime import MeshConfig
    from tpu_engine.scheduler import FleetScheduler, JobPriority, SubmissionState
    from tpu_engine.serving_fleet import (
        AutoscalerConfig,
        ReplicaAutoscaler,
        ServingFleet,
        ServingReplicaSpec,
    )
    from tpu_engine.sharding import TPUTrainConfig
    from tpu_engine.supervisor import JobStatus

    p = params
    vclock = VirtualClock(0.0)
    rec = FlightRecorder(
        max_spans=4096, max_events=8192, clock=vclock,
        id_factory=deterministic_ids("ctlcrash"),
    )
    hist = historian_mod.MetricHistorian(clock=vclock)
    ledger = GoodputLedger(clock=vclock, max_tracked=4096)

    old_rec = tracing_mod.get_recorder()
    old_hist = historian_mod.get_historian()
    old_ledger = goodput_mod.get_ledger()
    tracing_mod.set_recorder(rec)
    historian_mod.set_historian(hist)
    goodput_mod.set_ledger(ledger)
    gc_was_enabled = gc.isenabled()
    gc.disable()
    tmp = tempfile.TemporaryDirectory(prefix="ctl_crash_")
    try:
        journal = journal_mod.ControlPlaneJournal(
            os.path.join(tmp.name, "ctl_journal.jsonl"), clock=vclock
        )

        cfg = TPUTrainConfig(
            model_name="gpt-tiny", mesh=MeshConfig(data=1, fsdp=1),
            micro_batch_size=1, seq_len=32, precision="fp32",
            total_steps=5, activation_checkpointing=False,
        )
        jcount = iter(range(1 << 30))

        def make_train_job(sub) -> _CtlTrainJob:
            n = next(jcount)
            return _CtlTrainJob(
                vclock, p.job_base_s + p.job_spread_s * (n % p.job_spread_mod),
                JobStatus,
            )

        def new_sched() -> FleetScheduler:
            s = FleetScheduler(
                max_concurrent_jobs=p.max_concurrent,
                backfill_depth=p.max_concurrent,
                job_factory=make_train_job,
                poll_interval_s=3600.0,
                grow_back=False,
                hetero_rebalance=False,
                max_finished_history=4096,
            )
            s._ensure_thread = lambda: None  # the lane owns the poll cadence
            return s

        spec = ServingReplicaSpec(
            model_name="gpt-tiny", max_slots=p.replica_slots, max_len=128
        )

        def replica_job_factory(sub, spec_) -> _CtlReplicaJob:
            return _CtlReplicaJob(spec_.max_slots, JobStatus)

        def new_fleet(s, j) -> ServingFleet:
            return ServingFleet(
                s, spec,
                autoscaler=ReplicaAutoscaler(AutoscalerConfig(
                    min_replicas=1, max_replicas=max(4, p.n_replicas),
                )),
                replica_job_factory=replica_job_factory,
                journal=j,
            )

        sched = new_sched()
        sched.attach_journal(journal)
        fleet = new_fleet(sched, journal)
        fleet.scale_to(p.n_replicas)

        # Chaos storm: the SEEDED random plan picks the preemption polls;
        # the crash itself is an explicit spec consumed through the
        # injector seam (never part of random draws — see faults.py).
        storm = FaultPlan.random(
            seed, n_faults=p.n_chaos_faults, max_step=4 * p.crash_at_poll
        )
        storm_polls = sorted(
            s.at_step for s in storm.specs if s.at_step is not None
        )
        injector = FaultInjector(FaultPlan(seed=seed, specs=(
            [FaultSpec(kind=FaultKind.CONTROLPLANE_CRASH,
                       at_step=p.crash_at_poll)] if crash else []
        )))

        train_sids: List[str] = []
        fids: List[str] = []
        done_fids: set = set()
        submitted = 0
        polls = storms = 0
        crashed = False
        t_crash: Optional[float] = None
        recovery: Optional[dict] = None
        readopt: Optional[dict] = None
        double_identical = False
        held_recovered: List[str] = []
        t_done: Optional[float] = None
        max_polls = 400 + 40 * p.n_train_jobs

        def _train_done() -> int:
            return sum(
                1 for sid in train_sids
                if (s := sched.get(sid)) is not None
                and s.state == SubmissionState.COMPLETED
            )

        while True:
            # -- offered load ------------------------------------------------
            if submitted < p.n_train_jobs:
                k = min(p.submit_chunk, p.n_train_jobs - submitted)
                for _ in range(k):
                    sub = sched.submit(
                        cfg, priority=JobPriority.NORMAL,
                        submitter=f"team-{submitted % 4}",
                    )
                    train_sids.append(sub.submission_id)
                    submitted += 1
            if len(fids) < p.n_requests:
                for _ in range(min(p.requests_per_poll,
                                   p.n_requests - len(fids))):
                    prompt = [(seed * 131 + len(fids) * 17 + k) % 5003
                              for k in range(16)]
                    fids.append(fleet.submit_request(
                        prompt, max_new_tokens=p.tokens_per_request,
                    ))
            # -- chaos preemptions (the storm) -------------------------------
            while storm_polls and storm_polls[0] <= polls:
                storm_polls.pop(0)
                storms += 1
                for sid in train_sids:
                    s = sched.get(sid)
                    if (
                        s is not None
                        and s.state == SubmissionState.RUNNING
                        and isinstance(s.job, _CtlTrainJob)
                    ):
                        s.job.preempt()
                        break
            # -- one control pass --------------------------------------------
            sched.poll()
            for eng in fleet.running_replicas().values():
                eng.step(p.engine_tokens_per_poll)
            for fid in fids:
                if fid in done_fids:
                    continue
                if fleet.result(fid).get("status") == "done":
                    done_fids.add(fid)
            polls += 1
            if polls % p.snapshot_every_polls == 0:
                journal.snapshot(
                    journal_mod.collect_sections(scheduler=sched,
                                                 serving=fleet),
                    ts=vclock.now(),
                )
            # -- the kill point ----------------------------------------------
            if crash and not crashed and injector.take_controlplane_crash(polls):
                crashed = True
                t_crash = vclock.now()
                # Live reality at the moment of death: every third running
                # training job and the first replica die WITH the host;
                # everything else keeps running orphaned.
                live_jobs: Dict[str, Any] = {}
                nth_train = 0
                replica_vanished = False
                for s in sorted(sched._subs.values(), key=lambda x: x.seq):
                    if s.state not in (
                        SubmissionState.RUNNING, SubmissionState.CANCELLING
                    ) or s.job is None:
                        continue
                    if s.workload == "training":
                        nth_train += 1
                        if nth_train % 3 == 0:
                            continue  # died with the host
                    elif not replica_vanished:
                        replica_vanished = True
                        continue  # this replica's host died too
                    live_jobs[s.submission_id] = s.job
                # The crash lands mid-append: a torn half-line on the live
                # file that ingestion must skip, not raise on.
                with open(journal.path, "a", encoding="utf-8") as f:
                    f.write('{"record":"event","kind":"sched.su')
                # The old process is gone — no shutdown, no cleanup.
                journal2 = journal_mod.ControlPlaneJournal(
                    journal.path, clock=vclock
                )
                journal_mod.set_active_journal(journal2)
                sched2 = new_sched()
                recovery = sched2.restore(
                    journal2, live_jobs=live_jobs, now=vclock.now()
                )
                digest1 = json.dumps(sched2.snapshot_state(), sort_keys=True)
                # Double recovery from the same bytes must be byte-identical.
                sched3 = new_sched()
                sched3.restore(journal2, live_jobs=live_jobs,
                               now=vclock.now())
                digest2 = json.dumps(sched3.snapshot_state(), sort_keys=True)
                fleet3 = new_fleet(sched3, None)
                r3 = fleet3.re_adopt(journal2, redispatch=False)
                # Now the real recovery: re-adopt + re-dispatch the
                # vanished replica, then a fresh settling snapshot.
                fleet2 = new_fleet(sched2, None)
                readopt = fleet2.re_adopt(journal2)
                double_identical = (
                    digest1 == digest2
                    and readopt["held_fids"] == r3["held_fids"]
                    and readopt["replicas_readopted"]
                    == r3["replicas_readopted"]
                )
                held_recovered = list(readopt["held_fids"])
                sched, fleet, journal = sched2, fleet2, journal2
                journal.snapshot(
                    journal_mod.collect_sections(scheduler=sched,
                                                 serving=fleet),
                    ts=vclock.now(),
                )
            if not crash and t_crash is None and polls >= p.crash_at_poll:
                # The no-crash reference clocks its "MTTR" from the same
                # poll the crash run dies at.
                t_crash = vclock.now()
            # -- done? -------------------------------------------------------
            if (
                submitted >= p.n_train_jobs
                and len(fids) >= p.n_requests
                and _train_done() >= p.n_train_jobs
                and len(done_fids) >= p.n_requests
            ):
                t_done = vclock.now()
                break
            vclock.advance(p.poll_dt_s)
            if polls > max_polls:
                raise RuntimeError(
                    f"ctl_crash lane wedged: {_train_done()}/{p.n_train_jobs} "
                    f"jobs, {len(done_fids)}/{p.n_requests} requests "
                    f"after {polls} polls"
                )

        mttr_s = round(t_done - (t_crash if t_crash is not None else 0.0), 3)
        if crash:
            journal_mod.note_mttr(mttr_s)
        out = {
            "crash": crash,
            "polls": polls,
            "storm_preemptions": storms,
            "sim_s": round(vclock.now(), 3),
            "t_crash": t_crash,
            "mttr_s": mttr_s,
            "train_submitted": submitted,
            "train_completed": _train_done(),
            "train_subs_final": sum(
                1 for sid in train_sids if sched.get(sid) is not None
            ),
            "requests_total": len(fids),
            "requests_completed": len(done_fids),
            "journal": journal.stats(),
        }
        if crash:
            held_done = sum(1 for fid in held_recovered if fid in done_fids)
            out.update({
                "recovery": recovery,
                "re_adopt": {
                    k: v for k, v in (readopt or {}).items() if k != "ingest"
                },
                "double_recovery_identical": double_identical,
                "held_recovered": len(held_recovered),
                "held_done": held_done,
                "ingest": (recovery or {}).get("ingest", {}),
            })
        return out
    finally:
        journal_mod.clear_active_journal()
        tmp.cleanup()
        if gc_was_enabled:
            gc.enable()
        tracing_mod.set_recorder(old_rec)
        historian_mod.set_historian(old_hist)
        goodput_mod.set_ledger(old_ledger)


def ctl_crash_ab(
    seed: int = 0,
    params: CtlCrashLaneParams = CtlCrashLaneParams(),
) -> dict:
    """The durable-control-plane exit gate: the same seeded storm with and
    without a mid-storm control-plane kill. Gates: nothing the dead
    process had accepted is lost or duplicated, every held serving
    request completes, orphans are re-adopted (never re-launched), the
    vanished replica is re-dispatched, double recovery from the same
    journal bytes is byte-identical, the torn tail is skipped not raised,
    and crash-recovery MTTR stays within ``mttr_budget_ratio`` of the
    no-crash reference."""
    base = ctl_crash_lane(seed, crash=False, params=params)
    cr = ctl_crash_lane(seed, crash=True, params=params)

    budget = round(params.mttr_budget_ratio * base["mttr_s"], 3)
    ratio = round(cr["mttr_s"] / max(base["mttr_s"], 1e-9), 3)
    ingest = cr.get("ingest", {})
    gates = {
        "zero_lost_submissions": (
            cr["train_completed"] == params.n_train_jobs
        ),
        "zero_duplicated_submissions": (
            cr["train_subs_final"] == params.n_train_jobs
            and cr["train_submitted"] == params.n_train_jobs
        ),
        "held_requests_complete": (
            cr["held_recovered"] > 0
            and cr["held_done"] == cr["held_recovered"]
            and cr["requests_completed"] == params.n_requests
        ),
        "orphans_readopted": (
            (cr.get("recovery") or {}).get("readopted", 0) > 0
        ),
        "vanished_training_requeued": (
            (cr.get("recovery") or {}).get("requeued_vanished", 0) >= 1
        ),
        "vanished_replica_redispatched": (
            (cr.get("re_adopt") or {}).get("replicas_redispatched", 0) >= 1
        ),
        "no_phantom_double_grants": (
            (cr.get("recovery") or {}).get("double_grants", 0) == 0
        ),
        "double_recovery_identical": bool(cr.get("double_recovery_identical")),
        "torn_tail_skipped_not_raised": (
            (ingest.get("skipped_by_reason") or {}).get("torn_tail", 0) == 1
        ),
        "mttr_within_budget": cr["mttr_s"] <= budget,
    }
    return {
        "baseline": base,
        "crashed": cr,
        "mttr_ratio": ratio,
        "mttr_budget_s": budget,
        "gates": gates,
        "ok": all(gates.values()),
    }


def ctl_crash_bench_line(seed: int = 0, ab: Optional[dict] = None) -> dict:
    """The durable control plane's deterministic bench line, shared by
    ``bench.py`` and ``tools/bench_sentinel.py``. The gated value is the
    crash-recovery / no-crash MTTR ratio on the seeded storm — the exit
    criterion is that killing and restoring the control plane mid-storm
    costs at most 1.5× the no-crash completion time, with zero lost or
    duplicated submissions and every held request answered."""
    res = ab if ab is not None else ctl_crash_ab(seed=seed)
    cr = res["crashed"]
    return {
        "metric": "ctl_crash",
        "value": res["mttr_ratio"],
        "unit": "crash-recovery / no-crash MTTR ratio",
        "crash_mttr_s": cr["mttr_s"],
        "baseline_mttr_s": res["baseline"]["mttr_s"],
        "mttr_budget_s": res["mttr_budget_s"],
        "train_completed": cr["train_completed"],
        "requests_completed": cr["requests_completed"],
        "held_recovered": cr["held_recovered"],
        "jobs_readopted": (cr.get("recovery") or {}).get("readopted", 0),
        "requeued_vanished": (
            (cr.get("recovery") or {}).get("requeued_vanished", 0)),
        "replicas_redispatched": (
            (cr.get("re_adopt") or {}).get("replicas_redispatched", 0)),
        "double_grants": (cr.get("recovery") or {}).get("double_grants", 0),
        "journal_appends": cr["journal"]["appends_total"],
        "journal_snapshots": cr["journal"]["snapshots_total"],
        "gates": res["gates"],
        "ok": res["ok"],
    }
