"""Hot-op kernels (Pallas TPU) with pure-XLA fallbacks.

The reference has no first-party kernels (SURVEY.md §2.2 — all compute is
delegated to DeepSpeed/torch); here the hot ops are owned by the framework:
flash attention as a Pallas TPU kernel, falling back to an XLA implementation
on non-TPU backends (e.g. the 8-virtual-device CPU test mesh).
"""

from tpu_engine.ops import flash_attention

__all__ = ["flash_attention"]
