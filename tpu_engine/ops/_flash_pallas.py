"""First-party Pallas TPU flash attention (causal), with a memory-bounded
blockwise backward pass.

Forward: grid (batch·head, Q-block, K-block) with the K dimension innermost;
each program sees one [BLOCK_Q, D] query tile and one [BLOCK_K, D] key/value
tile (never the whole sequence), and online-softmax state (m/l/acc) lives in
VMEM scratch that persists across the K iterations. Peak VMEM is
O(BLOCK_Q · D + BLOCK_K · D + BLOCK_Q · BLOCK_K) regardless of sequence
length — the S×S score matrix is never materialised, and neither is a full
[S, D] K/V copy (the ``_xla_mha`` fallback materialises S×S).

Backward: custom_vjp over two Pallas kernels. The forward saves the
log-sum-exp rows; the backward reconstructs attention probabilities
block-by-block from (q, k, lse) and never materialises anything larger
than a [BLOCK, BLOCK] tile. A dQ kernel iterates K-blocks innermost
(accumulating dq in VMEM scratch) and a dK/dV kernel iterates Q-blocks
innermost — both skip the causally-masked block pairs entirely (compute
*and* DMA), so the backward does half the work of a dense S×S pass.

Layout: q/k/v [B, S, H, D] (GQA expanded by the caller, ``flash_attention.mha``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Renamed TPUCompilerParams -> CompilerParams across jax releases.
_CompilerParams = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams

_NEG_INF = -1e30


class FlashUnsupported(Exception):
    """Raised (at trace time) when a shape/config can't use the flash kernel."""


# Backward tile cap for LONG sequences (see _flash_bwd); module-level so
# the microbench can sweep it. Swept on chip (round 4, flash_microbench
# --bwd-block): at seq >= 4096 the 1024 tile beats the old blanket 512
# cap (fwd+bwd 4.87->4.64 ms @ seq4096, 14.57->14.44 @ 8192, 12.39->
# 12.31 windowed — the 4-tile f32 working set is 16 MiB, inside v5e
# VMEM), but at seq 2048 the bigger tile LOSES 8.7% (1.80->1.96 ms — a
# 2x2 outer grid leaves the pipeline too few blocks), so short
# sequences keep 512.
_BWD_BLOCK_CAP = 1024


def _pick_block(s: int) -> int:
    for b in (1024, 512, 256, 128, 64):
        if s % b == 0 and s // b >= 2:
            return b
    if s % 64 == 0:
        return min(s, 1024)
    return 0  # caller falls back to XLA attention


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _visible(q_pos, k_pos, window: int):
    """The causal (optionally sliding-window) visibility predicate."""
    vis = q_pos >= k_pos
    if window:
        vis &= q_pos - k_pos < window
    return vis


def _lo_block(q_idx, block: int, window: int):
    """Lowest K-block index visible to Q-block ``q_idx`` under ``window``
    (floor division handles the negative early-sequence case)."""
    return (q_idx * block - (window - 1)) // block


def _n_kv_blocks(n_blk: int, block: int, window: int) -> int:
    """Inner-grid length for Q-major (fwd / dQ) kernels: with a window only
    ceil((W-1)/block)+1 K-blocks can be visible to any Q-block, so the grid
    itself shrinks — windowed cost is O(S·W) in *programs*, not just in
    skipped compute."""
    if not window:
        return n_blk
    return min(n_blk, (window + block - 2) // block + 1)


def _n_q_blocks(n_blk: int, block: int, window: int) -> int:
    """Inner-grid length for the K-major (dK/dV) kernel: at most
    (block+W-2)//block + 1 Q-blocks can see any K-block."""
    if not window:
        return n_blk
    return min(n_blk, (block + window - 2) // block + 1)


def _k_index(q_idx, j, block: int, window: int):
    """Map the inner grid coordinate ``j`` to an actual K-block index. With
    a window the inner grid is shortened and offset to start at the lowest
    visible block; without one it is the K-block index itself."""
    if not window:
        return j
    return jnp.maximum(_lo_block(q_idx, block, window), 0) + j


_LOG2E = 1.4426950408889634
# Running-max floor, in base-2 logit units. Any REAL logit sits far above
# it, and a fully-masked row (all scores _NEG_INF) clamps here, pushing
# every exp2(s2 - m) to exactly 0.0 (fp32 flushes below 2^-149) — which is
# what makes the masked-probability select unnecessary (see _fwd_tile).
_M2_FLOOR = -1e6


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                q_scr, *, block_q: int, block_k: int, scale: float,
                window: int, causal: bool = True):
    q_idx = pl.program_id(1)
    j = pl.program_id(2)
    n_j = pl.num_programs(2)
    k_idx = _k_index(q_idx, j, block_q, window) if causal else j

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)
        # Fold scale·log2(e) into the Q tile ONCE per (bh, q_block): the
        # kernel then works entirely in base-2 logit units — jnp.exp2
        # instead of exp, and no [BQ, BK]-wide scale multiply per K tile.
        q_scr[...] = (
            q_ref[0].astype(jnp.float32) * (scale * _LOG2E)
        ).astype(q_scr.dtype)

    # Causal with BLOCK_Q == BLOCK_K: only K blocks with k_idx <= q_idx
    # contribute; the rest are skipped entirely. (The windowed lower bound
    # is built into the grid offset — k_idx never starts below it.)
    # Non-causal (ring attention's fully-visible hops): every block is
    # active and no visibility mask is computed at all.
    active = (k_idx <= q_idx) if causal else (j >= 0)

    def _tile(masked: bool):
        """One K-block of online softmax, in base-2 units.

        ``masked=False`` skips the visibility iota/compare/select entirely
        — correct for every tile strictly inside the visible band, which
        is MOST tiles at long sequence (the diagonal tile always masks;
        with a window, so do the tiles straddling its lower edge)."""
        q2 = q_scr[...]                         # [BQ, D] pre-scaled
        k_blk = k_ref[0]                        # [BK, D]
        v_blk = v_ref[0]                        # [BK, D]
        # bf16 operands, fp32 accumulation: the MXU's native contract.
        s2 = jax.lax.dot_general(
            q2, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [BQ, BK] base-2 logits
        if masked:
            q_pos = q_idx * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = k_idx * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s2 = jnp.where(_visible(q_pos, k_pos, window), s2, _NEG_INF)
        m = m_scr[...]
        # The _M2_FLOOR clamp replaces the old masked-p select: masked
        # entries hold -1e30, so exp2(-1e30 - floor) underflows to 0.0
        # without a [BQ, BK] where().
        m_new = jnp.maximum(jnp.maximum(m, jnp.max(s2, axis=-1)), _M2_FLOOR)
        p = jnp.exp2(s2 - m_new[:, None])
        corr = jnp.exp2(m - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * corr + jnp.sum(p, axis=-1)
        # p rounds to the storage dtype for the second MXU dot (standard
        # flash practice); l/m/acc stay fp32 so the normalisation is exact.
        acc_scr[...] = acc_scr[...] * corr[:, None] + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # A tile needs the visibility mask iff it touches the causal
        # diagonal or the window's lower edge; interior tiles are fully
        # visible and skip the iota/compare/select. (window is static:
        # without one this reduces to k_idx == q_idx.)
        needs_mask = k_idx == q_idx
        if window:
            needs_mask |= (q_idx - k_idx + 1) * block_q - 1 >= window

        @pl.when(active & needs_mask)
        def _compute_masked():
            _tile(True)

        @pl.when(active & jnp.logical_not(needs_mask))
        def _compute_interior():
            _tile(False)
    else:
        @pl.when(active)
        def _compute():
            _tile(False)

    @pl.when(j == n_j - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        # lse leaves the kernel in NATURAL-log units (ring-attention merges
        # and the backward recompute consume it): m2/log2(e) + ln(l).
        lse_ref[0, 0] = m_scr[...] * (1.0 / _LOG2E) + jnp.log(l_safe)


def _kv_clamp(block: int, window: int, causal: bool = True):
    """Index map for K/V blocks in Q-major grids: map the inner coordinate
    to the actual K-block, clamped into the active range so causally-masked
    iterations repeat an index the pipeline has already fetched — no
    bandwidth is spent on blocks the kernel won't read. Non-causal grids
    visit every block, so the coordinate maps straight through."""
    if not causal:
        return lambda bh, i, j: (bh, j, 0)
    return lambda bh, i, j: (bh, jnp.minimum(_k_index(i, j, block, window), i), 0)


def _flash_fwd(q, k, v, block: int, interpret: bool, window: int,
               causal: bool = True):
    """q/k/v: [BH, S, D] → (o [BH, S, D], lse [BH, S])."""
    BH, S, D = q.shape
    n_blk = S // block
    scale = 1.0 / (D ** 0.5)
    # Inner dim = K blocks (sequential); with a window it is shortened to
    # the max number of visible K-blocks per Q-block.
    grid = (BH, n_blk, _n_kv_blocks(n_blk, block, window) if causal else n_blk)
    kernel = partial(_fwd_kernel, block_q=block, block_k=block, scale=scale,
                     window=window, causal=causal)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block, D), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block, D), _kv_clamp(block, window, causal)),
            pl.BlockSpec((1, block, D), _kv_clamp(block, window, causal)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, D), lambda bh, i, j: (bh, i, 0)),
            # lse as [BH, 1, S]: TPU block tiling needs the last two block
            # dims (1, block) to be (equal-to-array, 128-divisible).
            pl.BlockSpec((1, 1, block), lambda bh, i, j: (bh, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block,), jnp.float32),      # running max m (base-2)
            pltpu.VMEM((block,), jnp.float32),      # running sum l
            pltpu.VMEM((block, D), jnp.float32),    # output accumulator
            pltpu.VMEM((block, D), q.dtype),        # scale·log2e-folded Q
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return o, lse.reshape(BH, S)


# ---------------------------------------------------------------------------
# Backward kernels
# ---------------------------------------------------------------------------


def _recompute_p(q, k, lse_row, q_idx, k_idx, block_q, block_k, scale, window,
                 causal=True):
    """Rebuild one [BQ, BK] tile of attention probabilities from saved lse."""
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    if not causal:
        return jnp.exp(s - lse_row[:, None])
    q_pos = q_idx * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_pos = k_idx * block_k + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    mask = _visible(q_pos, k_pos, window)
    return jnp.where(mask, jnp.exp(s - lse_row[:, None]), 0.0)


def _p_ds_tile(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
               q_idx, k_idx, block_q, block_k, scale, window, causal=True):
    """Shared gradient-tile math for both backward kernels: load the four
    blocks and return (p, ds, q, k, do) — ds = p ∘ (dO·Vᵀ − Δ) · scale.

    Blocks stay in their storage dtype (bf16) so every dot feeds the MXU
    its native input width; products/softmax math accumulate in fp32 via
    ``preferred_element_type``. ``p``/``ds`` are returned fp32 — callers
    round them to the storage dtype at their own MXU dots."""
    q = q_ref[0]                                # [BQ, D] storage dtype
    k_blk = k_ref[0]                            # [BK, D]
    v_blk = v_ref[0]                            # [BK, D]
    do = do_ref[0]                              # [BQ, D]
    p = _recompute_p(q, k_blk, lse_ref[0, 0], q_idx, k_idx,
                     block_q, block_k, scale, window, causal)
    dp = jax.lax.dot_general(
        do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )                                           # [BQ, BK] fp32
    ds = p * (dp - delta_ref[0, 0][:, None]) * scale
    return p, ds, q, k_blk, do


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   dq_scr, *, block_q: int, block_k: int, scale: float,
                   window: int, causal: bool = True):
    q_idx = pl.program_id(1)
    j = pl.program_id(2)
    n_j = pl.num_programs(2)
    k_idx = _k_index(q_idx, j, block_q, window) if causal else j

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when((k_idx <= q_idx) if causal else (j >= 0))
    def _compute():
        _, ds, _, k_blk, _ = _p_ds_tile(q_ref, k_ref, v_ref, do_ref,
                                        lse_ref, delta_ref, q_idx, k_idx,
                                        block_q, block_k, scale, window,
                                        causal)
        dq_scr[...] += jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == n_j - 1)
    def _finalize():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _q_index(k_idx, j, window: int):
    """Inner grid coordinate → actual Q-block index for the K-major kernel:
    with a window the grid starts at the diagonal (lowest visible Q-block
    is the K-block itself)."""
    return k_idx + j if window else j


def _bwd_dkv_kernel(k_ref, v_ref, q_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *,
                    block_q: int, block_k: int, scale: float, window: int,
                    n_blk: int, causal: bool = True):
    k_idx = pl.program_id(1)
    j = pl.program_id(2)
    n_j = pl.num_programs(2)
    q_idx = _q_index(k_idx, j, window) if causal else j

    @pl.when(j == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    if causal:
        active = q_idx >= k_idx
        if window:
            active &= q_idx < n_blk  # offset grid can run past the last Q-block
    else:
        active = j >= 0

    @pl.when(active)
    def _compute():
        p, ds, q, _, do = _p_ds_tile(q_ref, k_ref, v_ref, do_ref,
                                     lse_ref, delta_ref, q_idx, k_idx,
                                     block_q, block_k, scale, window, causal)
        dv_scr[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                           # [BK, D]
        dk_scr[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == n_j - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd(block: int, interpret: bool, window: int, res, do,
               causal: bool = True, dlse=None):
    """dq/dk/dv from the output cotangent ``do`` and, optionally, an LSE
    cotangent ``dlse`` [BH, S] (ring attention's hop merge differentiates
    through the returned lse). The kernels need no change for it: with
    cotangents (dO, dlse), the score gradient is
    ds = p ∘ (dO·Vᵀ − Δ + dlse), i.e. exactly the standard form with
    Δ' = rowsum(dO ∘ O) − dlse substituted for Δ."""
    q, k, v, o, lse = res  # q/k/v/o: [BH, S, D]; lse: [BH, S]
    BH, S, D = q.shape
    scale = 1.0 / (D ** 0.5)
    # The backward holds ~4 [BQ, BK] f32 tiles live at once (s/p, dp, ds)
    # plus four input blocks and two accumulators. Tile choice is
    # sequence-dependent (swept on chip, see _BWD_BLOCK_CAP): long
    # sequences take the big tile, short ones keep enough outer-grid
    # blocks to fill the pipeline.
    bb = min(block, _BWD_BLOCK_CAP if S >= 4096 else 512)
    # Power-of-two floor: ``block`` is a power of two dividing S, so any
    # power of two <= block divides S too. A swept/overridden cap that is
    # not a power of two (e.g. --bwd-block 768) would otherwise truncate
    # the grid and leave tail rows of dq/dk/dv unwritten.
    bb = 1 << (bb.bit_length() - 1)
    n_blk = S // bb

    do32 = do.astype(jnp.float32)
    # D_i = rowsum(dO ∘ O) — the softmax-jacobian diagonal term.
    delta = jnp.sum(do32 * o.astype(jnp.float32), axis=-1)  # [BH, S]
    if dlse is not None:
        delta = delta - dlse.astype(jnp.float32)
    lse3 = lse.reshape(BH, 1, S)
    delta3 = delta.reshape(BH, 1, S)

    qkv_spec = pl.BlockSpec((1, bb, D), lambda bh, i, j: (bh, i, 0))
    row_spec = pl.BlockSpec((1, 1, bb), lambda bh, i, j: (bh, 0, i))

    # The clamped index maps below pin the moving operand's index on
    # causally- or window-skipped iterations to a block already fetched,
    # so the pipeline elides the DMA.
    dq = pl.pallas_call(
        partial(_bwd_dq_kernel, block_q=bb, block_k=bb, scale=scale,
                window=window, causal=causal),
        # (bh, q-block, k-block innermost) — inner dim shortened by a window
        grid=(BH, n_blk, _n_kv_blocks(n_blk, bb, window) if causal else n_blk),
        in_specs=[
            qkv_spec,  # q
            pl.BlockSpec((1, bb, D), _kv_clamp(bb, window, causal)),  # k
            pl.BlockSpec((1, bb, D), _kv_clamp(bb, window, causal)),  # v
            qkv_spec,  # do
            row_spec,  # lse
            row_spec,  # delta
        ],
        out_specs=qkv_spec,
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bb, D), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v, do, lse3, delta3)

    if not causal:
        def _q_blk(i, j):
            return j
    elif window:
        # Offset inner grid: q-block = i + j, clamped to the last real block
        # for the tail iterations past the end of the sequence.
        def _q_blk(i, j):
            return jnp.minimum(_q_index(i, j, window), n_blk - 1)
    else:
        def _q_blk(i, j):
            return jnp.maximum(i, j)

    moving = pl.BlockSpec((1, bb, D), lambda bh, i, j: (bh, _q_blk(i, j), 0))
    moving_row = pl.BlockSpec((1, 1, bb), lambda bh, i, j: (bh, 0, _q_blk(i, j)))
    dk, dv = pl.pallas_call(
        partial(_bwd_dkv_kernel, block_q=bb, block_k=bb, scale=scale,
                window=window, n_blk=n_blk, causal=causal),
        # (bh, k-block, q-block innermost) — inner dim shortened by a window
        grid=(BH, n_blk, _n_q_blocks(n_blk, bb, window) if causal else n_blk),
        in_specs=[
            qkv_spec,    # k
            qkv_spec,    # v
            moving,      # q
            moving,      # do
            moving_row,  # lse
            moving_row,  # delta
        ],
        out_specs=[qkv_spec, qkv_spec],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), k.dtype),
            jax.ShapeDtypeStruct((BH, S, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bb, D), jnp.float32),
            pltpu.VMEM((bb, D), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(k, v, q, do, lse3, delta3)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# Public entry (custom_vjp over [BH, S, D] layout)
# ---------------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_bhsd(q, k, v, block: int, interpret: bool, window: int):
    o, _ = _flash_fwd(q, k, v, block, interpret, window)
    return o


def _flash_bhsd_fwd(q, k, v, block, interpret, window):
    o, lse = _flash_fwd(q, k, v, block, interpret, window)
    return o, (q, k, v, o, lse)


def _flash_bhsd_bwd(block, interpret, window, res, do):
    return _flash_bwd(block, interpret, window, res, do)


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


# ---------------------------------------------------------------------------
# (o, lse) entry for ring attention's per-hop blocks
# ---------------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_fwd_lse(q, k, v, block: int, interpret: bool, causal: bool):
    """Flash attention on [BH, S, D] returning ``(o, lse)`` — the entry ring
    attention calls per K/V hop. ``lse`` is differentiable: its cotangent
    from the hop merge folds into the standard backward via the Δ' trick
    (see :func:`_flash_bwd`). ``causal=False`` runs the unmasked kernels
    (a ring hop strictly in the past is fully visible)."""
    return _flash_fwd(q, k, v, block, interpret, 0, causal=causal)


def _flash_fwd_lse_fwd(q, k, v, block, interpret, causal):
    o, lse = _flash_fwd(q, k, v, block, interpret, 0, causal=causal)
    return (o, lse), (q, k, v, o, lse)


def _flash_fwd_lse_bwd(block, interpret, causal, res, cts):
    do, dlse = cts
    return _flash_bwd(block, interpret, 0, res, do, causal=causal, dlse=dlse)


flash_fwd_lse.defvjp(_flash_fwd_lse_fwd, _flash_fwd_lse_bwd)


def flash_mha(q, k, v, causal: bool = True, interpret: bool | None = None,
              window: int = 0):
    """Flash attention on [B, S, H, D]; returns [B, S, H, D].

    ``window > 0`` restricts each query to the trailing ``window`` keys
    (sliding-window attention, Mistral-style): block pairs wholly outside
    the window are skipped — compute and DMA — so cost is O(S·W), not O(S²).

    Raises :class:`FlashUnsupported` (at trace time) when the shape doesn't
    tile or attention is non-causal; the dispatcher in
    ``flash_attention.mha`` then falls back to the XLA path.
    """
    B, S, H, D = q.shape
    KV = k.shape[2]
    block = _pick_block(S)
    if not causal or block == 0 or S < 64:
        raise FlashUnsupported(f"no flash tiling for seq_len={S}, causal={causal}")
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window >= S:
        window = 0  # a window covering the whole sequence is plain causal
    if interpret is None:
        # Off-TPU the kernel would only run in interpret mode — orders of
        # magnitude slower than XLA attention. Don't do that silently; let
        # the dispatcher fall back to XLA. Tests opt in with interpret=True.
        if jax.devices()[0].platform != "tpu":
            raise FlashUnsupported("no TPU present (pass interpret=True to force)")
        interpret = False
    if KV != H:
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    o = _flash_bhsd(to_bhsd(q), to_bhsd(k), to_bhsd(v), block, interpret, window)
    return o.reshape(B, H, S, D).transpose(0, 2, 1, 3)
