"""First-party Pallas TPU flash attention (causal), with a memory-bounded
blockwise backward pass.

Forward: grid (batch·head, Q-block, K-block) with the K dimension innermost;
each program sees one [BLOCK_Q, D] query tile and one [BLOCK_K, D] key/value
tile (never the whole sequence), and online-softmax state (m/l/acc) lives in
VMEM scratch that persists across the K iterations. Peak VMEM is
O(BLOCK_Q · D + BLOCK_K · D + BLOCK_Q · BLOCK_K) regardless of sequence
length — the S×S score matrix is never materialised, and neither is a full
[S, D] K/V copy (the ``_xla_mha`` fallback materialises S×S).

Backward: custom_vjp. The forward saves the log-sum-exp rows; the backward
reconstructs attention probabilities block-by-block in plain JAX
(``lax.scan`` over K/V blocks) — memory O(S · BLOCK_K), XLA-fused, and it
avoids a second Pallas kernel while keeping the flash memory property.

Layout: q/k/v [B, S, H, D] (GQA expanded by the caller, ``flash_attention.mha``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


class FlashUnsupported(Exception):
    """Raised (at trace time) when a shape/config can't use the flash kernel."""


def _pick_block(s: int) -> int:
    for b in (1024, 512, 256, 128, 64):
        if s % b == 0 and s // b >= 2:
            return b
    if s % 64 == 0:
        return min(s, 1024)
    return 0  # caller falls back to XLA attention


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr, *,
                block_q: int, block_k: int, scale: float):
    q_idx = pl.program_id(1)
    k_idx = pl.program_id(2)
    n_k = pl.num_programs(2)

    @pl.when(k_idx == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # Causal with BLOCK_Q == BLOCK_K: only K blocks with k_idx <= q_idx
    # contribute; later iterations are skipped entirely.
    @pl.when(k_idx <= q_idx)
    def _compute():
        q = q_ref[0].astype(jnp.float32)        # [BQ, D]
        k_blk = k_ref[0].astype(jnp.float32)    # [BK, D]
        v_blk = v_ref[0].astype(jnp.float32)    # [BK, D]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK]
        q_pos = q_idx * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        k_pos = k_idx * block_k + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
        corr = jnp.exp(m - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * corr + jnp.sum(p, axis=-1)
        acc_scr[...] = acc_scr[...] * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(k_idx == n_k - 1)
    def _finalize():
        l_safe = jnp.maximum(l_scr[...], 1e-30)
        o_ref[0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse_ref[0, 0] = m_scr[...] + jnp.log(l_safe)


def _flash_fwd(q, k, v, block: int, interpret: bool):
    """q/k/v: [BH, S, D] → (o [BH, S, D], lse [BH, S])."""
    BH, S, D = q.shape
    scale = 1.0 / (D ** 0.5)
    grid = (BH, S // block, S // block)  # K-block dim innermost (sequential)
    kernel = partial(_fwd_kernel, block_q=block, block_k=block, scale=scale)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block, D), lambda bh, i, j: (bh, i, 0)),
            # K/V block index clamped to min(i, j): for the causally-masked
            # iterations (j > i) the index repeats, so the pipeline skips the
            # DMA — no bandwidth is spent on blocks the kernel won't read.
            pl.BlockSpec((1, block, D), lambda bh, i, j: (bh, jnp.minimum(i, j), 0)),
            pl.BlockSpec((1, block, D), lambda bh, i, j: (bh, jnp.minimum(i, j), 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, D), lambda bh, i, j: (bh, i, 0)),
            # lse as [BH, 1, S]: TPU block tiling needs the last two block
            # dims (1, block) to be (equal-to-array, 128-divisible).
            pl.BlockSpec((1, 1, block), lambda bh, i, j: (bh, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block,), jnp.float32),      # running max m
            pltpu.VMEM((block,), jnp.float32),      # running sum l
            pltpu.VMEM((block, D), jnp.float32),    # output accumulator
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=interpret,
    )(q, k, v)
    return o, lse.reshape(BH, S)


# ---------------------------------------------------------------------------
# Backward (blockwise JAX, flash memory profile)
# ---------------------------------------------------------------------------


def _flash_bwd(block: int, res, do):
    q, k, v, o, lse = res  # q/k/v/o: [BH, S, D]; lse: [BH, S]
    BH, S, D = q.shape
    scale = 1.0 / (D ** 0.5)

    q32 = q.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    # D_i = rowsum(dO ∘ O) — the softmax-jacobian diagonal term.
    delta = jnp.sum(do32 * o.astype(jnp.float32), axis=-1)  # [BH, S]
    q_pos = jnp.arange(S)

    def kv_block(carry, j):
        dq_acc = carry
        k_blk = lax.dynamic_slice_in_dim(k, j * block, block, axis=1).astype(jnp.float32)
        v_blk = lax.dynamic_slice_in_dim(v, j * block, block, axis=1).astype(jnp.float32)
        s = jnp.einsum("zqd,zkd->zqk", q32, k_blk) * scale  # [BH, S, BK]
        k_pos = j * block + jnp.arange(block)
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None], s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])  # [BH, S, BK]
        p = jnp.where(mask[None], p, 0.0)
        dv = jnp.einsum("zqk,zqd->zkd", p, do32)
        dp = jnp.einsum("zqd,zkd->zqk", do32, v_blk)
        ds = p * (dp - delta[..., None]) * scale
        dk = jnp.einsum("zqk,zqd->zkd", ds, q32)
        dq_acc = dq_acc + jnp.einsum("zqk,zkd->zqd", ds, k_blk)
        return dq_acc, (dk, dv)

    dq0 = jnp.zeros((BH, S, D), jnp.float32)
    dq, (dk_blocks, dv_blocks) = lax.scan(kv_block, dq0, jnp.arange(S // block))
    dk = jnp.moveaxis(dk_blocks, 0, 1).reshape(BH, S, D)
    dv = jnp.moveaxis(dv_blocks, 0, 1).reshape(BH, S, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Public entry (custom_vjp over [BH, S, D] layout)
# ---------------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_bhsd(q, k, v, block: int, interpret: bool):
    o, _ = _flash_fwd(q, k, v, block, interpret)
    return o


def _flash_bhsd_fwd(q, k, v, block, interpret):
    o, lse = _flash_fwd(q, k, v, block, interpret)
    return o, (q, k, v, o, lse)


def _flash_bhsd_bwd(block, interpret, res, do):
    return _flash_bwd(block, res, do)


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


def flash_mha(q, k, v, causal: bool = True, interpret: bool | None = None):
    """Flash attention on [B, S, H, D]; returns [B, S, H, D].

    Raises :class:`FlashUnsupported` (at trace time) when the shape doesn't
    tile or attention is non-causal; the dispatcher in
    ``flash_attention.mha`` then falls back to the XLA path.
    """
    B, S, H, D = q.shape
    KV = k.shape[2]
    block = _pick_block(S)
    if not causal or block == 0 or S < 64:
        raise FlashUnsupported(f"no flash tiling for seq_len={S}, causal={causal}")
    if interpret is None:
        # Off-TPU the kernel would only run in interpret mode — orders of
        # magnitude slower than XLA attention. Don't do that silently; let
        # the dispatcher fall back to XLA. Tests opt in with interpret=True.
        if jax.devices()[0].platform != "tpu":
            raise FlashUnsupported("no TPU present (pass interpret=True to force)")
        interpret = False
    if KV != H:
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    o = _flash_bhsd(to_bhsd(q), to_bhsd(k), to_bhsd(v), block, interpret)
    return o.reshape(B, H, S, D).transpose(0, 2, 1, 3)
