"""First-party Pallas TPU flash attention (causal), with a memory-bounded
blockwise backward pass.

Forward: one Pallas program per (batch·head, Q-block); K/V stream through
VMEM while an online-softmax accumulator keeps peak memory at
O(BLOCK_Q · D + BLOCK_Q · BLOCK_K) — the S×S score matrix is never
materialised (the ``_xla_mha`` fallback materialises it; kernel pattern per
the Pallas TPU guide's double-buffered matmul/softmax recipes).

Backward: custom_vjp. The forward saves the log-sum-exp rows; the backward
reconstructs attention probabilities block-by-block in plain JAX
(``lax.scan`` over K/V blocks) — memory O(S · BLOCK_K), XLA-fused, and it
avoids a second Pallas kernel while keeping the flash memory property.

Layout: q/k/v [B, S, H, D] (GQA expanded by the caller, ``flash_attention.mha``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG_INF = -1e30


class FlashUnsupported(Exception):
    """Raised (at trace time) when a shape/config can't use the flash kernel."""


def _pick_block(s: int) -> int:
    for b in (512, 256, 128, 64):
        if s % b == 0:
            return b
    return 0  # caller falls back to XLA attention


# ---------------------------------------------------------------------------
# Forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_q: int, block_k: int,
                scale: float):
    q_idx = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [BQ, D]

    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc0 = jnp.zeros((block_q, q.shape[-1]), jnp.float32)

    q_pos = q_idx * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * scale  # [BQ, BK]
        k_pos = j * block_k + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[:, None])
        p = jnp.where(s <= _NEG_INF / 2, 0.0, p)
        corr = jnp.exp(m - m_new)
        l_new = l * corr + jnp.sum(p, axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    # Causal with BLOCK_Q == BLOCK_K: only blocks j <= q_idx contribute.
    m, l, acc = lax.fori_loop(0, q_idx + 1, body, (m0, l0, acc0))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l_safe)


def _flash_fwd(q, k, v, block: int, interpret: bool):
    """q/k/v: [BH, S, D] → (o [BH, S, D], lse [BH, S])."""
    BH, S, D = q.shape
    scale = 1.0 / (D ** 0.5)
    grid = (BH, S // block)
    kernel = partial(_fwd_kernel, block_q=block, block_k=block, scale=scale)
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block, D), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, S, D), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, S, D), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block, D), lambda bh, i: (bh, i, 0)),
            # lse as [BH, 1, S]: TPU block tiling needs the last two block
            # dims (1, block) to be (equal-to-array, 128-divisible).
            pl.BlockSpec((1, 1, block), lambda bh, i: (bh, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, 1, S), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse.reshape(BH, S)


# ---------------------------------------------------------------------------
# Backward (blockwise JAX, flash memory profile)
# ---------------------------------------------------------------------------


def _flash_bwd(block: int, res, do):
    q, k, v, o, lse = res  # q/k/v/o: [BH, S, D]; lse: [BH, S]
    BH, S, D = q.shape
    scale = 1.0 / (D ** 0.5)

    q32 = q.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    # D_i = rowsum(dO ∘ O) — the softmax-jacobian diagonal term.
    delta = jnp.sum(do32 * o.astype(jnp.float32), axis=-1)  # [BH, S]
    q_pos = jnp.arange(S)

    def kv_block(carry, j):
        dq_acc = carry
        k_blk = lax.dynamic_slice_in_dim(k, j * block, block, axis=1).astype(jnp.float32)
        v_blk = lax.dynamic_slice_in_dim(v, j * block, block, axis=1).astype(jnp.float32)
        s = jnp.einsum("zqd,zkd->zqk", q32, k_blk) * scale  # [BH, S, BK]
        k_pos = j * block + jnp.arange(block)
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None], s, _NEG_INF)
        p = jnp.exp(s - lse[..., None])  # [BH, S, BK]
        p = jnp.where(mask[None], p, 0.0)
        dv = jnp.einsum("zqk,zqd->zkd", p, do32)
        dp = jnp.einsum("zqd,zkd->zqk", do32, v_blk)
        ds = p * (dp - delta[..., None]) * scale
        dk = jnp.einsum("zqk,zqd->zkd", ds, q32)
        dq_acc = dq_acc + jnp.einsum("zqk,zkd->zqd", ds, k_blk)
        return dq_acc, (dk, dv)

    dq0 = jnp.zeros((BH, S, D), jnp.float32)
    dq, (dk_blocks, dv_blocks) = lax.scan(kv_block, dq0, jnp.arange(S // block))
    dk = jnp.moveaxis(dk_blocks, 0, 1).reshape(BH, S, D)
    dv = jnp.moveaxis(dv_blocks, 0, 1).reshape(BH, S, D)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Public entry (custom_vjp over [BH, S, D] layout)
# ---------------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_bhsd(q, k, v, block: int, interpret: bool):
    o, _ = _flash_fwd(q, k, v, block, interpret)
    return o


def _flash_bhsd_fwd(q, k, v, block, interpret):
    o, lse = _flash_fwd(q, k, v, block, interpret)
    return o, (q, k, v, o, lse)


def _flash_bhsd_bwd(block, interpret, res, do):
    return _flash_bwd(block, res, do)


_flash_bhsd.defvjp(_flash_bhsd_fwd, _flash_bhsd_bwd)


def flash_mha(q, k, v, causal: bool = True, interpret: bool | None = None):
    """Flash attention on [B, S, H, D]; returns [B, S, H, D].

    Raises :class:`FlashUnsupported` (at trace time) when the shape doesn't
    tile or attention is non-causal; the dispatcher in
    ``flash_attention.mha`` then falls back to the XLA path.
    """
    B, S, H, D = q.shape
    KV = k.shape[2]
    block = _pick_block(S)
    if not causal or block == 0 or S < 64:
        raise FlashUnsupported(f"no flash tiling for seq_len={S}, causal={causal}")
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    if KV != H:
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)

    def to_bhsd(x):
        return x.transpose(0, 2, 1, 3).reshape(B * H, S, D)

    o = _flash_bhsd(to_bhsd(q), to_bhsd(k), to_bhsd(v), block, interpret)
    return o.reshape(B, H, S, D).transpose(0, 2, 1, 3)
