"""Flash attention for TPU (Pallas), with an XLA fallback.

Phase-7 home of the Pallas kernel; the public entry point :func:`mha` is
stable from day one so the model can dispatch to it unconditionally.

Layout convention: q [B, S, H, D], k/v [B, S, KV, D] (GQA when KV < H),
causal masking only (decoder-only LM).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _xla_mha(q, k, v, causal: bool = True, window: int = 0):
    B, S, H, D = q.shape
    KV = k.shape[2]
    if KV != H:
        k = jnp.repeat(k, H // KV, axis=2)
        v = jnp.repeat(v, H // KV, axis=2)
    scale = 1.0 / (D ** 0.5)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32) * scale
    if causal:
        pos = jnp.arange(S)
        mask = pos[:, None] >= pos[None, :]
        if window:
            mask &= pos[:, None] - pos[None, :] < window
        scores = jnp.where(mask[None, None, :, :], scores, -1e9)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def mha(q, k, v, causal: bool = True, force_xla: bool = False, window: int = 0,
        interpret: bool | None = None):
    """Multi-head attention dispatch.

    ``window > 0`` is sliding-window (Mistral-style) attention: each query
    sees only the trailing ``window`` keys. ``force_xla=True`` (or an
    untileable shape) → the XLA implementation; otherwise the first-party
    Pallas flash kernel.

    ``interpret=True`` forces the Pallas kernel in interpret mode off-TPU
    (slow — the multi-device shard_map path uses it so the CPU dry-run
    exercises the kernel's real custom_vjp wrapping rather than silently
    testing the XLA fallback); ``None`` lets ``flash_mha`` fall back to XLA
    when no TPU is attached.
    """
    if window < 0:
        raise ValueError(f"window must be >= 0, got {window}")
    if window and not causal:
        raise ValueError("sliding-window attention requires causal=True")
    if force_xla:
        return _xla_mha(q, k, v, causal=causal, window=window)
    from tpu_engine.ops._flash_pallas import FlashUnsupported, flash_mha

    try:
        return flash_mha(q, k, v, causal=causal, window=window,
                         interpret=interpret)
    except FlashUnsupported:
        return _xla_mha(q, k, v, causal=causal, window=window)
