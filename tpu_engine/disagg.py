"""Disaggregated prefill/decode serving: two planner-placed pools + live
KV handoff.

A symmetric :class:`~tpu_engine.serving_fleet.ServingFleet` replica does
both phases of a request's life: the compute-bound prompt prefill and the
HBM/batch-bound token decode. Under long-prefill bursty traffic that
coupling is the classic p99-TTFT killer — a 3k-token prompt occupies the
same engine that should be emitting decode tokens, and every co-resident
request stalls behind its chunks. The phases also want *different*
layouts (prefill: highest per-request compute roofline; decode: biggest
KV pool) — exactly the per-workload placement decision
:mod:`tpu_engine.placement` exists to make.

This module splits the fleet:

- **Prefill pool** — replicas sized by ``plan_serving_pool(role="prefill")``
  (compute-roofline ranked). A request prefills there with
  ``hold_kv=True`` and ``max_new_tokens=1``: its first token comes off the
  prefill logits (that IS the TTFT), and the finished slot stays pinned
  with the prompt's K/V until extraction.

- **Wire format** — :class:`KVHandoff`: host-side numpy K/V
  ``[L, T, KV, HD]`` plus metadata, optionally int8-quantized on the wire
  (symmetric absmax codes + per-(lane, kv-head) fp32 scales — the same
  shape :func:`tpu_engine.serving.init_slot_cache` stores for a
  ``kv_quant`` pool, produced by ``quant.quantize_weight(axis=-1)``).
  The wire is the natural place to quantize: it halves handoff bytes and
  a ``kv_quant`` decode pool ingests the codes directly.

- **Decode pool** — replicas sized by ``plan_serving_pool(role="decode")``
  (KV-capacity ranked). The payload enters through
  ``ContinuousBatcher.submit_prefilled``, which rebuilds a single-row
  ingestion cache (:func:`handoff_to_cache`, converting between fp and
  int8 pool modes as needed) and copies it into a reserved slot via the
  ordinary ``_insert_prefill`` jit — so TTFT = prefill-pool latency + one
  decode step, never "queue behind a saturated symmetric replica".

- **Control plane** — :class:`DisaggServingFleet` composes two
  :class:`ServingFleet` pools (each its own scheduler tenant, HBM-gated
  through ``estimate_serving_hbm(pool_role=...)`` against the shared
  per-device ledger) and pumps requests through the phase machine
  ``queued → prefilling → extracting → handoff → decoding → done``. A
  replica lost at ANY phase re-prefills the request from scratch
  (replicas stay stateless-above-the-snapshot; the wire payload is
  re-derivable), each pool's autoscaler runs on its own signal (prefill:
  queue depth + TTFT SLO; decode: occupancy + end-to-end p99), and every
  handoff is a traced span (wire bytes, quantization, src/dst replica) on
  the request's flight-recorder trace.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import numpy as np

from tpu_engine import tracing
from tpu_engine.scheduler import FleetScheduler, JobPriority
from tpu_engine.serving_fleet import (
    ReplicaAutoscaler,
    ServingFleet,
    ServingReplicaSpec,
    build_replica_engine,
)

log = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Wire format
# ---------------------------------------------------------------------------


@dataclass
class KVHandoff:
    """One request's KV state on the handoff wire (host-side, engine-free).

    Invariant (the slot pool's steady state, which is what makes the
    insert trivial): resident K/V covers every history token EXCEPT the
    last emitted one — the decode engine's next step ingests that token's
    K/V as it computes the following logits.

    ``k``/``v`` are ``[L, T, KV, HD]`` where ``T == length``: the wire fp
    dtype when ``quantized`` is False, int8 codes with per-(lane, kv-head)
    fp32 ``k_scale``/``v_scale`` ``[L, T, KV, 1]`` when True (absmax/127
    over head_dim — identical to a ``kv_quant`` slot pool's layout, so a
    quantized decode pool ingests the codes byte-for-byte).
    """

    prompt: list[int]
    emitted: list[int]            # tokens the prefill engine generated (>= 1)
    length: int                   # resident KV tokens == len(prompt+emitted)-1
    n_layers: int
    n_kv_heads: int
    head_dim: int
    dtype: str                    # wire fp dtype name (codes dtype when quantized)
    quantized: bool
    k: np.ndarray
    v: np.ndarray
    k_scale: Optional[np.ndarray] = None
    v_scale: Optional[np.ndarray] = None
    model_name: Optional[str] = None
    extracted_at: float = field(default_factory=time.time)

    @property
    def last_token(self) -> int:
        """The decode engine's first input token."""
        return int(self.emitted[-1]) if self.emitted else int(self.prompt[-1])

    def wire_bytes(self) -> int:
        n = int(self.k.nbytes) + int(self.v.nbytes)
        if self.k_scale is not None:
            n += int(self.k_scale.nbytes) + int(self.v_scale.nbytes)
        return n


def extract_slot_kv(
    cache: Any,
    slot: int,
    length: int,
    *,
    cfg: Any,
    prompt: list[int],
    emitted: list[int],
    quantize: bool = False,
    model_name: Optional[str] = None,
) -> KVHandoff:
    """Slice one slot's resident lanes out of a :class:`SlotCache` into a
    wire payload. Engine-thread only (the pool's donated buffers must not
    be read concurrently with a dispatch). Non-ring pools only — lane m
    holds position m, so ``[:length]`` IS the resident history.

    An already-quantized pool always ships codes + scales (dequantizing
    on extraction would add error AND bytes); a fp pool quantizes on the
    wire only when asked.
    """
    import jax.numpy as jnp  # local: keep module import engine-free

    if getattr(cache, "ring", False):
        raise ValueError("extract_slot_kv does not support ring pools")
    k = cache.k[:, slot, :length]          # [L, T, KV, HD] device
    v = cache.v[:, slot, :length]
    if cache.quantized:
        return KVHandoff(
            prompt=list(prompt), emitted=list(emitted), length=int(length),
            n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim, dtype="int8", quantized=True,
            k=np.asarray(k), v=np.asarray(v),
            k_scale=np.asarray(cache.k_scale[:, slot, :length]),
            v_scale=np.asarray(cache.v_scale[:, slot, :length]),
            model_name=model_name,
        )
    if quantize:
        from tpu_engine.quant import quantize_weight

        # absmax over head_dim (axis=-1): one scale per (layer, lane,
        # kv-head) — the same shape a kv_quant pool stores.
        qk = quantize_weight(k, axis=-1)
        qv = quantize_weight(v, axis=-1)
        return KVHandoff(
            prompt=list(prompt), emitted=list(emitted), length=int(length),
            n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
            head_dim=cfg.head_dim, dtype="int8", quantized=True,
            k=np.asarray(qk.q), v=np.asarray(qv.q),
            k_scale=np.asarray(qk.scale), v_scale=np.asarray(qv.scale),
            model_name=model_name,
        )
    # bf16 has no numpy dtype — ship fp32 on the wire (exact; the insert
    # casts back to the pool dtype, same as the prefill path's astype).
    wire = np.float32 if jnp.dtype(k.dtype) == jnp.dtype(jnp.bfloat16) \
        else np.dtype(k.dtype)
    return KVHandoff(
        prompt=list(prompt), emitted=list(emitted), length=int(length),
        n_layers=cfg.n_layers, n_kv_heads=cfg.n_kv_heads,
        head_dim=cfg.head_dim, dtype=np.dtype(wire).name, quantized=False,
        k=np.asarray(k, dtype=wire), v=np.asarray(v, dtype=wire),
        model_name=model_name,
    )


def _np_quantize(a: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Host-side twin of ``quant.quantize_weight(axis=-1)``: int8 codes +
    per-(lane, kv-head) fp32 scales (absmax/127 over head_dim)."""
    a32 = np.asarray(a, dtype=np.float32)
    scale = np.maximum(np.max(np.abs(a32), axis=-1, keepdims=True) / 127.0,
                       1e-12).astype(np.float32)
    q = np.clip(np.round(a32 / scale), -127, 127).astype(np.int8)
    return q, scale


def handoff_to_cache(
    handoff: KVHandoff,
    *,
    dtype: Any,
    kv_quant: bool,
    chunk: int,
    max_lanes: int,
) -> Any:
    """Materialise a wire payload as the single-row ingestion
    :class:`~tpu_engine.generate.KVCache` that ``_insert_prefill``
    consumes, converted to the destination pool's storage mode (all four
    fp/int8 wire × fp/int8 pool cases). Lane count buckets to ``chunk``
    multiples (same as the prefill path) so compiled insert shapes stay
    few."""
    import jax.numpy as jnp

    from tpu_engine.generate import KVCache

    T = int(handoff.length)
    L, KV, HD = handoff.n_layers, handoff.n_kv_heads, handoff.head_dim
    chunk = max(int(chunk), 1)
    M = min(max(-(-T // chunk) * chunk, chunk), int(max_lanes))
    if M < T:
        raise ValueError(
            f"handoff length {T} exceeds destination pool lanes {max_lanes}"
        )

    if handoff.quantized:
        codes_k, codes_v = handoff.k, handoff.v
        scale_k, scale_v = handoff.k_scale, handoff.v_scale
        if kv_quant:
            fp_k = fp_v = None
        else:
            fp_k = codes_k.astype(np.float32) * scale_k
            fp_v = codes_v.astype(np.float32) * scale_v
    else:
        fp_k, fp_v = handoff.k, handoff.v
        if kv_quant:
            codes_k, scale_k = _np_quantize(fp_k)
            codes_v, scale_v = _np_quantize(fp_v)

    def lanes(arr: np.ndarray, trailing: int, np_dtype: Any) -> np.ndarray:
        out = np.zeros((L, 1, M, KV, trailing), dtype=np_dtype)
        out[:, 0, :T] = arr
        return out

    if kv_quant:
        k = jnp.asarray(lanes(codes_k, HD, np.int8))
        v = jnp.asarray(lanes(codes_v, HD, np.int8))
        k_scale = jnp.asarray(lanes(scale_k, 1, np.float32))
        v_scale = jnp.asarray(lanes(scale_v, 1, np.float32))
    else:
        k = jnp.asarray(lanes(fp_k, HD, np.float32), dtype=dtype)
        v = jnp.asarray(lanes(fp_v, HD, np.float32), dtype=dtype)
        k_scale = v_scale = None

    return KVCache(
        k=k, v=v,
        pos=jnp.full((M,), -1, jnp.int32),  # unused on the non-ring insert
        length=jnp.asarray(T, jnp.int32),
        ring=False, k_scale=k_scale, v_scale=v_scale,
    )


def rebucket_handoff(
    handoff: KVHandoff,
    *,
    chunk: int,
    max_lanes: int,
    kv_quant: bool,
) -> KVHandoff:
    """Re-bucket a wire payload to a *different* destination pool
    geometry (chunk multiple / lane budget) and storage mode, returning
    a new wire payload ready for that pool.

    The reshard plane's serving primitive: a replica migrating across
    pools re-buckets its resident KV through the destination's own
    ingestion layout (:func:`handoff_to_cache`) and re-extracts
    (:func:`extract_slot_kv`), so the round trip exercises exactly the
    lanes/padding/conversion path the destination will decode from —
    all four fp/int8 wire × pool cases, unequal geometries included. A
    payload longer than the destination's lane budget raises the same
    structured ``ValueError`` ingestion would.
    """
    import types

    import jax.numpy as jnp

    cache = handoff_to_cache(
        handoff, dtype=jnp.float32, kv_quant=kv_quant,
        chunk=chunk, max_lanes=max_lanes,
    )
    cfg = types.SimpleNamespace(
        n_layers=handoff.n_layers,
        n_kv_heads=handoff.n_kv_heads,
        head_dim=handoff.head_dim,
    )
    return extract_slot_kv(
        cache, 0, handoff.length, cfg=cfg,
        prompt=handoff.prompt, emitted=handoff.emitted,
        quantize=False,  # a kv_quant staging cache already ships codes
        model_name=handoff.model_name,
    )


# ---------------------------------------------------------------------------
# Disaggregated fleet
# ---------------------------------------------------------------------------

_PENDING_PHASES = ("queued", "prefilling", "extracting", "handoff")


class DisaggServingFleet:
    """Prefill pool + decode pool + the handoff plane between them.

    Each pool is a full :class:`ServingFleet` (scheduler-tenant replicas,
    per-pool HBM admission through ``estimate_serving_hbm(pool_role=...)``,
    its own router and autoscaler); this object owns the REQUEST plane:
    route to a prefill replica (``hold_kv``), collect the first token +
    extracted :class:`KVHandoff`, reserve a decode slot (the decode
    router's free-slot accounting covers queued handoffs), deliver via
    ``submit_prefilled``, and stitch the final token stream. Any replica
    loss re-prefills the request from scratch — bounded by
    ``max_redispatch``.
    """

    def __init__(
        self,
        scheduler: FleetScheduler,
        prefill_spec: ServingReplicaSpec,
        decode_spec: ServingReplicaSpec,
        prefill_autoscaler: Optional[ReplicaAutoscaler] = None,
        decode_autoscaler: Optional[ReplicaAutoscaler] = None,
        wire_quant: bool = False,
        priority: JobPriority = JobPriority.NORMAL,
        submitter: str = "disagg-serving",
        engine_factory: Callable[[ServingReplicaSpec], Any] = build_replica_engine,
        latency_window: int = 512,
        max_redispatch: int = 8,
        prefill_fault_injector: Optional[Any] = None,
        decode_fault_injector: Optional[Any] = None,
    ):
        inflight = prefill_spec.inflight_handoffs or prefill_spec.max_slots
        prefill_spec = prefill_spec.model_copy(update={
            "pool_role": "prefill",
            # The physical pool IS the in-flight handoff window: estimate
            # and allocation agree (see estimate_serving_hbm).
            "max_slots": inflight,
            "inflight_handoffs": inflight,
        })
        decode_spec = decode_spec.model_copy(update={"pool_role": "decode"})
        self.prefill = ServingFleet(
            scheduler, prefill_spec, autoscaler=prefill_autoscaler,
            priority=priority, submitter=f"{submitter}-prefill",
            engine_factory=engine_factory, latency_window=latency_window,
            fault_injector=prefill_fault_injector,
        )
        self.decode = ServingFleet(
            scheduler, decode_spec, autoscaler=decode_autoscaler,
            priority=priority, submitter=f"{submitter}-decode",
            engine_factory=engine_factory, latency_window=latency_window,
            fault_injector=decode_fault_injector,
        )
        self.wire_quant = bool(wire_quant)
        self.max_redispatch = int(max_redispatch)

        self._lock = threading.RLock()
        self._requests: dict[str, dict[str, Any]] = {}
        self._req_seq = 0
        self._latencies: collections.deque[float] = collections.deque(
            maxlen=latency_window)
        self._ttfts: collections.deque[float] = collections.deque(
            maxlen=latency_window)
        self.requests_total = 0
        self.completed_total = 0
        self.failed_total = 0
        self.tokens_total = 0
        self.handoffs_total = 0
        self.handoff_bytes_total = 0
        self.reprefills_total = 0

        rec = tracing.get_recorder()
        self.trace_id = rec.new_trace_id()
        self._fleet_span = rec.start_span(
            f"disagg_fleet:{decode_spec.model_name}",
            kind="disagg_fleet",
            trace_id=self.trace_id,
            attrs={
                "model": decode_spec.model_name,
                "wire_quant": self.wire_quant,
                "prefill_slots": prefill_spec.max_slots,
                "decode_slots": decode_spec.max_slots,
            },
        )

    # -- pool lifecycle ------------------------------------------------------

    def start(self) -> None:
        self.prefill.start()
        self.decode.start()

    def stop(self) -> None:
        self.prefill.stop()
        self.decode.stop()
        if self._fleet_span.t1 is None:
            self._fleet_span.end(stopped=True)

    # -- request plane -------------------------------------------------------

    def submit_request(
        self,
        prompt: list[int],
        max_new_tokens: int = 64,
        temperature: float = 0.0,
    ) -> str:
        with self._lock:
            self._req_seq += 1
            fid = f"dreq_{self._req_seq}"
            self.requests_total += 1
            rec = tracing.get_recorder()
            span = rec.start_span(
                f"disagg_request:{fid}",
                kind="serving_request",
                attrs={
                    "fleet_trace_id": self.trace_id,
                    "prompt_tokens": len(prompt),
                    "max_new_tokens": int(max_new_tokens),
                },
            )
            self._requests[fid] = {
                "prompt": list(prompt),
                "max_new_tokens": int(max_new_tokens),
                "temperature": float(temperature),
                "phase": "queued",
                "prefill_sid": None, "prefill_rid": None,
                "decode_sid": None, "decode_rid": None,
                "prefill_tokens": [], "handoff": None,
                "submitted_at": time.time(),
                "first_token_at": None,
                "redispatches": 0,
                "tokens": [], "error": None,
                "trace_id": span.trace_id, "_span": span,
                "_handoff_span": None,
            }
            self._pump_locked()
            return fid

    def _requeue_locked(self, fid: str, r: dict[str, Any], reason: str) -> None:
        """Re-prefill from scratch (replica loss at any phase). The wire
        payload is re-derivable — prompt + determinism — so retry is the
        correct recovery, same contract as the symmetric fleet's
        re-dispatch."""
        r["redispatches"] += 1
        self.reprefills_total += 1
        hs = r.get("_handoff_span")
        if hs is not None and hs.t1 is None:
            hs.end(status="aborted", reason=reason)
        r["_handoff_span"] = None
        tracing.get_recorder().event(
            "re_prefill", kind="serving", trace_id=r.get("trace_id"),
            parent=r.get("_span"),
            attrs={"fid": fid, "reason": reason, "attempt": r["redispatches"]},
        )
        if r["redispatches"] > self.max_redispatch:
            r["phase"] = "failed"
            r["error"] = f"gave up after {self.max_redispatch} re-dispatches: {reason}"
            self.failed_total += 1
            span = r.get("_span")
            if span is not None and span.t1 is None:
                span.end(status="failed", error=r["error"])
            return
        r.update(phase="queued", prefill_sid=None, prefill_rid=None,
                 decode_sid=None, decode_rid=None, handoff=None,
                 prefill_tokens=[])

    def _finish_locked(self, fid: str, r: dict[str, Any],
                       tokens: list[int]) -> None:
        r["tokens"] = tokens
        r["phase"] = "done"
        self.completed_total += 1
        self.tokens_total += len(tokens)
        latency_ms = (time.time() - r["submitted_at"]) * 1000.0
        self._latencies.append(latency_ms)
        span = r.get("_span")
        if span is not None and span.t1 is None:
            span.end(status="done", tokens=len(tokens),
                     latency_ms=round(latency_ms, 3),
                     redispatches=r["redispatches"])

    def _record_ttft_locked(self, r: dict[str, Any],
                            first_at: Optional[float]) -> None:
        if first_at is None or r["first_token_at"] is not None:
            return
        r["first_token_at"] = float(first_at)
        ttft = (float(first_at) - r["submitted_at"]) * 1000.0
        if ttft >= 0:
            self._ttfts.append(ttft)
        tracing.get_recorder().event(
            "first_token", kind="serving", trace_id=r.get("trace_id"),
            parent=r.get("_span"), attrs={"ttft_ms": round(max(ttft, 0), 2)},
        )

    def _pump_locked(self) -> None:
        """Advance every request's phase machine one notch. Called under
        the lock from submit/result/tick — all engine calls here are
        non-blocking (the replica threads do the device work)."""
        rec = tracing.get_recorder()
        prefill_engines = self.prefill.running_replicas()
        decode_engines = self.decode.running_replicas()
        stats_of = ServingFleet._engine_router_stats
        self.prefill.router.update(
            {sid: stats_of(e) for sid, e in prefill_engines.items()})
        self.decode.router.update(
            {sid: stats_of(e) for sid, e in decode_engines.items()})

        for fid, r in self._requests.items():
            if r["phase"] == "queued":
                sid = self.prefill.router.route(r["prompt"])
                if sid is None or sid not in prefill_engines:
                    continue
                try:
                    rid = prefill_engines[sid].submit(
                        r["prompt"], max_new_tokens=1,
                        temperature=r["temperature"], hold_kv=True,
                    )
                except Exception:  # engine died under us — retry next pump
                    continue
                r["prefill_sid"], r["prefill_rid"] = sid, rid
                r["phase"] = "prefilling"
                rec.event(
                    "route_prefill", kind="serving",
                    trace_id=r.get("trace_id"), parent=r.get("_span"),
                    attrs={"fid": fid, "replica": sid, "engine_rid": rid},
                )

            elif r["phase"] == "prefilling":
                eng = prefill_engines.get(r["prefill_sid"])
                if eng is None:
                    self._requeue_locked(fid, r, "prefill replica lost")
                    continue
                try:
                    out = eng.result(r["prefill_rid"])
                except KeyError:
                    self._requeue_locked(fid, r, "prefill engine forgot request")
                    continue
                if out.get("status") == "failed":
                    self._requeue_locked(fid, r, "prefill engine drained")
                    continue
                if out.get("status") != "done":
                    continue
                r["prefill_tokens"] = list(out.get("tokens", []))
                self._record_ttft_locked(r, out.get("first_token_at"))
                try:
                    eng.request_handoff(r["prefill_rid"],
                                        quantize=self.wire_quant)
                except Exception:
                    self._requeue_locked(fid, r, "handoff request failed")
                    continue
                r["phase"] = "extracting"
                r["_handoff_span"] = rec.start_span(
                    f"kv_handoff:{fid}", kind="kv_handoff",
                    trace_id=r.get("trace_id"), parent=r.get("_span"),
                    attrs={"src_replica": r["prefill_sid"],
                           "quantized": self.wire_quant},
                )

            elif r["phase"] == "extracting":
                eng = prefill_engines.get(r["prefill_sid"])
                if eng is None:
                    self._requeue_locked(
                        fid, r, "prefill replica lost during extraction")
                    continue
                try:
                    h = eng.take_handoff(r["prefill_rid"])
                except RuntimeError:
                    self._requeue_locked(fid, r, "handoff extraction failed")
                    continue
                except KeyError:
                    self._requeue_locked(fid, r, "prefill engine forgot request")
                    continue
                if h is None:
                    continue  # engine thread has not serviced the order yet
                r["handoff"] = h
                self.handoffs_total += 1
                self.handoff_bytes_total += h.wire_bytes()
                r["phase"] = "handoff"

            if r["phase"] == "handoff":  # falls through from "extracting"
                h = r["handoff"]
                remaining = max(
                    r["max_new_tokens"] - len(r["prefill_tokens"]), 0)
                if remaining == 0:
                    # The prefill pool already emitted everything asked for.
                    hs = r.get("_handoff_span")
                    if hs is not None and hs.t1 is None:
                        hs.end(status="skipped", reason="no decode tokens needed")
                    r["handoff"] = None
                    self._finish_locked(fid, r, list(r["prefill_tokens"]))
                    continue
                sid = self.decode.router.route(r["prompt"])
                if sid is None or sid not in decode_engines:
                    continue  # no decode slot yet — payload waits host-side
                try:
                    rid = decode_engines[sid].submit_prefilled(
                        h, max_new_tokens=remaining,
                        temperature=r["temperature"],
                    )
                except Exception:
                    self._requeue_locked(fid, r, "decode submit failed")
                    continue
                r["decode_sid"], r["decode_rid"] = sid, rid
                r["handoff"] = None  # delivered — the decode engine owns it
                r["phase"] = "decoding"
                hs = r.get("_handoff_span")
                if hs is not None and hs.t1 is None:
                    hs.end(
                        status="delivered", dst_replica=sid,
                        wire_bytes=h.wire_bytes(), kv_tokens=h.length,
                        quantized=h.quantized,
                    )
                rec.event(
                    "route_decode", kind="serving",
                    trace_id=r.get("trace_id"), parent=r.get("_span"),
                    attrs={"fid": fid, "replica": sid, "engine_rid": rid,
                           "wire_bytes": h.wire_bytes()},
                )

            elif r["phase"] == "decoding":
                eng = decode_engines.get(r["decode_sid"])
                if eng is None:
                    self._requeue_locked(fid, r, "decode replica lost")
                    continue
                try:
                    out = eng.result(r["decode_rid"])
                except KeyError:
                    self._requeue_locked(fid, r, "decode engine forgot request")
                    continue
                if out.get("status") == "failed":
                    self._requeue_locked(fid, r, "decode engine drained")
                    continue
                if out.get("status") == "done":
                    self._finish_locked(
                        fid, r,
                        list(r["prefill_tokens"]) + list(out.get("tokens", [])),
                    )

    def result(self, fid: str) -> dict[str, Any]:
        with self._lock:
            r = self._requests.get(fid)
            if r is None:
                raise KeyError(fid)
            self._pump_locked()
            out: dict[str, Any] = {
                "id": fid,
                "phase": r["phase"],
                "prefill_replica": r["prefill_sid"],
                "decode_replica": r["decode_sid"],
                "redispatches": r["redispatches"],
            }
            if r["phase"] == "done":
                out["status"] = "done"
                out["tokens"] = list(r["tokens"])
            elif r["phase"] == "failed":
                out["status"] = "failed"
                out["error"] = r["error"]
                out["tokens"] = list(r["tokens"])
            else:
                out["status"] = ("running" if r["phase"] == "decoding"
                                 else "pending")
                out["tokens"] = list(r["prefill_tokens"])
            if r["first_token_at"] is not None:
                out["ttft_ms"] = round(
                    (r["first_token_at"] - r["submitted_at"]) * 1000.0, 2)
            out["trace_id"] = r.get("trace_id")
            return out

    def wait(self, fid: str, timeout: float = 60.0,
             poll_s: float = 0.005) -> dict[str, Any]:
        """Poll-pump until the request is terminal (the pools' replica
        threads do the device work; this just advances the phase
        machine)."""
        deadline = time.time() + timeout
        while True:
            out = self.result(fid)
            if out["status"] in ("done", "failed"):
                return out
            if time.time() >= deadline:
                raise TimeoutError(f"request {fid} not done in {timeout}s")
            time.sleep(poll_s)

    # -- control loop --------------------------------------------------------

    def _pct(self, vals: collections.deque, q: float) -> Optional[float]:
        if not vals:
            return None
        s = sorted(vals)
        return round(s[min(int(q * (len(s) - 1)), len(s) - 1)], 2)

    def ttft_percentiles(self) -> dict[str, Optional[float]]:
        with self._lock:
            return {"p50": self._pct(self._ttfts, 0.50),
                    "p99": self._pct(self._ttfts, 0.99)}

    def p99_latency_ms(self) -> Optional[float]:
        with self._lock:
            return self._pct(self._latencies, 0.99)

    def _pool_depths_locked(self) -> tuple[int, int]:
        """(prefill-side, decode-side) demand: requests waiting on each
        pool — the two SEPARATE autoscaler signals."""
        prefill_depth = sum(
            1 for r in self._requests.values()
            if r["phase"] in ("queued", "prefilling"))
        decode_depth = sum(
            1 for r in self._requests.values()
            if r["phase"] in ("extracting", "handoff"))
        for eng in self.decode.running_replicas().values():
            try:
                decode_depth += int(eng.stats().get("queued_handoffs", 0))
            except Exception:  # noqa: BLE001 — engine mid-teardown
                continue
        return prefill_depth, decode_depth

    def _drive_pool(self, pool: ServingFleet, now: float, depth: int,
                    p99: Optional[float],
                    ttft_p99: Optional[float]) -> None:
        """ServingFleet.tick's convergence-guarded scale action, driven by
        the DISAGG phase-machine's per-pool signal instead of the pool's
        own (unused) request plane."""
        n_running = len(pool.running_replicas())
        desired = pool.autoscaler.observe(
            now, depth, p99, n_running, ttft_p99_ms=ttft_p99)
        if desired > pool.desired_replicas:
            pool.scale_ups_total += 1
            pool.scale_to(desired)
        elif desired < pool.desired_replicas and n_running >= pool.desired_replicas:
            pool.scale_downs_total += 1
            pool.scale_to(desired)

    def tick(self, now: Optional[float] = None) -> dict[str, Any]:
        """One control pass: pump the phase machine, then scale each pool
        on ITS signal — prefill on queue depth + TTFT SLO, decode on
        handoff/occupancy depth + end-to-end p99."""
        now = time.time() if now is None else now
        with self._lock:
            self._pump_locked()
            prefill_depth, decode_depth = self._pool_depths_locked()
            ttft_p99 = self._pct(self._ttfts, 0.99)
            p99 = self._pct(self._latencies, 0.99)
            self._drive_pool(self.prefill, now, prefill_depth, None, ttft_p99)
            self._drive_pool(self.decode, now, decode_depth, p99, None)
        return self.status()

    def status(self) -> dict[str, Any]:
        with self._lock:
            pending = sum(1 for r in self._requests.values()
                          if r["phase"] in _PENDING_PHASES)
            decoding = sum(1 for r in self._requests.values()
                           if r["phase"] == "decoding")
            return {
                "wire_quant": self.wire_quant,
                "requests_total": self.requests_total,
                "completed_total": self.completed_total,
                "failed_total": self.failed_total,
                "tokens_total": self.tokens_total,
                "pending_requests": pending,
                "decoding_requests": decoding,
                "handoffs_total": self.handoffs_total,
                "handoff_bytes_total": self.handoff_bytes_total,
                "reprefills_total": self.reprefills_total,
                "ttft_p50_ms": self._pct(self._ttfts, 0.50),
                "ttft_p99_ms": self._pct(self._ttfts, 0.99),
                "p99_latency_ms": self._pct(self._latencies, 0.99),
                "prefill_pool": self.prefill.status(),
                "decode_pool": self.decode.status(),
            }
