"""Weight-only int8 quantization for serving.

The reference control plane launches DeepSpeed jobs with fp16/bf16
configs only (``deepspeed_launcher.py``: precision knobs, no inference
quantization — the reference has no inference path at all). Serving is
where quantization pays on TPU: decode is weight-HBM-bandwidth-bound
(every generated token re-reads every weight), so storing projection
kernels as int8 halves both the weight footprint and the per-token HBM
traffic — the same lever as the KV-cache int8 mode
(:func:`tpu_engine.generate.init_cache` ``kv_quant``), applied to the
other half of decode's working set. Together they serve llama-7b-class
models on a single 16 GiB v5e chip.

Scheme: symmetric per-output-channel absmax. A kernel ``[..., in, out]``
becomes int8 codes of the same shape plus an fp32 scale ``[..., 1, out]``
(the contracted dim reduced). Because the scale is constant along the
contraction, it applies AFTER the matmul — ``(h @ q) * scale`` — so the
int8→bf16 convert fuses into the dot's operand read (XLA producer
fusion) and HBM sees only the int8 bytes. int8 magnitudes ≤ 127 are
exact in bfloat16, so the cast loses nothing.

What quantizes: the per-layer projection kernels (q/k/v/o,
gate/up/down — incl. stacked MoE expert kernels — or fc/proj for
GPT-2-family) and the LM head. What stays in the master dtype:
embeddings (a lookup, and the tied head of gpt2/gemma — tied-head
models keep a full-precision head), norm scales/biases, projection
biases, the MoE router (fp32-critical and ~0.01% of bytes), and qk-norm
scales.

Training never sees :class:`QuantWeight` — this is a serving-side
transform applied to a trained (or snapshot) param tree.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P


@jax.tree_util.register_dataclass
@dataclass
class QuantWeight:
    """An int8-quantized linear kernel (a pytree — crosses jit/scan
    boundaries; ``lax.scan`` over a stacked ``[L, ...]`` tree slices
    ``q`` and ``scale`` in lockstep).

    ``q``: int8 codes, the original kernel's shape ``[..., in, out]``.
    ``scale``: fp32, ``[..., 1, out]`` — per-output-channel absmax/127,
    constant along the contracted (input) dim so it can be applied to
    the matmul OUTPUT.
    """

    q: jax.Array
    scale: jax.Array

    @property
    def shape(self) -> tuple[int, ...]:
        return self.q.shape

    @property
    def ndim(self) -> int:
        return self.q.ndim


def quantize_weight(w: jax.Array, axis: int = -2) -> QuantWeight:
    """Symmetric int8 quantization with the absmax taken over ``axis``
    (the contracted dim — every kernel this module touches contracts its
    second-to-last dim)."""
    w32 = w.astype(jnp.float32)
    scale = jnp.max(jnp.abs(w32), axis=axis, keepdims=True) / 127.0
    scale = jnp.maximum(scale, 1e-12)
    q = jnp.clip(jnp.round(w32 / scale), -127, 127).astype(jnp.int8)
    return QuantWeight(q=q, scale=scale)


def dequantize_weight(qw: QuantWeight, dtype=jnp.float32) -> jax.Array:
    return (qw.q.astype(jnp.float32) * qw.scale).astype(dtype)


# Per-layer projection names whose "kernel" quantizes. Covers the llama
# family (q/k/v/o/gate/up/down), GPT-2 (q/k/v/o/fc/proj), and MoE
# (stacked expert gate/up/down; the router stays fp32).
_QUANT_LAYER_KEYS = ("q", "k", "v", "o", "gate", "up", "down", "fc", "proj")


def _walk(params: dict[str, Any], kernel_fn) -> dict[str, Any]:
    """Structural walk shared by the param transform and the
    pspec mirror: applies ``kernel_fn`` to every quantization site,
    preserving everything else (biases, norms, router, embeddings)."""
    out = dict(params)
    if "layers" in params:
        layers = dict(params["layers"])
        for name in _QUANT_LAYER_KEYS:
            sub = layers.get(name)
            if isinstance(sub, dict) and "kernel" in sub:
                new_sub = dict(sub)
                new_sub["kernel"] = kernel_fn(sub["kernel"])
                layers[name] = new_sub
        out["layers"] = layers
    if "lm_head" in params:
        head = dict(params["lm_head"])
        head["kernel"] = kernel_fn(head["kernel"])
        out["lm_head"] = head
    return out


def quantize_params(params: dict[str, Any]) -> dict[str, Any]:
    """Param tree → serving tree with projection kernels as
    :class:`QuantWeight`. Idempotent-hostile by design: quantizing an
    already-quantized tree raises (re-quantization would silently
    compound the error)."""

    def quant(kernel):
        if isinstance(kernel, QuantWeight):
            raise ValueError("params are already int8-quantized")
        return quantize_weight(kernel)

    return _walk(params, quant)


def quantized_param_bytes(params: dict[str, Any]) -> int:
    """Total bytes of a (possibly quantized) param tree — int8 leaves
    count 1 byte, scales 4; the fit benchmarks' accounting helper."""
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(params)
    )


def quantize_pspecs(pspecs: dict[str, Any], qparams: dict[str, Any]) -> dict[str, Any]:
    """Mirror a PartitionSpec tree onto a quantized param tree: at each
    :class:`QuantWeight` site the kernel's spec applies to ``q``
    unchanged, and the scale inherits it with the contracted dim (which
    collapsed to size 1) unsharded. ``qparams`` supplies each site's
    rank (a spec may have trailing dims trimmed); both trees are walked
    in one paired traversal, so a site present in one but not the other
    fails loudly instead of misaligning.
    """

    def mirror(spec: P, site) -> QuantWeight:
        if not isinstance(site, QuantWeight):
            raise ValueError(
                "quantize_pspecs needs the QUANTIZED param tree to read "
                f"kernel ranks (found {type(site).__name__}); call "
                "quantize_params first"
            )
        axes = list(spec) + [None] * (site.ndim - len(spec))
        axes[-2] = None  # the contracted dim is size 1 in the scale
        return QuantWeight(q=spec, scale=P(*axes))

    out = dict(pspecs)
    if ("layers" in pspecs) != ("layers" in qparams):
        raise ValueError("pspec and param trees disagree on 'layers'")
    if "layers" in pspecs:
        layers = dict(pspecs["layers"])
        for name in _QUANT_LAYER_KEYS:
            spec_sub, par_sub = layers.get(name), qparams["layers"].get(name)
            has_spec = isinstance(spec_sub, dict) and "kernel" in spec_sub
            has_par = isinstance(par_sub, dict) and "kernel" in par_sub
            if has_spec != has_par:
                raise ValueError(f"pspec/param trees disagree on layers.{name}")
            if has_spec:
                new_sub = dict(spec_sub)
                new_sub["kernel"] = mirror(spec_sub["kernel"], par_sub["kernel"])
                layers[name] = new_sub
        out["layers"] = layers
    if ("lm_head" in pspecs) != ("lm_head" in qparams):
        raise ValueError("pspec and param trees disagree on 'lm_head'")
    if "lm_head" in pspecs:
        head = dict(pspecs["lm_head"])
        head["kernel"] = mirror(head["kernel"], qparams["lm_head"]["kernel"])
        out["lm_head"] = head
    return out


# ---------------------------------------------------------------------------
# Quantized serving snapshots: quantize once, serve many times
# ---------------------------------------------------------------------------

_MANIFEST = "quant_snapshot.json"


def save_quantized(qparams: dict[str, Any], out_dir: str,
                   model_config: Any = None) -> str:
    """Persist a quantized serving tree as one ``.npy`` per leaf plus a
    manifest. int8 codes dominate the bytes, so a llama-7b snapshot is
    ~7 GB instead of 13.5 (bf16) or 27 (fp32) — and
    :func:`load_quantized` mmaps + uploads it one leaf at a time, so a
    serving host never materialises the tree twice.

    The tree must contain at least one :class:`QuantWeight` (use
    :func:`quantize_params` first — persisting an unquantized tree here
    would silently lose the format's point and is probably a bug)."""
    os.makedirs(out_dir, exist_ok=True)
    if os.path.exists(os.path.join(out_dir, _MANIFEST)):
        # Leaf files are written in place; overwriting an existing
        # snapshot would leave a valid old manifest over mixed-step leaf
        # files if interrupted — and load_quantized would serve that
        # Frankenstein tree without error. Fresh directory per export.
        raise ValueError(
            f"'{out_dir}' already holds a snapshot; export to a fresh "
            "directory (a crashed overwrite would silently mix steps)"
        )
    manifest: dict[str, Any] = {"leaves": {}}
    if model_config is not None:
        import dataclasses as _dc

        # The frozen ModelConfig is all primitives — a self-describing
        # snapshot serves without the caller re-supplying the config.
        manifest["model_config"] = _dc.asdict(model_config)
    n_quant = 0

    _CHUNK_BYTES = 128 * 2**20

    def record(path: str, arr, kind: str) -> None:
        fname = path.replace("/", "__") + ".npy"
        fpath = os.path.join(out_dir, fname)
        shape = tuple(arr.shape)
        nbytes = int(np.prod(shape or (1,))) * jnp.dtype(arr.dtype).itemsize
        if nbytes > _CHUNK_BYTES and shape and shape[0] > 1:
            # Big stacked leaves (a 7B gate kernel is ~1.4 GB) fetch in
            # bounded slices along the leading dim: one giant device→host
            # transfer can stall remote runtimes, and the host never
            # needs more than a chunk resident. The memmap writes the
            # same .npy format np.save would.
            rows = max(1, shape[0] * _CHUNK_BYTES // nbytes)
            first = np.asarray(arr[:1])
            out = np.lib.format.open_memmap(
                fpath, mode="w+", dtype=first.dtype, shape=shape
            )
            out[:1] = first
            for i in range(1, shape[0], rows):
                out[i:i + rows] = np.asarray(arr[i:i + rows])
            out.flush()
            host_dtype = first.dtype
        else:
            host = np.asarray(arr)
            np.save(fpath, host)
            host_dtype = host.dtype
        manifest["leaves"][path] = {
            "file": fname, "kind": kind, "dtype": str(host_dtype),
            "shape": list(shape),
        }

    def walk(node, prefix: str) -> None:
        nonlocal n_quant
        if isinstance(node, QuantWeight):
            n_quant += 1
            record(prefix + ".q", node.q, "quant_q")
            record(prefix + ".scale", node.scale, "quant_scale")
        elif isinstance(node, dict):
            for k, v in node.items():
                walk(v, f"{prefix}/{k}" if prefix else k)
        else:
            record(prefix, node, "array")

    walk(qparams, "")
    if not n_quant:
        raise ValueError(
            "tree has no QuantWeight leaves — quantize_params first"
        )
    tmp = os.path.join(out_dir, _MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f)
    os.replace(tmp, os.path.join(out_dir, _MANIFEST))
    return out_dir


def load_quantized_config(snapshot_dir: str) -> Optional[Any]:
    """The ModelConfig recorded by :func:`save_quantized`, or None for
    snapshots written without one."""
    with open(os.path.join(snapshot_dir, _MANIFEST)) as f:
        raw = json.load(f).get("model_config")
    if raw is None:
        return None
    from tpu_engine.models.transformer import ModelConfig

    return ModelConfig(**raw)


def load_quantized(snapshot_dir: str,
                   shardings: Optional[dict[str, Any]] = None) -> dict[str, Any]:
    """Rebuild a quantized serving tree from :func:`save_quantized`
    output. Each leaf is mmapped and uploaded before the next is touched
    (bounded host residency). ``shardings``: an optional tree of
    NamedShardings matching the QUANTIZED structure (build with
    ``quantize_pspecs`` + ``named_shardings``) for mesh-sharded serving;
    omitted leaves go to the default device."""
    with open(os.path.join(snapshot_dir, _MANIFEST)) as f:
        manifest = json.load(f)
    leaves = manifest["leaves"]

    def put(path: str, sh) -> jax.Array:
        meta = leaves[path]
        host = np.load(os.path.join(snapshot_dir, meta["file"]), mmap_mode="r")
        want = np.dtype(meta["dtype"])  # ml_dtypes names resolve via jax
        if host.dtype != want:
            # Extended dtypes (bfloat16) round-trip .npy as raw void
            # bytes — reinterpret, don't convert.
            host = host.view(want)
        return jax.device_put(host, sh) if sh is not None else jnp.asarray(host)

    # Group leaf paths back into the nested dict structure.
    tree: dict[str, Any] = {}
    quant_sites: dict[str, dict[str, str]] = {}
    for path, meta in leaves.items():
        if meta["kind"] in ("quant_q", "quant_scale"):
            site, field = path.rsplit(".", 1)
            quant_sites.setdefault(site, {})[field] = path

    def sharding_at(path: str):
        node = shardings
        if node is None:
            return None
        for part in path.split("/"):
            if not isinstance(node, dict) or part not in node:
                return None
            node = node[part]
        return node

    def insert(path: str, value) -> None:
        parts = path.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value

    for path, meta in leaves.items():
        if meta["kind"] != "array":
            continue
        insert(path, put(path, sharding_at(path)))
    for site, fields in quant_sites.items():
        sh = sharding_at(site)
        q_sh = sh.q if isinstance(sh, QuantWeight) else None
        s_sh = sh.scale if isinstance(sh, QuantWeight) else None
        insert(site, QuantWeight(
            q=put(fields["q"], q_sh), scale=put(fields["scale"], s_sh),
        ))
    return tree
