"""Serving fleet: scheduler-managed decode replicas over the training fleet.

``tpu_engine/serving.py`` is one in-process :class:`ContinuousBatcher`; this
module is the subsystem that makes inference a first-class
:class:`~tpu_engine.scheduler.FleetScheduler` workload — the "serves heavy
traffic from millions of users" path:

- :class:`ServingReplicaSpec` — one replica's shape: model, slot pool,
  max sequence length, tensor parallelism, weight/KV quantization, prefix
  cache budget. Its HBM footprint goes through the KV-pool plane
  (:func:`tpu_engine.hbm_estimate.estimate_serving_hbm`) so admission is
  gated on params + ``max_slots × lanes`` of KV at the replica's dtype,
  against the same per-device reservation ledger training jobs use
  (placement-semantics stance: ONE cost model for every placement
  decision, arXiv:2601.02311).

- :class:`ServingReplicaJob` — the scheduler-driven lifecycle around one
  decode engine. Submitted with ``workload="serving"`` it rides the same
  priority queue, quotas, drain/cancel and preempt machinery as training;
  a CRITICAL training job evicts it through the ordinary watcher seam, but
  the teardown is **checkpoint-free** — a replica is stateless above its
  snapshot, so eviction drops the engine and the scheduler requeues the
  submission for re-admission when the training job drains.

- :class:`FleetRouter` — smooth weighted round-robin dispatch, weighted by
  each replica's measured decode throughput × free-slot fraction (Poplar's
  serve-the-degraded-host-less stance, arXiv:2408.12596), with
  shared-prefix affinity: requests opening with a system prompt already
  resident in some replica's prefix cache land on that replica.

- :class:`ReplicaAutoscaler` — replica count between min/max against a
  sliding window of queue depth and a p99-latency SLO, scale-down behind a
  hysteresis cooldown so a traffic dip does not thrash replicas the next
  burst needs. Pure function of (now, observation) — virtual-clock
  drivable, which is how ``benchmarks/serving_fleet_sim.py`` proves it.

- :class:`ServingFleet` — the orchestrator tying them together: submits
  replicas, routes requests, ticks the autoscaler, reports stats (the
  ``tpu_engine_serving_fleet_*`` Prometheus families render them).
"""

from __future__ import annotations

import collections
import logging
import math
import threading
import time
from typing import Any, Callable, Iterable, Optional

from pydantic import BaseModel, ConfigDict, Field

from tpu_engine import journal as journal_mod
from tpu_engine import tracing
from tpu_engine.hbm_estimate import HBMEstimate, estimate_serving_hbm
from tpu_engine.mesh_runtime import MeshConfig
from tpu_engine.scheduler import (
    TERMINAL_STATES,
    FleetScheduler,
    JobPriority,
    Submission,
    SubmissionState,
)
from tpu_engine.sharding import Precision, TPUTrainConfig
from tpu_engine.supervisor import JobStatus

log = logging.getLogger(__name__)


class ServingReplicaSpec(BaseModel):
    """Shape of one decode replica — every replica of a fleet is identical
    (heterogeneity is handled by the router's measured weights, not by
    per-replica shapes)."""

    model_config = ConfigDict(extra="forbid")

    model_name: str
    # Weight source: an int8 serving snapshot directory written by
    # ``TrainingJob.export_quantized_snapshot`` (quantize once, serve N
    # replicas), or None → fresh deterministic init (test/demo use).
    snapshot_dir: Optional[str] = None
    max_slots: int = Field(default=8, ge=1, le=256)
    max_len: int = Field(default=1024, ge=8)
    tensor_parallel: int = Field(default=1, ge=1)
    compute_dtype: Precision = Precision.BF16
    # "int8" → weight-only quantization (snapshot weights arrive already
    # quantized; a fresh init is quantized at build).
    weight_quant: Optional[str] = Field(default=None, pattern="^int8$")
    kv_quant: bool = False
    prefill_chunk: int = Field(default=256, ge=16)
    prefix_cache_tokens: int = Field(default=0, ge=0)
    decode_chunk_steps: int = Field(default=8, ge=1)
    eos_id: Optional[int] = Field(default=None, ge=0)
    seed: int = 0
    # Disaggregated serving (tpu_engine/disagg.py): a "prefill" pool's
    # replicas hold KV only for in-flight handoffs (its admission estimate
    # sizes the pool to ``inflight_handoffs`` slots with the prefill
    # workspace dominant); "decode" pools estimate like "unified" ones.
    # "draft" pools (tpu_engine/spec_pool.py) are tiny decode pools ranked
    # by propose latency that backfill fragmented verify-pool headroom.
    pool_role: str = Field(
        default="unified", pattern="^(unified|prefill|decode|draft)$"
    )
    inflight_handoffs: Optional[int] = Field(default=None, ge=1)

    def placement_config(self) -> TPUTrainConfig:
        """The config the scheduler queues for one replica: its mesh IS the
        replica's gang (tensor_parallel devices), and everything
        weight-shaped about footprint comes from the serving estimator, not
        from this stub's training fields."""
        return TPUTrainConfig(
            model_name=self.model_name,
            mesh=MeshConfig(data=1, model=self.tensor_parallel),
            micro_batch_size=1,
            seq_len=32,
            precision=self.compute_dtype,
            checkpoint_dir=None,  # checkpoint-free teardown
        )

    def estimate(self, *_args: Any, **_kw: Any) -> Optional[HBMEstimate]:
        """KV-pool HBM plane for this replica (scheduler ``estimate_fn``
        signature: extra args are the config/n_avail it passes — the spec
        already knows its own shape)."""
        return estimate_serving_hbm(
            self.model_name,
            self.max_slots,
            self.max_len,
            tensor_parallel=self.tensor_parallel,
            compute_dtype=self.compute_dtype,
            kv_quant=self.kv_quant,
            weight_quant=(
                "int8" if self.snapshot_dir is not None else self.weight_quant
            ),
            prefill_chunk=self.prefill_chunk,
            prefix_cache_tokens=self.prefix_cache_tokens,
            pool_role=self.pool_role,
            inflight_handoffs=self.inflight_handoffs,
        )


def build_replica_engine(spec: ServingReplicaSpec) -> Any:
    """Default engine factory: a real :class:`ContinuousBatcher` from the
    spec's weight source (int8 snapshot or fresh init), mesh-sharded when
    ``tensor_parallel > 1``. Heavy imports stay inside — fleets under test
    or simulation inject their own factory and never touch JAX."""
    import jax

    from tpu_engine.models import transformer as tfm
    from tpu_engine.serving import ContinuousBatcher

    mesh = None
    if spec.snapshot_dir is not None:
        from tpu_engine.quant import load_quantized, load_quantized_config

        cfg = load_quantized_config(spec.snapshot_dir)
        if cfg is None:
            raise ValueError(
                f"snapshot at '{spec.snapshot_dir}' has no recorded model_config"
            )
        qsh = None
        if spec.tensor_parallel > 1:
            from tpu_engine.mesh_runtime import build_mesh
            from tpu_engine.models.transformer import init_params, logical_axes
            from tpu_engine.quant import quantize_params, quantize_pspecs
            from tpu_engine.sharding import (
                ShardingStage,
                named_shardings,
                param_pspecs,
            )

            mesh = build_mesh(MeshConfig(model=spec.tensor_parallel))
            abs_q = jax.eval_shape(quantize_params, jax.eval_shape(
                lambda k: init_params(k, cfg), jax.random.PRNGKey(0)
            ))
            qsh = named_shardings(mesh, quantize_pspecs(
                param_pspecs(logical_axes(cfg), ShardingStage.FULL_PARTITIONING),
                abs_q,
            ))
        params = load_quantized(spec.snapshot_dir, shardings=qsh)
    else:
        cfg = tfm.MODEL_CONFIGS.get(spec.model_name)
        if cfg is None:
            raise ValueError(f"unknown model '{spec.model_name}'")
        params = tfm.init_params(jax.random.PRNGKey(spec.seed), cfg)
        if spec.weight_quant == "int8":
            from tpu_engine.quant import quantize_params

            params = quantize_params(params)
        if spec.tensor_parallel > 1:
            from tpu_engine.mesh_runtime import build_mesh
            from tpu_engine.models.transformer import logical_axes
            from tpu_engine.sharding import (
                ShardingStage,
                named_shardings,
                param_pspecs,
            )

            mesh = build_mesh(MeshConfig(model=spec.tensor_parallel))
            specs = param_pspecs(logical_axes(cfg), ShardingStage.FULL_PARTITIONING)
            if spec.weight_quant == "int8":
                from tpu_engine.quant import quantize_pspecs

                specs = quantize_pspecs(specs, params)
            params = jax.device_put(params, named_shardings(mesh, specs))

    return ContinuousBatcher(
        params, cfg, max_slots=spec.max_slots, max_len=spec.max_len,
        eos_id=spec.eos_id, seed=spec.seed,
        chunk_steps=spec.decode_chunk_steps,
        prefill_chunk=spec.prefill_chunk, mesh=mesh,
        kv_quant=spec.kv_quant,
        prefix_cache_tokens=spec.prefix_cache_tokens,
    )


class _ReplicaWatcher:
    """The scheduler's preempt verb for a replica: no GCE poll, no
    emergency save — fire the event, the job loop tears the engine down."""

    def __init__(self) -> None:
        self.fired = threading.Event()

    def simulate_interruption(self) -> None:
        self.fired.set()


class ServingReplicaJob:
    """One decode replica under scheduler lifecycle.

    Presents the job surface :class:`FleetScheduler` drives (``start`` /
    ``join`` / ``is_alive`` / ``status`` / ``watcher`` / ``_stop``) around
    an injected engine. The run thread builds the engine (weight load —
    potentially slow — happens off the scheduler's admit pass), then pumps
    ``engine.step()`` until stopped or preempted. Preemption is
    checkpoint-free: drop the engine, report ``PREEMPTED`` — the scheduler
    requeues the submission and a later admission rebuilds from the
    snapshot. In-flight requests die with the engine; the fleet router
    re-dispatches them (stateless-above-the-snapshot is the contract that
    makes replicas safely evictable by CRITICAL training jobs).
    """

    def __init__(
        self,
        sub: Submission,
        spec: ServingReplicaSpec,
        engine_factory: Callable[[ServingReplicaSpec], Any] = build_replica_engine,
        idle_sleep_s: float = 0.005,
        fault_injector: Optional[Any] = None,
    ):
        self.job_id = sub.job_id
        self.config = sub.config
        self.spec = spec
        # Chaos seam: an armed tpu_engine.faults.FaultInjector whose
        # preemption-signal faults fire against THIS replica's token
        # counter — same consumable contract as the training supervisor.
        self._faults = fault_injector
        self.status = JobStatus.PENDING
        self.error: Optional[str] = None
        self.current_step = 0  # tokens generated — the replica's "progress"
        self.watcher = _ReplicaWatcher()
        self.engine: Any = None
        self.engine_ready = threading.Event()
        self._engine_factory = engine_factory
        self._idle_sleep_s = idle_sleep_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"serving-replica-{self.job_id}"
        )

    @property
    def is_alive(self) -> bool:
        return self._thread.is_alive()

    def start(self) -> None:
        self._thread.start()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout)

    def describe(self) -> dict[str, Any]:
        return {
            "job_id": self.job_id,
            "status": self.status.value,
            "workload": "serving",
            "model_name": self.spec.model_name,
            "tokens_generated": self.current_step,
            "engine_ready": self.engine_ready.is_set(),
            "error": self.error,
        }

    def _run(self) -> None:
        try:
            engine = self._engine_factory(self.spec)
        except Exception as e:  # noqa: BLE001 — weight load / build boundary
            self.status = JobStatus.FAILED
            self.error = f"{type(e).__name__}: {e}"
            log.exception("serving replica %s: engine build failed", self.job_id)
            return
        self.engine = engine
        self.engine_ready.set()
        self.status = JobStatus.RUNNING
        try:
            while True:
                if self._faults is not None and self._faults.preempt_due(
                    self.current_step
                ):
                    self.watcher.fired.set()
                if self.watcher.fired.is_set():
                    self.status = JobStatus.PREEMPTED
                    return
                if self._stop.is_set():
                    self.status = JobStatus.STOPPED
                    return
                produced = int(engine.step() or 0)
                self.current_step += produced
                if produced == 0:
                    self._stop.wait(self._idle_sleep_s)
        except Exception as e:  # noqa: BLE001 — decode loop boundary
            self.status = JobStatus.FAILED
            self.error = f"{type(e).__name__}: {e}"
            log.exception("serving replica %s: decode loop failed", self.job_id)
        finally:
            # Checkpoint-free teardown: the engine (params + KV pool) is
            # this thread's only strong reference — dropping it frees the
            # replica's HBM for whoever preempted us.
            self.engine = None
            self.engine_ready.clear()


class _PercentileWindow:
    """Bounded sliding-window percentile estimator.

    Replaces the sort-the-whole-window percentile reads: each sample
    lands in a log-spaced bucket, a deque of bucket indexes keeps the
    window bounded, and a percentile read walks the fixed bucket array —
    O(buckets), independent of the window length and of how many samples
    ever passed through. With ``growth=1.015`` the representative value
    (the geometric bucket midpoint) is within ~0.75% of the exact
    sample — inside the 1% contract the property test pins. Values at or
    below ``lo_ms`` collapse into bucket 0 (reported as ``lo_ms``);
    values beyond ``hi_ms`` saturate the last bucket.
    """

    __slots__ = ("window", "_lo", "_log_growth", "_nb", "_counts", "_idxs",
                 "_total")

    def __init__(
        self,
        window: int = 512,
        lo_ms: float = 0.05,
        hi_ms: float = 1e7,
        growth: float = 1.015,
    ):
        self.window = int(window)
        self._lo = float(lo_ms)
        self._log_growth = math.log(float(growth))
        self._nb = int(math.ceil(math.log(hi_ms / lo_ms) / self._log_growth)) + 2
        self._counts = [0] * self._nb
        self._idxs: collections.deque[int] = collections.deque()
        self._total = 0

    def __len__(self) -> int:
        return self._total

    def _bucket(self, v: float) -> int:
        if v <= self._lo:
            return 0
        return min(
            int(math.log(v / self._lo) / self._log_growth) + 1, self._nb - 1
        )

    def _value_at(self, idx: int) -> float:
        if idx <= 0:
            return self._lo
        return self._lo * math.exp(self._log_growth * (idx - 0.5))

    def add(self, v: float) -> None:
        idx = self._bucket(float(v))
        self._idxs.append(idx)
        self._counts[idx] += 1
        self._total += 1
        while self._total > self.window:
            self._counts[self._idxs.popleft()] -= 1
            self._total -= 1

    def percentiles(self, qs: Iterable[float]) -> list[Optional[float]]:
        """Window percentiles at the same rank convention the sorted-window
        read used (``vals[int(q * (n - 1))]``); all-None when empty."""
        qs = list(qs)
        if not self._total:
            return [None] * len(qs)
        ranks = [min(int(q * (self._total - 1)), self._total - 1) for q in qs]
        out: list[Optional[float]] = [None] * len(qs)
        order = sorted(range(len(qs)), key=lambda i: ranks[i])
        cum, oi = 0, 0
        for idx, c in enumerate(self._counts):
            if not c:
                continue
            cum += c
            while oi < len(order) and ranks[order[oi]] < cum:
                out[order[oi]] = self._value_at(idx)
                oi += 1
            if oi == len(order):
                break
        return out


class FleetRouter:
    """Throughput-weighted dispatch with shared-prefix affinity.

    Smooth weighted round-robin (the nginx algorithm) over
    ``weight = (ε + tokens/sec) × (ε + free-slot fraction)``: a degraded
    replica — slow host, busy slots — serves proportionally less traffic
    instead of gating the fleet, and a cold replica (no throughput yet)
    still receives work through the ε floor. Requests whose leading
    ``affinity_tokens`` match a previously routed prompt stick to that
    replica while it has a free slot, so a shared system prompt keeps
    hitting the replica whose prefix cache already holds it.

    When a :class:`~tpu_engine.prefix_plane.PrefixPlane` is attached, the
    plane's radix index outranks the fixed-width pin: the route goes to
    the longest-prefix-HOLDING replica with a free slot (the plane knows
    which replicas actually retain the KV, the pin only remembers who was
    sent it last), and the pin re-anchors to the plane's pick. Every
    cache-steered pick — plane or pin — still pays its smooth-WRR weight
    share, so cache-heavy traffic cannot skew the fair rotation of the
    remaining (cold) traffic.
    """

    def __init__(self, affinity_tokens: int = 32, affinity_max: int = 512,
                 prefix_plane: Any = None):
        self.affinity_tokens = int(affinity_tokens)
        self.affinity_max = int(affinity_max)
        self.prefix_plane = prefix_plane
        self._weights: dict[str, float] = {}
        self._current: dict[str, float] = {}
        self._free: dict[str, int] = {}
        self._affinity: "collections.OrderedDict[tuple, str]" = (
            collections.OrderedDict()
        )
        self.affinity_hits = 0
        self.plane_hits = 0
        self.routed_total = 0

    def update(self, replica_stats: dict[str, dict[str, Any]]) -> None:
        """Refresh weights from live engine stats: ``{replica_id:
        {"tokens_per_sec", "free_slots", "slots"}}``. Replicas absent from
        the snapshot (preempted / torn down) are forgotten."""
        alive = set(replica_stats)
        died = [rid for rid in self._weights if rid not in alive]
        for rid in died:
            self._weights.pop(rid, None)
            self._current.pop(rid, None)
            self._free.pop(rid, None)
        for rid, st in replica_stats.items():
            slots = max(int(st.get("slots", 1)), 1)
            free = max(int(st.get("free_slots", 0)), 0)
            tps = max(float(st.get("tokens_per_sec", 0.0)), 0.0)
            self._weights[rid] = (0.05 + tps) * (0.05 + free / slots)
            self._current.setdefault(rid, 0.0)
            self._free[rid] = free
        # Affinity entries only go stale when a replica actually dies, so
        # the table scan is gated on that — steady-state update() cost is
        # O(live replicas), independent of affinity table size.
        if died:
            dead = set(died)
            for key in [
                k for k, rid in self._affinity.items() if rid in dead
            ]:
                self._affinity.pop(key, None)
            if self.prefix_plane is not None:
                for rid in died:
                    self.prefix_plane.drop_replica(rid)

    def _charge(self, pick: str) -> None:
        """Smooth-WRR accounting for one dispatch landing on ``pick``:
        everyone accrues their weight, the pick pays the total. Cache-
        steered picks (plane/affinity) run the SAME ledger as fair
        rotation — skipping it would permanently skew later WRR picks
        toward whichever replicas the cache never favors."""
        total = sum(self._weights.values())
        for rid, w in self._weights.items():
            self._current[rid] = self._current.get(rid, 0.0) + w
        self._current[pick] -= total

    def _pin(self, key: Optional[tuple], pick: str,
             overwrite: bool = True) -> None:
        if key is None:
            return
        if not overwrite:
            cur = self._affinity.get(key)
            # A live pin survives a busy fall-through: the pinned replica
            # still HOLDS the prefix KV — re-pinning to this dispatch's
            # pick would scatter one prefix across the fleet, one replica
            # per momentary slot-full blip. Only a dead/unknown target
            # releases the pin.
            if cur is not None and cur in self._weights:
                self._affinity.move_to_end(key)
                return
        self._affinity[key] = pick
        self._affinity.move_to_end(key)
        while len(self._affinity) > self.affinity_max:
            self._affinity.popitem(last=False)

    def route(self, prompt: Any = None) -> Optional[str]:
        """Pick a replica id for this prompt; None when the fleet has no
        routable replica (caller queues fleet-side)."""
        if not self._weights:
            return None
        self.routed_total += 1
        key = None
        if prompt is not None and self.affinity_tokens > 0:
            key = tuple(prompt[: self.affinity_tokens])
            # Fleet prefix plane first: the radix index knows who HOLDS
            # the longest prefix (affinity only remembers who was sent it).
            if self.prefix_plane is not None:
                rid, matched = self.prefix_plane.route_hint(
                    list(prompt), self._free
                )
                if rid is not None and matched > 0 and \
                        self._free.get(rid, 0) > 0:
                    self.plane_hits += 1
                    self._charge(rid)
                    self._free[rid] -= 1
                    self._pin(key, rid)
                    return rid
            rid = self._affinity.get(key)
            if rid is not None and self._free.get(rid, 0) > 0:
                self._affinity.move_to_end(key)
                self.affinity_hits += 1
                # Affinity picks pay their weight share too — the hit path
                # skipping the ledger skewed subsequent WRR picks toward
                # the unpinned replicas under affinity-heavy traffic.
                self._charge(rid)
                self._free[rid] -= 1
                return rid
        # Smooth WRR: current += weight; pick the max; charge it the total.
        for rid, w in self._weights.items():
            self._current[rid] = self._current.get(rid, 0.0) + w
        pick = max(self._current, key=lambda r: self._current[r])
        self._current[pick] -= sum(self._weights.values())
        self._free[pick] = max(self._free.get(pick, 0) - 1, 0)
        # Busy fall-through must NOT overwrite a live pin (satellite of the
        # prefix plane: the pinned replica still holds the KV).
        self._pin(key, pick, overwrite=False)
        return pick

    def stats(self) -> dict[str, Any]:
        out = {
            "weights": {r: round(w, 4) for r, w in self._weights.items()},
            "affinity_entries": len(self._affinity),
            "affinity_hits": self.affinity_hits,
            "routed_total": self.routed_total,
            "plane_hits": self.plane_hits,
        }
        if self.prefix_plane is not None:
            out["prefix_plane"] = self.prefix_plane.stats()
        return out


class AutoscalerConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    min_replicas: int = Field(default=1, ge=0)
    max_replicas: int = Field(default=4, ge=1)
    # Scale up when the windowed mean queue depth per replica crosses this
    # (or p99 breaches the SLO); scale down when it falls below the low
    #-water mark AND p99 has headroom.
    target_queue_per_replica: float = Field(default=4.0, gt=0)
    low_water_queue_per_replica: float = Field(default=0.5, ge=0)
    p99_slo_ms: float = Field(default=2000.0, gt=0)
    # Optional TTFT SLO: breaching it scales up even while end-to-end p99
    # is healthy (long-prefill bursts hurt time-to-first-token long before
    # they hurt completion latency — the disaggregated prefill pool scales
    # on this signal).
    ttft_slo_ms: Optional[float] = Field(default=None, gt=0)
    window_s: float = Field(default=30.0, gt=0)
    scale_up_cooldown_s: float = Field(default=5.0, ge=0)
    # Hysteresis: scaling down waits this long after ANY scale event, so a
    # dip between bursts does not shed the replicas the next burst needs
    # (and a flapping signal cannot thrash submit/cancel cycles through
    # the scheduler).
    scale_down_cooldown_s: float = Field(default=60.0, ge=0)


class ReplicaAutoscaler:
    """Queue-depth + p99-SLO autoscaler, one step per ``observe`` call.

    Deliberately clockless: every decision is a function of the ``now``
    the caller passes, so the virtual-clock benchmark drives the SAME
    object the live fleet ticks."""

    def __init__(self, cfg: Optional[AutoscalerConfig] = None):
        self.cfg = cfg or AutoscalerConfig()
        self._samples: collections.deque[tuple[float, float]] = collections.deque()
        self._last_up: Optional[float] = None
        self._last_down: Optional[float] = None
        self.scale_ups = 0
        self.scale_downs = 0
        self.last_reason = "init"

    def observe(
        self,
        now: float,
        queue_depth: float,
        p99_ms: Optional[float],
        n_replicas: int,
        ttft_p99_ms: Optional[float] = None,
    ) -> int:
        """Record one observation, return the desired replica count.
        ``ttft_p99_ms`` only matters when the config sets ``ttft_slo_ms``
        (the disaggregated prefill pool's scale signal)."""
        c = self.cfg
        self._samples.append((now, float(queue_depth)))
        while self._samples and now - self._samples[0][0] > c.window_s:
            self._samples.popleft()
        mean_q = sum(q for _, q in self._samples) / len(self._samples)
        per_rep = mean_q / max(n_replicas, 1)

        if n_replicas < c.min_replicas:
            self.last_reason = f"below min_replicas ({c.min_replicas})"
            return c.min_replicas

        last_event = max(
            (t for t in (self._last_up, self._last_down) if t is not None),
            default=None,
        )
        slo_breach = p99_ms is not None and p99_ms > c.p99_slo_ms
        ttft_breach = (
            c.ttft_slo_ms is not None
            and ttft_p99_ms is not None
            and ttft_p99_ms > c.ttft_slo_ms
        )
        if (
            (per_rep > c.target_queue_per_replica or slo_breach or ttft_breach)
            and n_replicas < c.max_replicas
            and (self._last_up is None or now - self._last_up >= c.scale_up_cooldown_s)
        ):
            self._last_up = now
            self.scale_ups += 1
            if slo_breach:
                self.last_reason = (
                    f"scale up: p99 {p99_ms:.0f}ms > SLO {c.p99_slo_ms:.0f}ms"
                )
            elif ttft_breach:
                self.last_reason = (
                    f"scale up: ttft p99 {ttft_p99_ms:.0f}ms > TTFT SLO "
                    f"{c.ttft_slo_ms:.0f}ms"
                )
            else:
                self.last_reason = (
                    f"scale up: queue/replica {per_rep:.2f} > "
                    f"{c.target_queue_per_replica}"
                )
            return n_replicas + 1

        window_full = (
            self._samples and now - self._samples[0][0] >= 0.8 * c.window_s
        )
        if (
            n_replicas > c.min_replicas
            and window_full
            and per_rep < c.low_water_queue_per_replica
            and not slo_breach
            and not ttft_breach
            and (last_event is None or now - last_event >= c.scale_down_cooldown_s)
        ):
            self._last_down = now
            self.scale_downs += 1
            self.last_reason = (
                f"scale down: queue/replica {per_rep:.2f} < "
                f"{c.low_water_queue_per_replica} for the window"
            )
            return n_replicas - 1

        self.last_reason = "hold"
        return n_replicas

    def stats(self) -> dict[str, Any]:
        return {
            "scale_ups": self.scale_ups,
            "scale_downs": self.scale_downs,
            "last_reason": self.last_reason,
            "window_samples": len(self._samples),
        }


class ServingFleet:
    """N decode replicas as first-class scheduler submissions.

    Each replica is one ``workload="serving"`` submission through the
    SHARED :class:`FleetScheduler` — same priority queue, quota, drain/
    cancel, per-device HBM ledger (via the spec's KV-pool estimator) and
    preempt machinery as every training job. The fleet object routes
    requests across whatever subset is currently RUNNING, so a replica
    preempted by a CRITICAL training job just drops out of rotation until
    the scheduler re-admits it.
    """

    def __init__(
        self,
        scheduler: FleetScheduler,
        spec: ServingReplicaSpec,
        autoscaler: Optional[ReplicaAutoscaler] = None,
        router: Optional[FleetRouter] = None,
        priority: JobPriority = JobPriority.NORMAL,
        submitter: str = "serving-fleet",
        engine_factory: Callable[[ServingReplicaSpec], Any] = build_replica_engine,
        latency_window: int = 512,
        fault_injector: Optional[Any] = None,
        prefix_plane: Optional[Any] = None,
        journal: Optional[journal_mod.ControlPlaneJournal] = None,
        replica_job_factory: Optional[
            Callable[[Submission, ServingReplicaSpec], Any]
        ] = None,
    ):
        self.scheduler = scheduler
        self.spec = spec
        self.autoscaler = autoscaler or ReplicaAutoscaler()
        self.router = router or FleetRouter(prefix_plane=prefix_plane)
        self.priority = priority
        self.submitter = submitter
        self.engine_factory = engine_factory
        self.fault_injector = fault_injector
        # Durable control plane: replica roster, desired count and held
        # requests are written ahead to the journal; re_adopt() rebuilds a
        # crashed fleet object around the replicas that kept serving.
        self._journal = journal
        # Replica job construction seam (the ctl_crash lane swaps in a
        # thread-free virtual-clock job); default is the real thread-backed
        # ServingReplicaJob.
        self.replica_job_factory = replica_job_factory
        # Fleet prefix plane (tpu_engine/prefix_plane.py): the router takes
        # hints from it; dispatch below reports admissions back and spills
        # replica-cache overflow to its host tier via export_prefix.
        self.prefix_plane = prefix_plane
        if prefix_plane is not None:
            if self.router.prefix_plane is None:
                self.router.prefix_plane = prefix_plane
            if prefix_plane.spill is None:
                prefix_plane.spill = self._spill_prefix

        self._lock = threading.RLock()
        self._replicas: dict[str, Submission] = {}  # submission_id → sub
        self.desired_replicas = 0
        self._pending: collections.deque[tuple[str, dict[str, Any]]] = (
            collections.deque()
        )
        self._requests: dict[str, dict[str, Any]] = {}
        self._req_seq = 0
        self._latencies = _PercentileWindow(window=latency_window)
        # Fleet-level TTFT: first_token_at (engine stamp) minus FLEET
        # submission time — includes fleet queueing and routing, which the
        # engine's own ttft_ms cannot see.
        self._ttfts = _PercentileWindow(window=latency_window)
        self.requests_total = 0
        self.completed_total = 0
        self.tokens_total = 0
        self.scale_ups_total = 0
        self.scale_downs_total = 0

        # Fleet-level flight-recorder lane: replica submissions and
        # autoscaler decisions annotate this trace; each request gets its
        # own trace (enqueue → route → completion) linked back to it.
        rec = tracing.get_recorder()
        self.trace_id = rec.new_trace_id()
        self._fleet_span = rec.start_span(
            f"serving_fleet:{spec.model_name}",
            kind="serving_fleet",
            trace_id=self.trace_id,
            attrs={"model": spec.model_name, "submitter": submitter},
        )

    # -- replica lifecycle ---------------------------------------------------

    def start(self) -> None:
        self.scale_to(max(self.autoscaler.cfg.min_replicas, 1))

    def stop(self) -> None:
        with self._lock:
            for sid in list(self._replicas):
                self.scheduler.cancel(sid)
            self.desired_replicas = 0
        if self._fleet_span.t1 is None:
            self._fleet_span.end(stopped=True)

    def _journal_event(self, kind: str, payload: dict[str, Any]) -> None:
        j = self._journal
        if j is not None:
            j.append(kind, payload)

    def _make_replica_job(self, s: Submission) -> Any:
        if self.replica_job_factory is not None:
            return self.replica_job_factory(s, self.spec)
        return ServingReplicaJob(
            s, self.spec, engine_factory=self.engine_factory,
            fault_injector=self.fault_injector,
        )

    def _submit_replica(self) -> Submission:
        spec = self.spec
        sub = self.scheduler.submit(
            spec.placement_config(),
            priority=self.priority,
            submitter=self.submitter,
            workload="serving",
            estimate_fn=spec.estimate,
            job_factory=self._make_replica_job,
        )
        self._replicas[sub.submission_id] = sub
        self._journal_event("fleet.replica", {"sid": sub.submission_id})
        tracing.get_recorder().event(
            "replica_submit",
            kind="serving",
            trace_id=self.trace_id,
            parent=self._fleet_span,
            attrs={
                "submission_id": sub.submission_id,
                "replica_trace_id": sub.trace_id,
            },
        )
        return sub

    def scale_to(self, n: int) -> int:
        """Submit or cancel replicas toward ``n`` (clamped to the
        autoscaler's [min, max]); returns the resulting desired count."""
        c = self.autoscaler.cfg
        n = max(min(int(n), c.max_replicas), c.min_replicas)
        with self._lock:
            live = [
                s for s in self._replicas.values() if s.state not in TERMINAL_STATES
            ]
            while len(live) < n:
                live.append(self._submit_replica())
            if len(live) > n:
                # Shed queued replicas first (they serve nobody), then the
                # emptiest running engines — never a busy one over an idle
                # one.
                def load(s: Submission) -> tuple[int, int]:
                    job = s.job
                    eng = getattr(job, "engine", None) if job is not None else None
                    if s.state == SubmissionState.QUEUED or eng is None:
                        return (0, 0)
                    st = eng.stats()
                    return (1, int(st.get("active_slots", 0)) + int(st.get("queued", 0)))

                for victim in sorted(live, key=load)[: len(live) - n]:
                    self.scheduler.cancel(victim.submission_id)
            if n != self.desired_replicas:
                self._journal_event("fleet.desired", {"n": n})
            self.desired_replicas = n
        return n

    def running_replicas(self) -> dict[str, Any]:
        """Submission id → live engine, for every replica that is admitted
        AND has finished building its engine."""
        out = {}
        with self._lock:
            for sid, sub in self._replicas.items():
                job = sub.job
                if (
                    sub.state == SubmissionState.RUNNING
                    and job is not None
                    and getattr(job, "engine_ready", None) is not None
                    and job.engine_ready.is_set()
                    and job.engine is not None
                ):
                    out[sid] = job.engine
        return out

    # -- durability: journal snapshot + crash recovery -----------------------

    def snapshot_state(self) -> dict[str, Any]:
        """Serialized fleet state — the ``serving`` section of a journal
        snapshot. Deterministically ordered so the digest is comparable
        across double recoveries."""
        with self._lock:
            return {
                "desired_replicas": self.desired_replicas,
                "req_seq": self._req_seq,
                "replicas": sorted(self._replicas),
                "requests": {
                    fid: {
                        "submitted_at": r["submitted_at"],
                        "prompt": list(r["prompt"]),
                        "max_new_tokens": r["max_new_tokens"],
                        "temperature": r["temperature"],
                        "done": bool(r["done"]),
                    }
                    for fid, r in sorted(self._requests.items())
                },
                "counters": {
                    "requests_total": self.requests_total,
                    "completed_total": self.completed_total,
                    "tokens_total": self.tokens_total,
                },
            }

    def re_adopt(
        self, journal: journal_mod.ControlPlaneJournal, redispatch: bool = True
    ) -> dict[str, Any]:
        """Rebuild a crashed fleet object from its journal. Call on a
        freshly constructed fleet whose scheduler already ran
        ``restore(journal, ...)``.

        Journaled replicas whose submissions survived in the restored
        scheduler (re-adopted live jobs, or still queued) are taken back
        into the roster; vanished ones (marked ``vanished_at_recovery``
        by the scheduler) are replaced by re-dispatching fresh replicas
        up to the journaled desired count (``redispatch=False`` skips
        that — used when comparing double-recovery digests, since fresh
        submissions mint fresh ids). Every held (journaled, not done)
        request is re-created and re-queued for dispatch — no request
        accepted before the crash is lost. ``tokens_total`` restores from
        the snapshot only (per-token progress is not journaled)."""
        doc = journal.read()
        snap = doc.get("snapshot") or {}
        base = (snap.get("sections") or {}).get("serving") or {}
        desired = int(base.get("desired_replicas", 0))
        req_seq = int(base.get("req_seq", 0))
        roster = set(base.get("replicas", []))
        requests: dict[str, dict] = {
            fid: dict(r)
            for fid, r in (base.get("requests") or {}).items()
            if isinstance(r, dict)
        }
        counters = {
            "requests_total": 0, "completed_total": 0, "tokens_total": 0,
        }
        counters.update({
            k: int(v) for k, v in (base.get("counters") or {}).items()
            if k in counters
        })
        for ev in doc.get("events", []):
            kind = ev.get("kind") or ""
            p = ev.get("payload")
            if not kind.startswith("fleet.") or not isinstance(p, dict):
                continue
            if kind == "fleet.desired":
                desired = int(p.get("n", desired))
            elif kind == "fleet.replica" and p.get("sid"):
                roster.add(p["sid"])
            elif kind == "fleet.request" and p.get("fid"):
                requests[p["fid"]] = {
                    "submitted_at": p.get("submitted_at"),
                    "prompt": list(p.get("prompt") or []),
                    "max_new_tokens": int(p.get("max_new_tokens", 64)),
                    "temperature": float(p.get("temperature", 0.0)),
                    "done": False,
                }
                counters["requests_total"] += 1
                try:
                    req_seq = max(req_seq, int(p["fid"].rsplit("_", 1)[-1]))
                except (ValueError, IndexError):
                    pass
            elif kind == "fleet.request_done" and p.get("fid") in requests:
                requests[p["fid"]]["done"] = True
                counters["completed_total"] += 1

        readopted = 0
        held: list[str] = []
        with self._lock:
            self._req_seq = max(self._req_seq, req_seq)
            self.requests_total = counters["requests_total"]
            self.completed_total = counters["completed_total"]
            self.tokens_total = counters["tokens_total"]
            for sid in sorted(roster):
                sub = self.scheduler.get(sid)
                if sub is None or sub.state in TERMINAL_STATES:
                    continue  # vanished — replaced by the re-dispatch below
                self._replicas[sid] = sub
                readopted += 1
            # Re-create every held request, oldest first (fid order), with
            # a fresh trace span — the original span died with the crash.
            rec = tracing.get_recorder()
            def _fid_key(fid: str) -> tuple:
                try:
                    return (0, int(fid.rsplit("_", 1)[-1]))
                except (ValueError, IndexError):
                    return (1, fid)
            for fid in sorted(requests, key=_fid_key):
                r = requests[fid]
                if r.get("done"):
                    continue
                span = rec.start_span(
                    f"request:{fid}",
                    kind="serving_request",
                    attrs={
                        "fleet_trace_id": self.trace_id,
                        "prompt_tokens": len(r["prompt"]),
                        "max_new_tokens": int(r["max_new_tokens"]),
                        "recovered": True,
                    },
                )
                req = {
                    "submitted_at": r.get("submitted_at") or time.time(),
                    "prompt": list(r["prompt"]),
                    "max_new_tokens": int(r["max_new_tokens"]),
                    "temperature": float(r.get("temperature", 0.0)),
                    "replica": None,
                    "engine_rid": None,
                    "done": False,
                    "trace_id": span.trace_id,
                    "_span": span,
                }
                self._requests[fid] = req
                self._pending.append((fid, req))
                held.append(fid)
            self.desired_replicas = 0
        # Attach before re-dispatching so the replacement replicas are
        # themselves written ahead — they must survive a second crash.
        self._journal = journal
        redispatched = 0
        if redispatch and desired > 0:
            before = len(self._replicas)
            self.scale_to(desired)
            redispatched = len(self._replicas) - before
        else:
            with self._lock:
                self.desired_replicas = desired
        journal_mod.note_recovery(
            replicas_readopted_total=readopted,
            replicas_redispatched_total=redispatched,
            requests_recovered_total=len(held),
        )
        summary = {
            "desired_replicas": desired,
            "replicas_readopted": readopted,
            "replicas_redispatched": redispatched,
            "requests_recovered": len(held),
            "held_fids": held,
            "ingest": doc.get("stats", {}),
        }
        log.info("serving fleet: re-adopted from journal — %s", summary)
        return summary

    # -- request plane -------------------------------------------------------

    def submit_request(
        self,
        prompt: list[int],
        max_new_tokens: int = 64,
        temperature: float = 0.0,
    ) -> str:
        """Route a request to a replica (or hold it fleet-side until one is
        admitted). Returns a fleet-scoped request id."""
        with self._lock:
            self._req_seq += 1
            fid = f"req_{self._req_seq}"
            self.requests_total += 1
            rec = tracing.get_recorder()
            span = rec.start_span(
                f"request:{fid}",
                kind="serving_request",
                attrs={
                    "fleet_trace_id": self.trace_id,
                    "prompt_tokens": len(prompt),
                    "max_new_tokens": int(max_new_tokens),
                },
            )
            self._requests[fid] = {
                "submitted_at": time.time(),
                "prompt": list(prompt),
                "max_new_tokens": int(max_new_tokens),
                "temperature": float(temperature),
                "replica": None,
                "engine_rid": None,
                "done": False,
                "trace_id": span.trace_id,
                "_span": span,
            }
            rec.event(
                "enqueue", kind="serving", trace_id=span.trace_id, parent=span,
                attrs={"fid": fid},
            )
            self._journal_event("fleet.request", {
                "fid": fid,
                "prompt": list(prompt),
                "max_new_tokens": int(max_new_tokens),
                "temperature": float(temperature),
                "submitted_at": self._requests[fid]["submitted_at"],
            })
            self._pending.append((fid, self._requests[fid]))
            self._flush_pending()
            return fid

    def _flush_pending(self) -> None:
        engines = self.running_replicas()
        if not engines:
            return
        self.router.update({
            sid: self._engine_router_stats(e) for sid, e in engines.items()
        })
        still: collections.deque = collections.deque()
        while self._pending:
            fid, req = self._pending.popleft()
            sid = self.router.route(req["prompt"])
            if sid is None or sid not in engines:
                still.append((fid, req))
                continue
            try:
                rid = engines[sid].submit(
                    req["prompt"],
                    max_new_tokens=req["max_new_tokens"],
                    temperature=req["temperature"],
                )
            except Exception:  # engine died under us — requeue fleet-side
                still.append((fid, req))
                continue
            req["replica"], req["engine_rid"] = sid, rid
            if self.prefix_plane is not None:
                self._observe_plane(req["prompt"], sid, engines.get(sid))
            tracing.get_recorder().event(
                "route",
                kind="serving",
                trace_id=req.get("trace_id"),
                parent=req.get("_span"),
                attrs={"fid": fid, "replica": sid, "engine_rid": rid},
            )
        self._pending.extend(still)

    def _observe_plane(self, prompt: list[int], sid: str, engine: Any) -> None:
        """Report one admission to the prefix plane; a host-tier hit
        rehydrates the payload into the replica's prefix cache. Plane
        bookkeeping is an optimization — it must never fail a dispatch."""
        try:
            obs = self.prefix_plane.observe_admit(prompt, sid)
            if (
                obs["kind"] == "host"
                and obs["payload"] is not None
                and engine is not None
                and hasattr(engine, "install_prefix")
            ):
                engine.install_prefix(list(obs["prefix"]), obs["payload"])
        except Exception:  # noqa: BLE001
            pass

    def _spill_prefix(self, prefix: tuple, rid: str) -> Optional[Any]:
        """Default plane spill: export the evicted prefix's KV off the
        replica that held it (None when the replica or its entry is gone —
        the host tier then simply misses)."""
        eng = self.running_replicas().get(rid)
        if eng is None or not hasattr(eng, "export_prefix"):
            return None
        try:
            return eng.export_prefix(list(prefix))
        except Exception:  # noqa: BLE001
            return None

    @staticmethod
    def _engine_router_stats(engine: Any) -> dict[str, Any]:
        # Busy accounting is pool-aware: active_slots already counts held
        # (finished-but-pinned) prefill slots, and queued_handoffs are wire
        # payloads that will claim a slot before any new route lands.
        st = engine.stats()
        slots = int(st.get("slots", 1))
        busy = (
            int(st.get("active_slots", 0))
            + int(st.get("prefilling", 0))
            + int(st.get("queued_handoffs", 0))
        )
        return {
            "tokens_per_sec": float(st.get("tokens_per_sec_recent", 0.0)),
            "free_slots": max(slots - busy, 0),
            "slots": slots,
        }

    def result(self, fid: str) -> dict[str, Any]:
        """Fleet-side view of one request; re-dispatches it when its
        replica was preempted mid-flight (stateless replicas make retry the
        correct recovery)."""
        with self._lock:
            req = self._requests.get(fid)
            if req is None:
                raise KeyError(fid)
            if req["replica"] is None:
                self._flush_pending()
                if req["replica"] is None:
                    return {"id": fid, "status": "pending", "replica": None}
            engines = self.running_replicas()
            eng = engines.get(req["replica"])
            if eng is None:
                # Replica torn down (preempt/cancel) before completion:
                # requeue the request for the next flush.
                if not req["done"]:
                    req["replica"] = req["engine_rid"] = None
                    self._pending.append((fid, req))
                    tracing.get_recorder().event(
                        "redispatch",
                        kind="serving",
                        trace_id=req.get("trace_id"),
                        parent=req.get("_span"),
                        attrs={"fid": fid, "reason": "replica lost"},
                    )
                    return {"id": fid, "status": "pending", "replica": None}
                return {"id": fid, "status": "done", "replica": req["replica"]}
            try:
                out = eng.result(req["engine_rid"])
            except KeyError:
                req["replica"] = req["engine_rid"] = None
                self._pending.append((fid, req))
                tracing.get_recorder().event(
                    "redispatch",
                    kind="serving",
                    trace_id=req.get("trace_id"),
                    parent=req.get("_span"),
                    attrs={"fid": fid, "reason": "engine forgot request"},
                )
                return {"id": fid, "status": "pending", "replica": None}
            out = dict(out)
            out["id"] = fid
            out["replica"] = req["replica"]
            if out.get("status") in ("done", "failed") and not req["done"]:
                req["done"] = True
                self.completed_total += 1
                self._journal_event("fleet.request_done", {"fid": fid})
                n_new = len(out.get("tokens", []) or [])
                self.tokens_total += n_new
                latency_ms = (time.time() - req["submitted_at"]) * 1000.0
                self._latencies.add(latency_ms)
                first_at = out.get("first_token_at")
                if first_at is not None:
                    ttft = (float(first_at) - req["submitted_at"]) * 1000.0
                    if ttft >= 0:
                        self._ttfts.add(ttft)
                        out["fleet_ttft_ms"] = round(ttft, 2)
                span = req.get("_span")
                if span is not None and span.t1 is None:
                    span.end(
                        status=out.get("status"),
                        tokens=n_new,
                        replica=req["replica"],
                        latency_ms=round(latency_ms, 3),
                    )
            out["trace_id"] = req.get("trace_id")
            return out

    # -- control loop --------------------------------------------------------

    def p99_latency_ms(self) -> Optional[float]:
        with self._lock:
            (p99,) = self._latencies.percentiles((0.99,))
            return p99

    def ttft_percentiles(self) -> dict[str, Optional[float]]:
        """p50/p99 of fleet-level TTFT (fleet submit → engine first token)
        over the latency window; None until a completion reports one.
        Reads walk the bounded histogram (within 1% of the exact window
        percentile) instead of sorting the window per call."""
        with self._lock:
            p50, p99 = self._ttfts.percentiles((0.50, 0.99))
            if p50 is None:
                return {"p50": None, "p99": None}
            return {"p50": round(p50, 2), "p99": round(p99, 2)}

    def queue_depth(self) -> int:
        engines = self.running_replicas()
        with self._lock:
            depth = len(self._pending)
        for eng in engines.values():
            try:
                depth += int(eng.stats().get("queued", 0))
            except Exception:  # noqa: BLE001 — engine mid-teardown
                continue
        return depth

    def tick(self, now: Optional[float] = None) -> dict[str, Any]:
        """One control-loop pass: flush held requests, refresh router
        weights, drive the autoscaler. The HTTP plane calls this on status
        reads; a live deployment would pin it to a timer."""
        now = time.time() if now is None else now
        with self._lock:
            self._flush_pending()
            engines = self.running_replicas()
            self.router.update({
                sid: self._engine_router_stats(e) for sid, e in engines.items()
            })
            n_running = len(engines)
            p99 = self.p99_latency_ms()
            ttfts = self.ttft_percentiles()
            desired = self.autoscaler.observe(
                now, self.queue_depth(), p99, n_running,
                ttft_p99_ms=ttfts["p99"],
            )
            # Feed the fleet SLO alerter's serving-p99 window (burn-rate
            # evaluation happens on the read path, not here).
            if p99 is not None:
                try:
                    from tpu_engine import goodput as goodput_mod

                    goodput_mod.get_alerter().observe_p99(p99, ts=now)
                except Exception:  # alerting must never break serving
                    pass
            # Only act on autoscaler output once the fleet has converged to
            # the previous desired count — scheduler admission latency must
            # not read as "need another replica".
            if desired > self.desired_replicas:
                self.scale_ups_total += 1
                tracing.get_recorder().event(
                    "scale_up",
                    kind="autoscaler",
                    trace_id=self.trace_id,
                    parent=self._fleet_span,
                    attrs={"desired": desired, "running": n_running},
                )
                self.scale_to(desired)
            elif desired < self.desired_replicas and n_running >= self.desired_replicas:
                self.scale_downs_total += 1
                tracing.get_recorder().event(
                    "scale_down",
                    kind="autoscaler",
                    trace_id=self.trace_id,
                    parent=self._fleet_span,
                    attrs={"desired": desired, "running": n_running},
                )
                self.scale_to(desired)
        return self.status()

    def status(self) -> dict[str, Any]:
        with self._lock:
            # Refresh router weights so a status/metrics read reports the
            # dispatch plane as it would route NOW (no autoscaler side
            # effects — only tick() scales).
            self.router.update({
                sid: self._engine_router_stats(e)
                for sid, e in self.running_replicas().items()
            })
            ttfts = self.ttft_percentiles()  # one histogram walk per status
            replicas = {}
            for sid, sub in self._replicas.items():
                job = sub.job
                entry = {
                    "state": sub.state.value,
                    "job_id": sub.job_id,
                    "attempts": sub.attempts,
                    "preemptions": sub.preemptions,
                    "engine_ready": bool(
                        job is not None
                        and getattr(job, "engine_ready", None) is not None
                        and job.engine_ready.is_set()
                    ),
                }
                if entry["engine_ready"]:
                    try:
                        entry["engine"] = job.engine.stats()
                    except Exception:  # noqa: BLE001 — engine mid-teardown
                        entry["engine_ready"] = False
                replicas[sid] = entry
            return {
                "model": self.spec.model_name,
                "desired_replicas": self.desired_replicas,
                "running_replicas": sum(
                    1 for r in replicas.values() if r["engine_ready"]
                ),
                "replicas": replicas,
                "pending_requests": len(self._pending),
                "requests_total": self.requests_total,
                "completed_total": self.completed_total,
                "tokens_total": self.tokens_total,
                "p99_latency_ms": self.p99_latency_ms(),
                "ttft_p50_ms": ttfts["p50"],
                "ttft_p99_ms": ttfts["p99"],
                "scale_ups_total": self.scale_ups_total,
                "scale_downs_total": self.scale_downs_total,
                "router": self.router.stats(),
                "autoscaler": self.autoscaler.stats(),
                "prefix_plane": (
                    None if self.prefix_plane is None
                    else self.prefix_plane.stats()
                ),
            }
