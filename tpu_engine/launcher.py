"""TPU training launcher: config → sharding plan → supervised in-process job.

Capability parity with the reference's ``DeepSpeedLauncher``
(``ai_engine/deepspeed_launcher.py:103-407``), inverted for TPU (SURVEY.md §7
design stance): instead of generating a ZeRO JSON file and shelling out to the
``deepspeed`` CLI (``write_config`` :242, ``build_launch_command`` :258,
``Popen`` :354), the launcher *owns* the training engine — it resolves the
config into a concrete sharding plan, builds the pjit train program, and runs
it as a supervised thread with real status tracking (vs the reference's
fire-and-forget pid capture at ``:362``).

- ``generate_plan``  ≈ ``generate_config`` (:114-240): the inspectable,
  serialisable description of what will run (mesh, shardings, optimizer,
  precision, offload, checkpointing, effective batch);
- ``launch``         ≈ ``launch`` (:302-367): ``dry_run`` short-circuits
  after plan generation (parity with ``:349-351``; the API layer defaults
  ``dry_run=True`` exactly like reference ``backend/routers/training.py:44``);
- ``presets``        ≈ ``presets`` (:369-407).
"""

from __future__ import annotations

import threading
import uuid
from datetime import datetime, timezone
from typing import Any, Callable, Optional

import jax
from pydantic import BaseModel, Field

from tpu_engine import comm, faults, quant_train
from tpu_engine import scheduler as scheduler_mod
from tpu_engine.hbm_estimate import gang_size
from tpu_engine.mesh_runtime import MESH_AXES
from tpu_engine.parallel import pipeline_zb
from tpu_engine.scheduler import FleetScheduler, JobPriority, QuotaExceeded
from tpu_engine.models import transformer as tfm
from tpu_engine.sharding import (
    ShardingStage,
    TPUTrainConfig,
    grad_pspecs,
    logical_to_mesh_axes,
    opt_state_pspecs,
    param_pspecs,
    presets as config_presets,
    resolve_pipeline_schedule,
)
from tpu_engine.supervisor import JobStatus, TrainingJob
from tpu_engine.tpu_manager import TPUManager


class LaunchResult(BaseModel):
    """Mirrors reference ``LaunchResult`` (``deepspeed_launcher.py:90-100``),
    plus the two-phase fields: a launch that cannot be admitted right now is
    ``status="queued"`` with its queue position — not a refusal."""

    job_id: str
    status: str  # "dry_run" | "launched" | "queued" | "failed"
    model_name: str
    effective_batch_size: int
    num_devices: int
    plan: dict[str, Any] = Field(default_factory=dict)
    error: Optional[str] = None
    submission_id: Optional[str] = None
    queue_position: Optional[int] = None


class TPULauncher:
    """In-process launch + job registry (replaces subprocess orchestration).

    Admission is owned by the :class:`~tpu_engine.scheduler.FleetScheduler`
    (one admission authority): ``launch`` is a thin wrapper over ``submit``
    with ``priority=normal``."""

    def __init__(
        self,
        max_concurrent_jobs: int = 1,
        scheduler: Optional[FleetScheduler] = None,
    ):
        """``max_concurrent_jobs``: running-job cap for this process's
        devices (default 1 — concurrent sharded train loops would fight
        for the same HBM and silently thrash; raise it deliberately for
        tiny-model multi-tenancy). Enforced by the scheduler."""
        self._jobs: dict[str, TrainingJob] = {}
        self._lock = threading.Lock()
        # Default to a live fleet view: without one, admission is
        # capacity-only and the elastic shrink path can never engage — a
        # self-healed job would be re-admitted onto the same bad chip.
        self.scheduler = scheduler or FleetScheduler(
            max_concurrent_jobs=max_concurrent_jobs,
            job_factory=self._make_job,
            fleet_fn=TPUManager().get_fleet_status,
        )
        if scheduler is not None:
            self.scheduler.job_factory = self._make_job

    @property
    def max_concurrent_jobs(self) -> int:
        return self.scheduler.max_concurrent_jobs

    @max_concurrent_jobs.setter
    def max_concurrent_jobs(self, n: int) -> None:
        self.scheduler.max_concurrent_jobs = n

    def _make_job(self, sub: "scheduler_mod.Submission") -> TrainingJob:
        """Scheduler job factory: construct the attempt AND register it, so
        the existing registry views (get_job/list_jobs/stop_job) keep
        working; a requeued attempt reuses its job_id — newest wins."""
        job = scheduler_mod._default_job_factory(sub)
        with self._lock:
            self._jobs[job.job_id] = job
        return job

    # -- plan generation (generate_config parity) ----------------------------

    def generate_plan(self, config: TPUTrainConfig) -> dict[str, Any]:
        """Resolve a config into the concrete execution plan.

        The TPU analogue of the generated ZeRO JSON
        (``deepspeed_launcher.py:124-240``): instead of bucket sizes and
        offload dicts consumed by an external engine, the plan states the
        mesh shape, per-tensor-class PartitionSpecs for params/grads/optimizer
        state, optimizer+schedule, precision, remat, and checkpoint policy.
        """
        model_cfg = tfm.MODEL_CONFIGS.get(config.model_name)
        n_avail = jax.device_count()
        try:
            mesh_shape = dict(zip(MESH_AXES, config.mesh.resolved_shape(n_avail)))
            mesh_note = f"resolved on {n_avail} visible device(s)"
        except ValueError:
            mesh_shape = config.mesh.model_dump()
            mesh_note = (
                f"requested shape (does not fit the {n_avail} visible device(s); "
                "valid on the target slice)"
            )

        stage = config.sharding_stage
        # Representative logical tensors → the sharding each stage gives them.
        rep = {
            "attention_qkv [embed, heads]": ("embed", "heads"),
            "mlp_in [embed, mlp]": ("embed", "mlp"),
            "embedding [vocab, embed]": ("vocab", "embed"),
            "norm_scale [embed]": ("embed",),
        }

        def spec_str(p) -> str:
            return str(tuple(p)) if len(tuple(p)) else "(replicated)"

        shardings = {
            name: {
                "params": spec_str(logical_to_mesh_axes(lg, shard_fsdp=stage >= 3)),
                "grads": spec_str(logical_to_mesh_axes(lg, shard_fsdp=stage >= 2)),
                "opt_state": spec_str(logical_to_mesh_axes(lg, shard_fsdp=stage >= 1)),
            }
            for name, lg in rep.items()
        }

        plan: dict[str, Any] = {
            "model": {
                "name": config.model_name,
                "known": model_cfg is not None,
                "param_count": tfm.param_count(model_cfg) if model_cfg else None,
                "seq_len": config.seq_len,
            },
            "mesh": {"shape": mesh_shape, "note": mesh_note, "axes_order_note":
                     "outer→inner = DCN-most→ICI-most: " + str(MESH_AXES)},
            "pipeline_schedule": {
                "configured": config.pipeline_schedule,
                "resolved": resolve_pipeline_schedule(config),
                # Analytic per-stage tick/busy-lane account for the
                # resolved schedule (None off the pipelined path).
                "tick_account": (
                    pipeline_zb.schedule_account(
                        resolve_pipeline_schedule(config),
                        config.mesh.pipe,
                        config.gradient_accumulation_steps,
                    )
                    if config.mesh.pipe > 1 else None
                ),
            },
            "sharding": {
                "stage": int(stage),
                "stage_name": ShardingStage(stage).name,
                "semantics": {
                    "params": "sharded over fsdp" if stage >= 3 else "replicated",
                    "gradients": "reduce-scattered to fsdp shards" if stage >= 2 else "all-reduced",
                    "optimizer_state": "sharded over fsdp" if stage >= 1 else "replicated",
                },
                "representative_tensors": shardings,
            },
            "batch": {
                "micro_batch_size": config.micro_batch_size,
                "gradient_accumulation_steps": config.gradient_accumulation_steps,
                "effective_batch_size": config.effective_batch_size,
            },
            "optimizer": {
                "name": config.optimizer,
                "learning_rate": config.learning_rate,
                "min_lr": config.min_lr,
                "schedule": f"warmup_{config.lr_schedule}",
                "warmup_steps": config.warmup_steps,
                "total_steps": config.total_steps,
                "weight_decay": config.weight_decay,
                "betas": [config.beta1, config.beta2],
                "grad_clip_norm": config.grad_clip_norm,
                "offload": config.optimizer_offload.value,
            },
            "precision": {
                "compute": config.precision.value,
                "master_params": config.param_dtype.value,
                "loss_scaling": "none (bf16 — not needed)",
            },
            # ZeRO++-style collective compression (tpu_engine/comm_compress.py):
            # which mechanisms are on and the analytic wire-volume factors.
            "comm_compression": comm.compression_plan(config),
            # AQT-style MXU int8 quantized training (tpu_engine/quant_train.py):
            # mode, targeted matmul groups, and the MFU accounting basis.
            "quant_training": quant_train.training_plan(config),
            "activation_checkpointing": {
                "enabled": config.activation_checkpointing,
                "policy": config.remat_policy,
            },
            "checkpoint": {
                "dir": config.checkpoint_dir,
                "interval_steps": config.checkpoint_interval_steps,
                "max_to_keep": config.max_checkpoints_to_keep,
                "stable_pointer": True,
                "rollback_on_divergence": True,
            },
            "elasticity": {
                "mode": "relaunch-at-new-mesh-shape + resume-from-checkpoint"
                if config.elastic_resume
                else "disabled",
                # Declared admissible device-count bounds: with min set, a
                # resume on a mismatched slice auto-selects the largest
                # admissible mesh (supervisor._elastic_config) instead of
                # failing; None = exact-fit only.
                "min_devices": config.elastic_min_devices,
                "max_devices": config.elastic_max_devices,
                # Effective-batch preservation across a resize (the
                # reference's min/max batch elasticity): accumulation is
                # rescaled to hold micro x accum x dp invariant; these
                # bounds gate admission of the achieved batch.
                "min_batch_size": config.elastic_min_batch_size,
                "max_batch_size": config.elastic_max_batch_size,
                "preserve_effective_batch": True,
                "note": "TPU slices are fixed-shape; live resize is not a TPU concept "
                "(reference elasticity block: deepspeed_launcher.py:226-238)",
            },
            # Self-healing recovery pipeline (tpu_engine/faults.py +
            # supervisor/scheduler seams): what happens when a mesh chip
            # goes unhealthy mid-training, and whether chaos injection is
            # currently armed in this process.
            "fault_tolerance": {
                "self_heal": bool(config.elastic_resume),
                "recovery_path": (
                    "detect unhealthy mesh chip -> synchronous emergency save "
                    "(bounded exponential-backoff retry; quarantine the step "
                    "on persistent I/O failure) -> requeue -> elastic-shrink "
                    "re-admission on the healthy remainder -> resume from the "
                    "emergency checkpoint (zero lost steps)"
                ),
                "elastic_shrink_on_admission": bool(
                    config.elastic_resume and config.elastic_min_devices is not None
                ),
                "grow_back_when_chips_recover": True,
                "fault_injection_armed": faults.get_active() is not None,
            },
            # Placement planner (tpu_engine/placement.py): the ranked
            # alternative-layout table for this job at the same gang —
            # what `mesh="auto"` would have picked, and how the submitted
            # layout compares. Advisory on the dry-run/plan surface;
            # binding only at auto admission.
            "placement": self._placement_section(config, n_avail),
        }
        return plan

    def _placement_section(
        self, config: TPUTrainConfig, n_avail: int
    ) -> dict[str, Any]:
        planner = self.scheduler.planner
        if config.model_name not in tfm.MODEL_CONFIGS:
            return {
                "available": False,
                "reason": f"no_estimate:{config.model_name}",
            }
        try:
            fleet = self.scheduler._fleet()
            devices = (
                [d for d in fleet.devices if d.is_available]
                if fleet is not None and fleet.devices
                else None
            )
            gang = gang_size(config, len(devices) if devices else n_avail)
            result = planner.plan(
                config, devices=devices, reserved=self.scheduler._reserved,
                gang=gang,
            )
        except Exception as e:  # advisory plane — never sink the plan
            return {"available": False, "reason": f"{type(e).__name__}: {e}"}
        return {
            "available": True,
            "gang": gang,
            "evaluated": result.evaluated,
            "feasible": len(result.plans),
            "pruned": len(result.pruned),
            "ranked_plans": result.table(top_k=5),
            "note": (
                "predicted step times are a nominal-roofline RANKING model "
                "(see tpu_engine/placement.py); submit with mesh='auto' to "
                "admit the top feasible plan"
            ),
        }

    # -- launch --------------------------------------------------------------

    def launch(
        self,
        config: TPUTrainConfig,
        dry_run: bool = False,
        max_steps: Optional[int] = None,
        data_fn: Optional[Callable[[int], jax.Array]] = None,
        watch_preemption: Optional[bool] = None,
        install_signal_handlers: bool = False,
        block: bool = False,
        priority: JobPriority = JobPriority.NORMAL,
        submitter: str = "anonymous",
    ) -> LaunchResult:
        """Two-phase: submit to the scheduler, then one synchronous admit
        pass. An admitted job is ``"launched"``; one the fleet cannot take
        right now is ``"queued"`` with its position (the scheduler keeps
        working on it — this is not a refusal).

        ``watch_preemption=True`` opts into the REAL GCE metadata poll /
        signal handlers; the default (None) still gets a watcher wired to
        the scheduler's preempt seam."""
        plan = self.generate_plan(config)
        ts = datetime.now(timezone.utc).strftime("%Y%m%d_%H%M%S")
        # Reference id format (:330) + a uniquifier: second-resolution stamps
        # collide for rapid launches of the same model.
        job_id = f"tpu_{config.model_name}_{ts}_{uuid.uuid4().hex[:6]}"

        base = dict(
            model_name=config.model_name,
            effective_batch_size=config.effective_batch_size,
            num_devices=jax.device_count(),
            plan=plan,
        )
        if dry_run:
            return LaunchResult(job_id=job_id, status="dry_run", **base)

        if config.model_name not in tfm.MODEL_CONFIGS:
            return LaunchResult(
                job_id=job_id,
                status="failed",
                error=f"unknown model '{config.model_name}'; known: {sorted(tfm.MODEL_CONFIGS)}",
                **base,
            )
        job_kwargs: dict[str, Any] = dict(
            data_fn=data_fn,
            max_steps=max_steps,
            install_signal_handlers=install_signal_handlers,
        )
        if watch_preemption is not None:
            job_kwargs["watch_preemption"] = watch_preemption
        try:
            sub = self.scheduler.submit(
                config, priority=priority, submitter=submitter,
                job_kwargs=job_kwargs,
            )
        except QuotaExceeded as e:
            return LaunchResult(job_id=job_id, status="failed", error=str(e), **base)
        self.scheduler.poll()
        if block:
            sub = self.scheduler.wait(sub.submission_id)
        state = sub.state
        if state == scheduler_mod.SubmissionState.QUEUED:
            return LaunchResult(
                job_id=sub.job_id,
                status="queued",
                submission_id=sub.submission_id,
                queue_position=self.scheduler.queue_position(sub.submission_id),
                **base,
            )
        if state == scheduler_mod.SubmissionState.FAILED and (
            sub.job is None or sub.attempts == 0
        ):
            return LaunchResult(
                job_id=sub.job_id,
                status="failed",
                submission_id=sub.submission_id,
                error=sub.last_skip_reason or "admission failed",
                **base,
            )
        return LaunchResult(
            job_id=sub.job_id,
            status="launched",
            submission_id=sub.submission_id,
            **base,
        )

    # -- presets (reference :369-407) ---------------------------------------

    @staticmethod
    def presets() -> dict[str, TPUTrainConfig]:
        return config_presets()

    # -- registry ------------------------------------------------------------

    def get_job(self, job_id: str) -> Optional[TrainingJob]:
        return self._jobs.get(job_id)

    def list_jobs(self) -> list[dict[str, Any]]:
        return [j.describe() for j in self._jobs.values()]

    def stop_job(self, job_id: str) -> bool:
        job = self._jobs.get(job_id)
        if job is None:
            # Not admitted yet — a queued submission is cancelled instead.
            sub = self.scheduler.find_by_job_id(job_id)
            if sub is not None:
                return self.scheduler.cancel(sub.submission_id)
            return False
        job.stop()
        return True

    def delete_job(self, job_id: str) -> bool:
        """Drop a *terminal* job from the registry (bounds registry growth;
        checkpoints on disk are untouched). Raises ValueError for a job
        that is still pending/compiling/running — stop it first."""
        with self._lock:
            job = self._jobs.get(job_id)
            if job is None:
                return False
            if job.status in (JobStatus.PENDING, JobStatus.COMPILING, JobStatus.RUNNING):
                raise ValueError(
                    f"job '{job_id}' is {job.status.value}; stop it before deleting"
                )
            del self._jobs[job_id]
        return True


# ---------------------------------------------------------------------------
# CLI — `python -m tpu_engine.launcher` (the worker entrypoint used by
# infra/tpu-jobset.yaml; role-parity with the external `deepspeed` CLI the
# reference shells out to at deepspeed_launcher.py:354, except training runs
# in this process).
# ---------------------------------------------------------------------------


def main(argv: Optional[list[str]] = None) -> int:
    import argparse
    import json
    import os

    parser = argparse.ArgumentParser(description="TPU training launcher")
    parser.add_argument("--preset", help="named preset (see --list-presets)")
    parser.add_argument("--model", help="model name (overrides preset's)")
    parser.add_argument("--list-presets", action="store_true")
    parser.add_argument("--max-steps", type=int, default=None)
    parser.add_argument("--checkpoint-dir", default=os.environ.get("CHECKPOINT_DIR"))
    parser.add_argument("--watch-preemption", action="store_true",
                        help="poll the GCE preemption notice; checkpoint on warning")
    parser.add_argument("--dry-run", action="store_true",
                        help="print the execution plan and exit")
    parser.add_argument("--set", action="append", default=[], metavar="KEY=VALUE",
                        help="config override, e.g. --set seq_len=4096 "
                        "--set mesh.fsdp=8 (repeatable)")
    args = parser.parse_args(argv)

    launcher = TPULauncher()
    if args.list_presets:
        for name, cfg in launcher.presets().items():
            print(f"{name}: {cfg.model_name} stage={int(cfg.sharding_stage)} "
                  f"eff_batch={cfg.effective_batch_size}")
        return 0

    if args.preset:
        all_presets = launcher.presets()
        if args.preset not in all_presets:
            parser.error(f"unknown preset '{args.preset}'; known: {sorted(all_presets)}")
        cfg_dict = all_presets[args.preset].model_dump()
    else:
        cfg_dict = TPUTrainConfig().model_dump()
    if args.model:
        cfg_dict["model_name"] = args.model
    if args.checkpoint_dir:
        cfg_dict["checkpoint_dir"] = args.checkpoint_dir
    for item in args.set:
        key, _, value = item.partition("=")
        if not value:
            parser.error(f"--set expects KEY=VALUE, got '{item}'")
        target, leaf = cfg_dict, key
        if "." in key:
            head, leaf = key.rsplit(".", 1)
            for part in head.split("."):
                target = target.setdefault(part, {})
        try:
            target[leaf] = json.loads(value)
        except json.JSONDecodeError:
            target[leaf] = value
    config = TPUTrainConfig(**cfg_dict)

    # Comm-tuning XLA flags must land before the backend initialises
    # (tpu_engine/comm.py — the reference's overlap_comm/bucket analogue).
    from tpu_engine.comm import apply_comm_flags

    apply_comm_flags(config)

    # Multi-host rendezvous FIRST: jax.distributed.initialize() refuses to
    # run once any jax call has initialised the XLA backend — and the
    # compile-cache enable below probes the backend platform.
    from tpu_engine.mesh_runtime import initialize_distributed

    initialize_distributed()

    # Persistent compilation cache: restarts of this worker (preemption,
    # elastic relaunch) warm-start their compiles (tpu_engine/compile_cache).
    from tpu_engine.compile_cache import enable_compilation_cache

    enable_compilation_cache(config.compilation_cache_dir)

    result = launcher.launch(
        config,
        dry_run=args.dry_run,
        max_steps=args.max_steps,
        # True opts into the real GCE poll; None keeps the scheduler seam.
        watch_preemption=True if args.watch_preemption else None,
        install_signal_handlers=not args.dry_run,
        block=not args.dry_run,
    )
    print(json.dumps(result.model_dump(), indent=2, default=str))
    if result.status == "failed":
        return 1
    if result.status == "dry_run":
        return 0
    job = launcher.get_job(result.job_id)
    final = job.describe() if job else {}
    print(json.dumps(final, indent=2, default=str))
    return 0 if final.get("status") == "completed" else 1


if __name__ == "__main__":
    raise SystemExit(main())
