"""Durable control-plane state: write-ahead journal + snapshot + recovery.

The data plane survives chip loss, torn checkpoints, and preemption, but
the control plane (scheduler queue, HBM reservation ledger, serving-fleet
roster, held requests, autopilot/spill cooldowns, prefix host-tier index)
is a single in-memory process. :class:`ControlPlaneJournal` makes its
death recoverable: every state-changing control event is appended as one
JSONL line (write-ahead), and a periodic full-state ``snapshot`` record
bounds replay length. Recovery is ``snapshot + replay of the event
suffix`` — deterministic, so restoring the same journal twice yields
byte-identical state — followed by reconciliation against live reality
(see ``FleetScheduler.restore`` / ``ServingFleet.re_adopt``).

Persistence follows the flight recorder's idiom exactly
(``tracing.FlightRecorder._persist``): size-capped file, atomic
``os.replace`` rotation keeping exactly one previous generation,
``schema_version`` stamped on every line. Ingestion mirrors
``twin.read_recorder_jsonl``: a torn final line of the live file, parse
errors, unknown schema versions, and unknown record kinds are all
skipped and counted, never raised.

``stats()`` and the module-level :func:`journal_stats` /
:func:`recovery_stats` read O(1) counters only — a metrics scrape never
walks journal contents (see ``tests/test_depth_bounds.py``).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

__all__ = [
    "SCHEMA_VERSION",
    "SKIP_REASONS",
    "ControlPlaneJournal",
    "set_active_journal",
    "get_active_journal",
    "clear_active_journal",
    "journal_stats",
    "recovery_stats",
    "note_mttr",
    "note_recovery",
    "collect_sections",
]

# Version stamped onto every journal line. Bump on any change to the
# record shape; readers accept lines at or below their own version and
# skip newer ones, so an old journal stays restorable across upgrades.
SCHEMA_VERSION = 1

# Record kinds a reader of this build understands.
_KNOWN_RECORDS = ("snapshot", "event")

SKIP_REASONS = ("torn_tail", "parse_error", "unknown_schema", "unknown_record")

DEFAULT_MAX_BYTES = 16 * 1024 * 1024


# -- module health counters (tpu_engine_journal_* / _ctl_recovery_*) ----------

_STATS_LOCK = threading.Lock()
_READ_STATS: Dict[str, Any] = {
    "reads_total": 0,
    "read_lines_total": 0,
    "read_skipped_lines_total": 0,
    "read_skipped_by_reason": {r: 0 for r in SKIP_REASONS},
}
_RECOVERY: Dict[str, Any] = {
    "restores_total": 0,
    "records_replayed_total": 0,
    "jobs_readopted_total": 0,
    "requeued_vanished_total": 0,
    "double_grants_total": 0,
    "replicas_readopted_total": 0,
    "replicas_redispatched_total": 0,
    "requests_recovered_total": 0,
    "last_mttr_seconds": 0.0,
}


def recovery_stats() -> Dict[str, Any]:
    """Snapshot of the crash-recovery counters (O(1), no journal walk)."""
    with _STATS_LOCK:
        return dict(_RECOVERY)


def note_mttr(seconds: float) -> None:
    """Record the wall duration of the last control-plane recovery."""
    with _STATS_LOCK:
        _RECOVERY["last_mttr_seconds"] = float(seconds)


def note_recovery(**deltas: float) -> None:
    """Accumulate recovery counters (called by the restore/re_adopt paths)."""
    with _STATS_LOCK:
        for k, v in deltas.items():
            _RECOVERY[k] += v


def _reset_stats_for_tests() -> None:
    with _STATS_LOCK:
        for k, v in list(_READ_STATS.items()):
            _READ_STATS[k] = {r: 0 for r in SKIP_REASONS} if isinstance(v, dict) else 0
        for k, v in list(_RECOVERY.items()):
            _RECOVERY[k] = 0 if isinstance(v, int) else 0.0


# -- the journal ---------------------------------------------------------------


class ControlPlaneJournal:
    """Bounded, atomically-rotated JSONL write-ahead journal.

    Two record kinds: ``event`` (one control-plane state change) and
    ``snapshot`` (full serialized state; replay starts from the newest
    one). Appends never raise — persistence failures increment
    ``append_errors_total`` and the control plane keeps running, exactly
    like the flight recorder."""

    def __init__(
        self,
        path: str,
        max_bytes: int = DEFAULT_MAX_BYTES,
        clock: Callable[[], float] = time.time,
    ):
        self.path = path
        self.max_bytes = int(max_bytes)
        self.clock = clock
        self._lock = threading.Lock()
        self.bytes = 0
        self.appends_total = 0
        self.snapshots_total = 0
        self.rotations_total = 0
        self.append_errors_total = 0
        if os.path.exists(path):
            try:
                self.bytes = os.path.getsize(path)
            except OSError:
                pass

    # -- writes ---------------------------------------------------------------

    def append(self, kind: str, payload: Dict[str, Any], ts: Optional[float] = None) -> None:
        """Write-ahead one control-plane event (e.g. ``sched.submit``)."""
        self._write({
            "record": "event",
            "kind": kind,
            "ts": self.clock() if ts is None else ts,
            "payload": payload,
        })
        with self._lock:
            self.appends_total += 1

    def snapshot(self, sections: Dict[str, Any], ts: Optional[float] = None) -> None:
        """Write a full-state snapshot; replay starts at the newest one.

        ``sections`` maps component name (``scheduler``, ``serving``,
        ``autopilot``, ``spec_spill``, ``prefix_host``) to that
        component's serialized state dict."""
        self._write({
            "record": "snapshot",
            "ts": self.clock() if ts is None else ts,
            "sections": sections,
        })
        with self._lock:
            self.snapshots_total += 1

    def _write(self, record: Dict[str, Any]) -> None:
        try:
            record = dict(record, schema_version=SCHEMA_VERSION)
            line = json.dumps(record, default=str) + "\n"
            with self._lock:
                if self.bytes + len(line) > self.max_bytes:
                    # rotate: keep exactly one previous generation bounded
                    try:
                        os.replace(self.path, self.path + ".1")
                    except OSError:
                        pass
                    self.bytes = 0
                    self.rotations_total += 1
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line)
                self.bytes += len(line)
        except Exception:
            with self._lock:
                self.append_errors_total += 1

    # -- reads ----------------------------------------------------------------

    def read(self) -> Dict[str, Any]:
        """Ingest the journal (rotated ``.1`` generation first) into the
        newest snapshot plus the event suffix recorded after it.

        Hardened for mid-write capture exactly like
        ``twin.read_recorder_jsonl``: an undecodable *final* line of the
        live file is a torn tail, any other bad line a parse error, a
        ``schema_version`` above this build's an unknown future format,
        an unrecognized ``record`` kind an unknown record — all skipped
        and counted, never raised. Lines without ``schema_version`` are
        legacy and accepted."""
        files = [p for p in (self.path + ".1", self.path) if os.path.exists(p)]
        snapshot: Optional[dict] = None
        events: list = []
        stats: Dict[str, Any] = {
            "files": len(files),
            "lines": 0,
            "accepted": 0,
            "skipped": 0,
            "skipped_by_reason": {},
            "legacy_lines": 0,
            "schema_version": SCHEMA_VERSION,
        }

        def _skip(reason: str) -> None:
            stats["skipped"] += 1
            by = stats["skipped_by_reason"]
            by[reason] = by.get(reason, 0) + 1

        for fi, fp in enumerate(files):
            with open(fp, encoding="utf-8", errors="replace") as f:
                lines = f.read().split("\n")
            if lines and lines[-1] == "":
                lines.pop()
            for li, line in enumerate(lines):
                if not line.strip():
                    continue
                stats["lines"] += 1
                # Only the live file's final line can be a torn partial
                # write; rotation happens on line boundaries.
                torn_candidate = fi == len(files) - 1 and li == len(lines) - 1
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    _skip("torn_tail" if torn_candidate else "parse_error")
                    continue
                if not isinstance(rec, dict):
                    _skip("parse_error")
                    continue
                sv = rec.get("schema_version")
                if sv is None:
                    stats["legacy_lines"] += 1  # pre-versioning journal
                elif not isinstance(sv, int) or sv < 1 or sv > SCHEMA_VERSION:
                    _skip("unknown_schema")
                    continue
                kind = rec.get("record")
                if kind not in _KNOWN_RECORDS:
                    _skip("unknown_record")
                    continue
                stats["accepted"] += 1
                if kind == "snapshot":
                    snapshot = rec
                    events = []  # replay restarts at the newest snapshot
                else:
                    events.append(rec)

        with _STATS_LOCK:
            _READ_STATS["reads_total"] += 1
            _READ_STATS["read_lines_total"] += stats["lines"]
            _READ_STATS["read_skipped_lines_total"] += stats["skipped"]
            for r, n in stats["skipped_by_reason"].items():
                by = _READ_STATS["read_skipped_by_reason"]
                by[r] = by.get(r, 0) + n
        return {"snapshot": snapshot, "events": events, "stats": stats}

    # -- health ---------------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """O(1) counters — never opens or walks the journal files."""
        with self._lock:
            return {
                "path": self.path,
                "max_bytes": self.max_bytes,
                "bytes": self.bytes,
                "appends_total": self.appends_total,
                "snapshots_total": self.snapshots_total,
                "rotations_total": self.rotations_total,
                "append_errors_total": self.append_errors_total,
            }


# -- process-wide active journal (mirrors faults.set_active) -------------------

_ACTIVE_LOCK = threading.Lock()
_active: Optional[ControlPlaneJournal] = None


def set_active_journal(journal: Optional[ControlPlaneJournal]) -> None:
    global _active
    with _ACTIVE_LOCK:
        _active = journal


def get_active_journal() -> Optional[ControlPlaneJournal]:
    with _ACTIVE_LOCK:
        return _active


def clear_active_journal() -> None:
    set_active_journal(None)


def journal_stats() -> Dict[str, Any]:
    """Module health snapshot for ``/metrics`` and ``/api/v1/journal``:
    the active journal's write counters (zeros when none is attached)
    plus the module-level read counters. O(1) — no file access."""
    j = get_active_journal()
    js = j.stats() if j is not None else {
        "path": None,
        "max_bytes": 0,
        "bytes": 0,
        "appends_total": 0,
        "snapshots_total": 0,
        "rotations_total": 0,
        "append_errors_total": 0,
    }
    with _STATS_LOCK:
        out = dict(js)
        out["attached"] = j is not None
        out["reads_total"] = _READ_STATS["reads_total"]
        out["read_lines_total"] = _READ_STATS["read_lines_total"]
        out["read_skipped_lines_total"] = _READ_STATS["read_skipped_lines_total"]
        out["read_skipped_by_reason"] = dict(_READ_STATS["read_skipped_by_reason"])
    return out


# -- snapshot assembly ---------------------------------------------------------


def collect_sections(
    scheduler: Any = None,
    serving: Any = None,
    autopilot: Any = None,
    spec_spill: Any = None,
    prefix_plane: Any = None,
) -> Dict[str, Any]:
    """Gather one full-state snapshot from the live control-plane
    components. Each argument is optional; components that expose
    ``snapshot_state()`` / ``export_state()`` contribute a section."""
    sections: Dict[str, Any] = {}
    if scheduler is not None:
        sections["scheduler"] = scheduler.snapshot_state()
    if serving is not None:
        sections["serving"] = serving.snapshot_state()
    if autopilot is not None:
        sections["autopilot"] = autopilot.export_state()
    if spec_spill is not None:
        sections["spec_spill"] = spec_spill.export_state()
    if prefix_plane is not None:
        sections["prefix_host"] = prefix_plane.export_host_index()
    return sections
