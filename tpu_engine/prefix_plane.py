"""Fleet-wide prefix plane: radix token index + host-RAM KV tier.

Today's prefix reuse is strictly per-replica: every
:class:`~tpu_engine.serving.ContinuousBatcher` keeps its own
``_PrefixCache`` and the :class:`~tpu_engine.serving_fleet.FleetRouter`
only exploits it through fixed-width affinity pinning. At
millions-of-users traffic the same system prompts get redundantly
prefilled and redundantly cached on every replica, and a replica
eviction throws the fleet's only copy away. This module promotes the
cache to a fleet tier (ZeRO-Infinity's device/host capacity-tiering
idea applied to serving KV, with the PR 12 int8
:class:`~tpu_engine.disagg.KVHandoff` wire format as the transport):

- :class:`PrefixTrieIndex` — a radix/trie token index over every
  replica's resident prefixes plus the host tier's, so routing can ask
  "who holds the longest prefix of THIS prompt" in one walk instead of
  a per-replica scan.
- :class:`HostKVTier` — a budgeted host-RAM tier of int8 ``KVHandoff``
  payloads absorbing evicted/overflow prefixes. Eviction is driven by
  historian-measured reuse (the per-prefix hit-token series this plane
  records into :class:`~tpu_engine.historian.MetricHistorian`), not
  recency: a prefix that re-earns its bytes stays even when it was not
  touched most recently.
- :class:`PrefixPlane` — the control object the router consults
  (:meth:`PrefixPlane.route_hint`) and the fleet feeds
  (:meth:`PrefixPlane.observe_admit`): cache-aware routing to the
  longest-prefix-holding replica with a free slot, host-tier
  rehydration when no replica holds it, and replica-cache mirrors whose
  overflow spills to the host tier.

Admission stays honest through
:func:`tpu_engine.hbm_estimate.estimate_serving_hbm`'s host-tier term:
:meth:`PrefixPlane.plan_host_tier` sizes the tier through the estimator
and propagates its structured
:class:`~tpu_engine.hbm_estimate.HostBudgetExceeded` rejection, so the
plane can never promise KV the host cannot hold.

Everything is clockless (pass ``now=``) so the twin's
``prefix_plane_lane`` drives the SAME objects the live fleet does, and
module-level counters back the always-rendered
``tpu_engine_prefix_plane_*`` Prometheus families.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from tpu_engine import historian as historian_mod

__all__ = [
    "HIT_TOKENS_SERIES",
    "PrefixTrieIndex",
    "HostKVTier",
    "PrefixPlane",
    "quantize_handoff",
    "plane_stats",
]

# Per-prefix hit-token series the plane records into the historian; the
# host tier's reuse-driven eviction queries it back (agg="sum" over the
# reuse window). One labelled series per prefix key.
HIT_TOKENS_SERIES = "serving.prefix_plane.hit_tokens"

# Sentinel holder id for host-tier residency inside the trie index.
HOST_HOLDER = "__host__"


# -- module health counters (tpu_engine_prefix_plane_* families) --------------

_STATS_LOCK = threading.Lock()
_STATS: Dict[str, float] = {
    "lookups_total": 0,
    "index_hits_total": 0,
    "host_hits_total": 0,
    "host_stores_total": 0,
    "host_evictions_total": 0,
    "rehydrations_total": 0,
    "hit_tokens_total": 0,
    # Gauges: the most recent plane snapshot (one live plane per process
    # in practice; the twin installs its own and restores after).
    "index_prefixes": 0,
    "host_entries": 0,
    "host_bytes": 0,
}


def plane_stats() -> Dict[str, float]:
    """Snapshot of the plane's monotonic counters + last-seen gauges."""
    with _STATS_LOCK:
        return dict(_STATS)


def _reset_stats_for_tests() -> None:
    with _STATS_LOCK:
        for k in _STATS:
            _STATS[k] = 0


def _bump(**deltas: float) -> None:
    with _STATS_LOCK:
        for k, v in deltas.items():
            _STATS[k] += v


def _gauge(**values: float) -> None:
    with _STATS_LOCK:
        _STATS.update(values)


def quantize_handoff(handoff: Any) -> Any:
    """The host tier's storage form: int8 codes + per-(layer, token,
    kv-head) fp32 scales — 3.2x smaller than the fp32 wire, within the
    documented one-token decode bound. Already-quantized payloads pass
    through byte-for-byte (re-quantizing int8 codes would only add
    error)."""
    import dataclasses as _dc

    from tpu_engine.disagg import _np_quantize

    if getattr(handoff, "quantized", False):
        return handoff
    qk, sk = _np_quantize(handoff.k)
    qv, sv = _np_quantize(handoff.v)
    return _dc.replace(
        handoff, dtype="int8", quantized=True,
        k=qk, v=qv, k_scale=sk, v_scale=sv,
    )


# -- radix token index --------------------------------------------------------


class _TrieNode:
    __slots__ = ("children", "holders")

    def __init__(self):
        self.children: Dict[int, "_TrieNode"] = {}
        self.holders: set = set()


class PrefixTrieIndex:
    """Radix/trie index from token prefixes to the holders caching them.

    A holder is a replica id (or :data:`HOST_HOLDER`); each registered
    prefix marks its terminal node. :meth:`longest_holders` walks a
    prompt once and returns the deepest marked node — O(prompt length),
    independent of fleet size and entry count."""

    def __init__(self):
        self._root = _TrieNode()
        self._holder_prefixes: Dict[str, set] = {}
        self.nodes = 1

    @property
    def n_prefixes(self) -> int:
        return len({p for ps in self._holder_prefixes.values() for p in ps})

    def prefixes(self, holder: str) -> set:
        return set(self._holder_prefixes.get(holder, ()))

    def insert(self, prefix: Sequence[int], holder: str) -> None:
        prefix = tuple(int(t) for t in prefix)
        if not prefix:
            return
        node = self._root
        for tok in prefix:
            nxt = node.children.get(tok)
            if nxt is None:
                nxt = node.children[tok] = _TrieNode()
                self.nodes += 1
            node = nxt
        node.holders.add(holder)
        self._holder_prefixes.setdefault(holder, set()).add(prefix)

    def remove(self, prefix: Sequence[int], holder: str) -> None:
        prefix = tuple(int(t) for t in prefix)
        held = self._holder_prefixes.get(holder)
        if held is None or prefix not in held:
            return
        held.discard(prefix)
        if not held:
            self._holder_prefixes.pop(holder, None)
        path: List[Tuple[_TrieNode, int]] = []
        node = self._root
        for tok in prefix:
            nxt = node.children.get(tok)
            if nxt is None:
                return
            path.append((node, tok))
            node = nxt
        node.holders.discard(holder)
        # Prune now-empty tail nodes so the index stays bounded by the
        # LIVE prefix set, not everything ever registered.
        for parent, tok in reversed(path):
            child = parent.children[tok]
            if child.holders or child.children:
                break
            del parent.children[tok]
            self.nodes -= 1

    def drop_holder(self, holder: str) -> None:
        """Forget every prefix a dead holder registered."""
        for prefix in list(self._holder_prefixes.get(holder, ())):
            self.remove(prefix, holder)

    def longest_holders(
        self, prompt: Sequence[int], exclude: Optional[set] = None
    ) -> Tuple[int, set]:
        """Deepest marked node along ``prompt``: (matched token count,
        holder set). ``exclude`` filters holders (e.g. the host sentinel
        when picking a replica)."""
        node = self._root
        best_len, best_holders = 0, set()
        depth = 0
        for tok in prompt:
            node = node.children.get(int(tok))
            if node is None:
                break
            depth += 1
            holders = node.holders if exclude is None else \
                node.holders - exclude
            if holders:
                best_len, best_holders = depth, set(holders)
        return best_len, best_holders


# -- host-RAM KV tier ---------------------------------------------------------


class HostKVTier:
    """Budgeted host-RAM tier of int8 ``KVHandoff`` prefix payloads.

    ``put`` quantizes fp payloads on store (:func:`quantize_handoff`) and
    charges ``wire_bytes()`` against the byte budget; capacity-model
    callers (the twin lane) pass ``nbytes`` instead of a payload and the
    ledger works identically. Eviction picks the LOWEST reuse score —
    hit-tokens over the trailing ``reuse_window_s`` from the historian's
    per-prefix series, falling back to the tier's own lifetime counters
    when the series has no coverage — with insertion-order (LRU via
    ``get``'s move-to-end) as the deterministic tie-break."""

    def __init__(
        self,
        budget_bytes: int = 256 << 20,
        historian: Optional["historian_mod.MetricHistorian"] = None,
        clock: Callable[[], float] = time.time,
        reuse_window_s: float = 600.0,
    ):
        self.budget_bytes = int(budget_bytes)
        self.reuse_window_s = float(reuse_window_s)
        self._historian = historian
        self._clock = clock
        self._entries: "collections.OrderedDict[tuple, Any]" = \
            collections.OrderedDict()
        self._bytes: Dict[tuple, int] = {}
        self._hit_tokens: Dict[tuple, int] = {}
        self.total_bytes = 0
        self.hits = 0
        self.misses = 0
        self.stores = 0
        self.evictions = 0

    # The historian label for one prefix: short, deterministic, and
    # unique for any realistic prefix population (length + first/last
    # token disambiguate shared-prefix traces without shipping the whole
    # token tuple as a label value).
    @staticmethod
    def prefix_label(prefix: tuple) -> str:
        return f"{len(prefix)}:{prefix[0]}:{prefix[-1]}" if prefix else "0"

    def historian(self) -> "historian_mod.MetricHistorian":
        return self._historian if self._historian is not None else \
            historian_mod.get_historian()

    def __contains__(self, prefix: tuple) -> bool:
        return tuple(prefix) in self._entries

    def contains(self, prefix: Sequence[int]) -> bool:
        return tuple(int(t) for t in prefix) in self._entries

    def note_hit(self, prefix: tuple, tokens: int,
                 now: Optional[float] = None) -> None:
        """Record ``tokens`` of prefix reuse: the tier's own ledger AND
        the historian series eviction scores against."""
        prefix = tuple(prefix)
        now = self._clock() if now is None else float(now)
        self._hit_tokens[prefix] = self._hit_tokens.get(prefix, 0) + int(tokens)
        try:
            self.historian().record(
                HIT_TOKENS_SERIES, float(tokens), ts=now,
                labels={"prefix": self.prefix_label(prefix)},
            )
        except Exception:
            pass  # reuse telemetry must never fail a request

    def _reuse_score(self, prefix: tuple, now: float) -> float:
        try:
            q = self.historian().query(
                HIT_TOKENS_SERIES, t0=now - self.reuse_window_s, t1=now,
                agg="sum", labels={"prefix": self.prefix_label(prefix)},
            )
            if q.get("count"):
                return float(q["value"] or 0.0)
        except Exception:
            pass
        return float(self._hit_tokens.get(prefix, 0))

    def put(self, prefix: Sequence[int], handoff: Any = None,
            nbytes: Optional[int] = None,
            now: Optional[float] = None) -> bool:
        """Store (or refresh) a prefix payload; False when it alone
        exceeds the whole budget (storing it would evict every reusable
        entry for bytes that may never be hit again)."""
        prefix = tuple(int(t) for t in prefix)
        if not prefix:
            return False
        now = self._clock() if now is None else float(now)
        if handoff is not None:
            handoff = quantize_handoff(handoff)
            nbytes = int(handoff.wire_bytes())
        nbytes = int(nbytes or 0)
        if nbytes > self.budget_bytes:
            return False
        if prefix in self._entries:
            self.total_bytes -= self._bytes[prefix]
        while self.total_bytes + nbytes > self.budget_bytes and self._entries:
            self._evict_one(now)
        self._entries[prefix] = handoff
        self._bytes[prefix] = nbytes
        self.total_bytes += nbytes
        self.stores += 1
        _bump(host_stores_total=1)
        self._publish()
        return True

    def _evict_one(self, now: float) -> None:
        victim = min(
            self._entries,
            key=lambda p: (self._reuse_score(p, now),
                           list(self._entries).index(p)),
        )
        self.total_bytes -= self._bytes.pop(victim)
        self._entries.pop(victim)
        self._hit_tokens.pop(victim, None)
        self.evictions += 1
        _bump(host_evictions_total=1)

    def get(self, prefix: Sequence[int],
            now: Optional[float] = None) -> Any:
        """The stored payload (None for capacity-model entries AND for
        misses — use :meth:`contains` to tell them apart). A hit counts
        reuse and refreshes recency."""
        prefix = tuple(int(t) for t in prefix)
        if prefix not in self._entries:
            self.misses += 1
            return None
        self._entries.move_to_end(prefix)
        self.hits += 1
        _bump(host_hits_total=1)
        self.note_hit(prefix, len(prefix), now=now)
        return self._entries[prefix]

    def pop(self, prefix: Sequence[int]) -> Any:
        prefix = tuple(int(t) for t in prefix)
        if prefix not in self._entries:
            return None
        self.total_bytes -= self._bytes.pop(prefix)
        self._hit_tokens.pop(prefix, None)
        out = self._entries.pop(prefix)
        self._publish()
        return out

    def _publish(self) -> None:
        _gauge(host_entries=len(self._entries),
               host_bytes=self.total_bytes)

    def stats(self) -> Dict[str, Any]:
        return {
            "entries": len(self._entries),
            "bytes": self.total_bytes,
            "budget_bytes": self.budget_bytes,
            "occupancy": round(
                self.total_bytes / self.budget_bytes, 4
            ) if self.budget_bytes else 0.0,
            "hits": self.hits,
            "misses": self.misses,
            "stores": self.stores,
            "evictions": self.evictions,
        }


# -- the plane ----------------------------------------------------------------


class PrefixPlane:
    """Fleet-wide prefix-cache control plane.

    The router consults :meth:`route_hint` (longest-prefix-holding
    replica with a free slot); the fleet/lane reports every admission
    through :meth:`observe_admit`, which keeps a bounded per-replica
    mirror of what each replica's cache plausibly holds, spills mirror
    overflow to the host tier (via ``spill`` — a real payload exporter
    in the live fleet, a byte-count model in the twin), and classifies
    the admission as ``replica`` hit / ``host`` rehydration / ``cold``.

    ``prefix_tokens`` is the indexed prefix width (matches the router's
    affinity window by default); ``replica_prefix_budget`` bounds each
    replica's mirror at the entry count its on-device cache can actually
    retain."""

    def __init__(
        self,
        prefix_tokens: int = 32,
        replica_prefix_budget: int = 64,
        host: Optional[HostKVTier] = None,
        historian: Optional["historian_mod.MetricHistorian"] = None,
        clock: Callable[[], float] = time.time,
        spill: Optional[Callable[[tuple, str], Any]] = None,
    ):
        self.prefix_tokens = int(prefix_tokens)
        self.replica_prefix_budget = int(replica_prefix_budget)
        self.index = PrefixTrieIndex()
        self.host = host if host is not None else \
            HostKVTier(historian=historian, clock=clock)
        self._historian = historian
        self._clock = clock
        # spill(prefix, rid) -> KVHandoff | int bytes | None: called when
        # a replica-mirror eviction leaves no other replica holding the
        # prefix; None drops it (nothing to absorb).
        self.spill = spill
        self._replica_lru: Dict[str, "collections.OrderedDict[tuple, None]"] = {}
        self.lookups = 0
        self.index_hits = 0
        self.host_rehydrations = 0
        self.hit_tokens = 0

    @classmethod
    def plan_host_tier(
        cls,
        model_name: str,
        max_slots: int,
        max_len: int,
        host_prefix_tokens: int,
        host_budget_gib: float,
        **estimate_kw: Any,
    ) -> HostKVTier:
        """Size a host tier through the HBM estimator's host-tier term —
        raises :class:`~tpu_engine.hbm_estimate.HostBudgetExceeded` (the
        structured rejection) when the promised tokens oversubscribe the
        budget, so a plane can never be built around KV the host cannot
        hold."""
        from tpu_engine.hbm_estimate import estimate_serving_hbm

        est = estimate_serving_hbm(
            model_name, max_slots, max_len,
            host_prefix_tokens=host_prefix_tokens,
            host_budget_gib=host_budget_gib,
            **estimate_kw,
        )
        if est is None:
            raise ValueError(f"unknown model {model_name!r}")
        return HostKVTier(budget_bytes=int(host_budget_gib * (1 << 30)))

    def _prefix_of(self, prompt: Sequence[int]) -> tuple:
        return tuple(int(t) for t in prompt[: self.prefix_tokens])

    def historian(self) -> "historian_mod.MetricHistorian":
        return self._historian if self._historian is not None else \
            historian_mod.get_historian()

    def note_hit(self, prefix: tuple, tokens: int,
                 now: Optional[float] = None) -> None:
        self.hit_tokens += int(tokens)
        _bump(hit_tokens_total=int(tokens))
        self.host.note_hit(tuple(prefix), tokens, now=now)

    # -- routing ----------------------------------------------------------

    def route_hint(
        self,
        prompt: Sequence[int],
        free: Dict[str, int],
    ) -> Tuple[Optional[str], int]:
        """(replica id, matched token count) for the longest-prefix
        holder with a free slot; (None, matched) when only the host tier
        (or nobody) holds it. Ties break on most free slots, then
        replica id — deterministic for the twin."""
        self.lookups += 1
        _bump(lookups_total=1)
        matched, holders = self.index.longest_holders(
            prompt[: self.prefix_tokens], exclude={HOST_HOLDER}
        )
        if matched <= 0:
            return None, 0
        live = [r for r in holders if free.get(r, 0) > 0]
        if not live:
            return None, matched
        pick = max(live, key=lambda r: (free.get(r, 0), r))
        self.index_hits += 1
        _bump(index_hits_total=1)
        return pick, matched

    def host_prefix_for(self, prompt: Sequence[int]) -> Optional[tuple]:
        """Longest host-tier-resident prefix of ``prompt`` (None when the
        host tier holds nothing useful)."""
        matched, holders = self.index.longest_holders(
            prompt[: self.prefix_tokens]
        )
        if matched <= 0 or HOST_HOLDER not in holders:
            return None
        prefix = tuple(int(t) for t in prompt[:matched])
        return prefix if self.host.contains(prefix) else None

    # -- admission bookkeeping --------------------------------------------

    def observe_admit(self, prompt: Sequence[int], rid: str,
                      now: Optional[float] = None) -> Dict[str, Any]:
        """Record that ``rid`` admitted ``prompt``; returns
        ``{"kind": "replica"|"host"|"cold", "prefix", "payload",
        "evicted"}``. ``payload`` is the host-tier payload to rehydrate
        (a ``KVHandoff`` in the live fleet, None in capacity-model
        runs)."""
        now = self._clock() if now is None else float(now)
        prefix = self._prefix_of(prompt)
        if not prefix:
            return {"kind": "cold", "prefix": prefix, "payload": None,
                    "evicted": []}
        lru = self._replica_lru.setdefault(rid, collections.OrderedDict())
        payload = None
        if prefix in lru:
            kind = "replica"
            lru.move_to_end(prefix)
            self.note_hit(prefix, len(prefix), now=now)
        elif self.host.contains(prefix):
            kind = "host"
            payload = self.host.get(prefix, now=now)
            self.host_rehydrations += 1
            _bump(rehydrations_total=1)
        else:
            kind = "cold"
        evicted: List[tuple] = []
        if kind != "replica":
            lru[prefix] = None
            self.index.insert(prefix, rid)
            while len(lru) > self.replica_prefix_budget:
                old, _ = lru.popitem(last=False)
                self.index.remove(old, rid)
                evicted.append(old)
                self._spill(old, rid, now)
        self._publish()
        return {"kind": kind, "prefix": prefix, "payload": payload,
                "evicted": evicted}

    def _spill(self, prefix: tuple, rid: str, now: float) -> None:
        """Absorb a replica-cache eviction into the host tier when no
        other replica still holds the prefix."""
        _, holders = self.index.longest_holders(prefix,
                                                exclude={HOST_HOLDER})
        if holders or self.host.contains(prefix):
            return
        payload = self.spill(prefix, rid) if self.spill is not None else None
        if payload is None:
            return
        stored = (
            self.host.put(prefix, nbytes=payload, now=now)
            if isinstance(payload, (int, float))
            else self.host.put(prefix, handoff=payload, now=now)
        )
        if stored:
            self.index.insert(prefix, HOST_HOLDER)

    def store_host(self, prefix: Sequence[int], handoff: Any = None,
                   nbytes: Optional[int] = None,
                   now: Optional[float] = None) -> bool:
        """Directly park a prefix payload in the host tier (teardown /
        drain paths)."""
        prefix = tuple(int(t) for t in prefix)
        ok = self.host.put(prefix, handoff=handoff, nbytes=nbytes, now=now)
        if ok:
            self.index.insert(prefix, HOST_HOLDER)
        self._sync_host_index()
        self._publish()
        return ok

    def _sync_host_index(self) -> None:
        """Drop index markers for prefixes the host tier evicted."""
        for prefix in self.index.prefixes(HOST_HOLDER):
            if not self.host.contains(prefix):
                self.index.remove(prefix, HOST_HOLDER)

    def drop_replica(self, rid: str) -> None:
        """A replica died/drained: forget its mirror and index entries
        (its device KV is gone — only the host tier survives it)."""
        self._replica_lru.pop(rid, None)
        self.index.drop_holder(rid)
        self._publish()

    # -- durability (control-plane journal snapshot section) -----------------

    def export_host_index(self) -> Dict[str, Any]:
        """Serialized host-tier *index* for the control-plane journal:
        which prefixes the host tier holds and how many bytes each
        charges. Payloads are deliberately NOT journaled — after
        :meth:`load_host_index` the index is warm (routing and capacity
        accounting work immediately) and payloads refetch on miss."""
        entries = []
        for prefix in sorted(self.index.prefixes(HOST_HOLDER)):
            if self.host.contains(prefix):
                entries.append({
                    "prefix": [int(t) for t in prefix],
                    "nbytes": int(self.host._bytes.get(tuple(prefix), 0)),
                })
        return {
            "prefix_tokens": self.prefix_tokens,
            "entries": entries,
        }

    def load_host_index(self, state: Dict[str, Any]) -> int:
        """Inverse of :meth:`export_host_index` on a fresh plane: re-park
        every journaled prefix as a capacity-model entry (``handoff=None``
        — the bytes ledger and routing index are restored; the payload
        itself rehydrates from a replica or refetches on first use).
        Returns the number of entries restored."""
        if not isinstance(state, dict):
            return 0
        restored = 0
        for e in state.get("entries") or []:
            try:
                prefix = tuple(int(t) for t in e["prefix"])
                nbytes = int(e.get("nbytes", 0))
            except (KeyError, TypeError, ValueError):
                continue
            if self.host.put(prefix, nbytes=nbytes):
                self.index.insert(prefix, HOST_HOLDER)
                restored += 1
        self._publish()
        return restored

    def _publish(self) -> None:
        _gauge(index_prefixes=self.index.n_prefixes)

    def stats(self) -> Dict[str, Any]:
        return {
            "prefix_tokens": self.prefix_tokens,
            "lookups": self.lookups,
            "index_hits": self.index_hits,
            "host_rehydrations": self.host_rehydrations,
            "hit_tokens": self.hit_tokens,
            "index_prefixes": self.index.n_prefixes,
            "index_nodes": self.index.nodes,
            "replicas_tracked": len(self._replica_lru),
            "host": self.host.stats(),
        }
