"""Preemption / spot resiliency — the reference's stub, implemented for real.

Reference ``ai_engine/spot_resiliency.py`` is a 49-line stub: it polls a
simulated flag every 5 s (``:24-41``) and *prints* what an emergency
checkpoint would do (``:43-49``); the real metadata URLs exist only in
comments (``:25-29``). Here:

- the GCE metadata preemption endpoint is actually polled
  (``/computeMetadata/v1/instance/preempted``, the exact URL the stub cites);
- a SIGTERM/SIGINT handler triggers the same emergency path (GKE and TPU
  maintenance events deliver SIGTERM with a grace window);
- the fault-injection seam is preserved (``simulate_interruption`` — parity
  with ``_simulate_interruption``, ``spot_resiliency.py:39-41``) so tests can
  drive the full emergency path without a cloud;
- the emergency callback is supplied by the supervisor: synchronous Orbax
  save → mark job preempted → (optionally) exit. Auto-resume on restart is
  the supervisor's side (``tpu_engine/supervisor.py``).

Cloud scope — GCP ONLY, deliberately. The reference stub's comments cite
both the AWS instance-action URL and the GCP preempted URL
(``spot_resiliency.py:25-29``); TPUs exist only in Google Cloud, so this
TPU-native build polls the GCE endpoint and does not carry a dead AWS
code path. Non-GCE environments (including any future AWS-hosted
runtime) are still covered by the SIGTERM handler — every major cloud
delivers spot/maintenance interruptions as SIGTERM with a grace window —
and by the simulation seam for tests.
"""

from __future__ import annotations

import logging
import signal
import threading
import urllib.request
from typing import Callable, Optional

log = logging.getLogger(__name__)

GCE_PREEMPTION_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/preempted"
)


def check_gce_preempted(timeout: float = 1.0) -> bool:
    """Poll the GCE metadata server; False on any error (not on GCE, etc.)."""
    try:
        req = urllib.request.Request(
            GCE_PREEMPTION_URL, headers={"Metadata-Flavor": "Google"}
        )
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.read().decode().strip().upper() == "TRUE"
    except Exception:
        return False


class PreemptionWatcher:
    """Background preemption monitor with a fault-injection seam.

    ``on_preemption`` is called exactly once, from the watcher thread (or the
    signal handler's main thread), when any of these fire:
    metadata says preempted · ``simulate_interruption()`` set · SIGTERM/SIGINT.
    """

    def __init__(
        self,
        on_preemption: Callable[[str], None],
        check_interval_s: float = 5.0,  # reference poll interval, spot_resiliency.py:13
        install_signal_handlers: bool = False,
        metadata_check: Optional[Callable[[], bool]] = check_gce_preempted,
    ):
        self.on_preemption = on_preemption
        self.check_interval_s = check_interval_s
        self.metadata_check = metadata_check
        self._install_signals = install_signal_handlers
        self._simulated = threading.Event()
        self._stop = threading.Event()
        self._fired = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prev_handlers: dict[int, object] = {}

    # -- fault injection seam (parity with _simulate_interruption :39-41) ----

    def simulate_interruption(self) -> None:
        """Inject a preemption notice (test seam)."""
        self._simulated.set()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._install_signals:
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    self._prev_handlers[sig] = signal.signal(sig, self._signal_handler)
                except ValueError:
                    pass  # not on main thread; metadata/simulated paths still work
        self._thread = threading.Thread(target=self._loop, daemon=True, name="preemption-watcher")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.check_interval_s + 2)
        for sig, handler in self._prev_handlers.items():
            try:
                signal.signal(sig, handler)  # type: ignore[arg-type]
            except (ValueError, TypeError):
                pass

    @property
    def fired(self) -> bool:
        return self._fired.is_set()

    # -- internals -----------------------------------------------------------

    def _signal_handler(self, signum, frame) -> None:
        log.warning("received signal %s — triggering emergency checkpoint", signum)
        self._fire(f"signal:{signal.Signals(signum).name}")

    def _fire(self, reason: str) -> None:
        if self._fired.is_set():
            return
        self._fired.set()
        try:
            self.on_preemption(reason)
        except Exception:
            log.exception("preemption callback failed")

    def _loop(self) -> None:
        while not self._stop.is_set():
            if self._simulated.is_set():
                self._fire("simulated")
                return
            if self.metadata_check is not None and self.metadata_check():
                self._fire("gce-metadata")
                return
            self._stop.wait(self.check_interval_s)
