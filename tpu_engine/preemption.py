"""Preemption / spot resiliency — the reference's stub, implemented for real.

Reference ``ai_engine/spot_resiliency.py`` is a 49-line stub: it polls a
simulated flag every 5 s (``:24-41``) and *prints* what an emergency
checkpoint would do (``:43-49``); the real metadata URLs exist only in
comments (``:25-29``). Here:

- the GCE metadata preemption endpoint is actually polled
  (``/computeMetadata/v1/instance/preempted``, the exact URL the stub cites);
- a SIGTERM/SIGINT handler triggers the same emergency path (GKE and TPU
  maintenance events deliver SIGTERM with a grace window);
- the fault-injection seam is preserved (``simulate_interruption`` — parity
  with ``_simulate_interruption``, ``spot_resiliency.py:39-41``) so tests can
  drive the full emergency path without a cloud;
- the emergency callback is supplied by the supervisor: synchronous Orbax
  save → mark job preempted → (optionally) exit. Auto-resume on restart is
  the supervisor's side (``tpu_engine/supervisor.py``).

Cloud scope — GCP ONLY, deliberately. The reference stub's comments cite
both the AWS instance-action URL and the GCP preempted URL
(``spot_resiliency.py:25-29``); TPUs exist only in Google Cloud, so this
TPU-native build polls the GCE endpoint and does not carry a dead AWS
code path. Non-GCE environments (including any future AWS-hosted
runtime) are still covered by the SIGTERM handler — every major cloud
delivers spot/maintenance interruptions as SIGTERM with a grace window —
and by the simulation seam for tests.
"""

from __future__ import annotations

import logging
import signal
import threading
import urllib.request
from typing import Callable, Optional

log = logging.getLogger(__name__)

GCE_PREEMPTION_URL = (
    "http://metadata.google.internal/computeMetadata/v1/instance/preempted"
)


def probe_gce_preempted(timeout: float = 1.0) -> Optional[bool]:
    """One metadata probe, hardened against every request failure mode.

    Returns True/False from a successful read, or **None** when the probe
    could not determine anything: no route / DNS failure / connection
    refused / socket timeout / HTTP error status / undecodable body. The
    tri-state matters — callers distinguish "not preempted" from "metadata
    server unreachable", which is what drives the watcher's backoff so a
    flapping endpoint can't spin the poll loop at full rate.
    """
    req = urllib.request.Request(
        GCE_PREEMPTION_URL, headers={"Metadata-Flavor": "Google"}
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            status = getattr(resp, "status", 200)
            if status is not None and not (200 <= int(status) < 300):
                return None
            body = resp.read(64)
    except Exception:  # URLError, timeout, OSError, anything urllib raises
        return None
    try:
        return body.decode("utf-8", "replace").strip().upper() == "TRUE"
    except Exception:
        return None


def check_gce_preempted(timeout: float = 1.0) -> bool:
    """Poll the GCE metadata server; False on any error (not on GCE, etc.)."""
    return probe_gce_preempted(timeout) is True


class PreemptionWatcher:
    """Background preemption monitor with a fault-injection seam.

    ``on_preemption`` is called exactly once, from the watcher thread (or the
    signal handler's main thread), when any of these fire:
    metadata says preempted · ``simulate_interruption()`` set · SIGTERM/SIGINT.
    """

    def __init__(
        self,
        on_preemption: Callable[[str], None],
        check_interval_s: float = 5.0,  # reference poll interval, spot_resiliency.py:13
        install_signal_handlers: bool = False,
        metadata_check: Optional[Callable[[], Optional[bool]]] = probe_gce_preempted,
        max_backoff_s: float = 60.0,
    ):
        self.on_preemption = on_preemption
        self.check_interval_s = check_interval_s
        self.metadata_check = metadata_check
        self.max_backoff_s = max_backoff_s
        #: consecutive probe failures (None result or raised exception) —
        #: drives exponential backoff; reset on any successful probe.
        self.metadata_failures = 0
        self._install_signals = install_signal_handlers
        self._simulated = threading.Event()
        self._stop = threading.Event()
        self._fired = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._prev_handlers: dict[int, object] = {}

    # -- fault injection seam (parity with _simulate_interruption :39-41) ----

    def simulate_interruption(self) -> None:
        """Inject a preemption notice (test seam)."""
        self._simulated.set()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        if self._install_signals:
            for sig in (signal.SIGTERM, signal.SIGINT):
                try:
                    self._prev_handlers[sig] = signal.signal(sig, self._signal_handler)
                except ValueError:
                    pass  # not on main thread; metadata/simulated paths still work
        self._thread = threading.Thread(target=self._loop, daemon=True, name="preemption-watcher")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.check_interval_s + 2)
        for sig, handler in self._prev_handlers.items():
            try:
                signal.signal(sig, handler)  # type: ignore[arg-type]
            except (ValueError, TypeError):
                pass

    @property
    def fired(self) -> bool:
        return self._fired.is_set()

    # -- internals -----------------------------------------------------------

    def _signal_handler(self, signum, frame) -> None:
        log.warning("received signal %s — triggering emergency checkpoint", signum)
        self._fire(f"signal:{signal.Signals(signum).name}")

    def _fire(self, reason: str) -> None:
        if self._fired.is_set():
            return
        self._fired.set()
        try:
            self.on_preemption(reason)
        except Exception:
            log.exception("preemption callback failed")

    def _poll_once(self) -> Optional[str]:
        """One watcher tick → fire reason, or None to keep waiting.

        A raising ``metadata_check`` must NOT kill the watcher thread (it
        used to — an exception here silently disabled preemption handling
        for the rest of the job); raised exceptions count as probe failures
        and feed the same backoff as a None result.
        """
        if self._simulated.is_set():
            return "simulated"
        if self.metadata_check is None:
            return None
        try:
            result = self.metadata_check()
        except Exception:
            log.exception("metadata preemption check raised; backing off")
            result = None
        if result is None:
            self.metadata_failures += 1
        else:
            self.metadata_failures = 0
            if result:
                return "gce-metadata"
        return None

    def _wait_s(self) -> float:
        """Poll interval with exponential backoff while the probe is failing."""
        backoff = self.check_interval_s * (2 ** min(self.metadata_failures, 20))
        return min(backoff, max(self.max_backoff_s, self.check_interval_s))

    def _loop(self) -> None:
        while not self._stop.is_set():
            reason = self._poll_once()
            if reason is not None:
                self._fire(reason)
                return
            self._stop.wait(self._wait_s())
