"""Fleet historian: bounded metric history + causal incident correlation.

The observability plane before this module was rich but amnesiac — the
flight recorder (``tracing.py``), the goodput ledger (``goodput.py``) and
~120 Prometheus families all answer "what is the fleet doing *now*",
while the SLO burn-rate alerter and the step-time anomaly detector each
kept their own private sample windows. This module is the shared memory
those consumers (and PR 15's fleet autopilot) read instead:

- :class:`MetricHistorian` — an embedded multi-resolution time-series
  store. Raw samples land in a bounded per-series ring; every sample is
  simultaneously folded into 10s and 1m downsampled rollup buckets
  (count/sum/min/max/first/last — the tiers *conserve* the raw ring's
  sum/min/max by construction) under configurable retention. A small
  query engine answers range queries (``avg``/``min``/``max``/``last``/
  ``sum``/``count``/``rate``/``p99``) against whichever tier still
  covers the window. Every write takes an explicit timestamp, so
  virtual-clock sims and the digital twin record exactly like live
  processes — and replaying a recorded trace rebuilds the same store.

- :class:`IncidentCorrelator` — stitches recorder activity that overlaps
  in time into causally-linked incidents: ``FaultEvent`` mirrors and
  ``detect`` spans open an incident; scheduler/admission actions
  (preempt, requeue, shrink-admit, grow-back, rebalance) attach through
  the recorder's parent links (or, for unlinked live events, through
  trace/time adjacency); ``resume``/``grow_back``/alert-resolve records
  resolve it. Each incident carries a timeline (detect → action →
  resolution), the implicated device/trace, and — via the historian —
  metric-series snippets around its window.

Both are pure stdlib with no imports from the rest of ``tpu_engine``, so
every other layer (tracing, goodput, faults, scheduler, supervisor,
twin, routers) can depend on them without cycles.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

__all__ = [
    "MetricHistorian",
    "IncidentCorrelator",
    "Incident",
    "percentile",
    "get_historian",
    "set_historian",
    "get_correlator",
    "set_correlator",
]

#: (bucket width seconds, max retained buckets) — 10s tier holds 2 h,
#: 1m tier holds 24 h by default.
DEFAULT_TIERS: Tuple[Tuple[float, int], ...] = ((10.0, 720), (60.0, 1440))

AGGS = ("avg", "min", "max", "last", "sum", "count", "rate", "p99")

# Bucket list layout (kept as a plain list for memory, not a dataclass):
# [count, sum, min, max, first_ts, first, last_ts, last]
_B_COUNT, _B_SUM, _B_MIN, _B_MAX, _B_FTS, _B_FIRST, _B_LTS, _B_LAST = range(8)


def percentile(values: List[float], q: float) -> float:
    """Linear-interpolated percentile (same convention as ``twin.percentile``)."""
    vs = sorted(values)
    if not vs:
        return 0.0
    idx = (len(vs) - 1) * q
    lo = int(idx)
    hi = min(lo + 1, len(vs) - 1)
    return vs[lo] + (vs[hi] - vs[lo]) * (idx - lo)


def _series_key(name: str, labels: Optional[Dict[str, Any]]) -> tuple:
    if not labels:
        return (name, ())
    return (name, tuple(sorted((str(k), str(v)) for k, v in labels.items())))


class _Series:
    __slots__ = ("name", "labels", "raw", "tiers", "last_ts")

    def __init__(
        self,
        name: str,
        labels: Dict[str, str],
        raw_capacity: int,
        tiers: Tuple[Tuple[float, int], ...],
    ):
        self.name = name
        self.labels = labels
        self.raw: deque = deque(maxlen=raw_capacity)  # (ts, value)
        # width_s -> OrderedDict[bucket_idx -> bucket list]
        self.tiers: Dict[float, OrderedDict] = {w: OrderedDict() for w, _ in tiers}
        self.last_ts: Optional[float] = None


class MetricHistorian:
    """Embedded, bounded, multi-resolution time-series store.

    Memory is bounded three ways: the raw ring per series
    (``raw_capacity`` samples), the rollup tiers per series
    (``tiers[i][1]`` buckets each), and the series registry itself
    (``max_series``, least-recently-written evicted). Writes take an
    explicit ``ts`` so virtual-clock callers never touch the wall clock;
    ``clock`` is only consulted when ``ts`` is omitted.
    """

    def __init__(
        self,
        raw_capacity: int = 4096,
        tiers: Tuple[Tuple[float, int], ...] = DEFAULT_TIERS,
        max_series: int = 512,
        clock: Optional[Callable[[], float]] = None,
    ):
        self._lock = threading.RLock()
        self.raw_capacity = int(raw_capacity)
        self.tiers = tuple((float(w), int(n)) for w, n in tiers)
        self.max_series = int(max_series)
        self.clock = clock or time.time
        self._series: "OrderedDict[tuple, _Series]" = OrderedDict()
        self._collectors: List[Callable[[float], Any]] = []
        self.samples_total = 0
        self.ticks_total = 0
        self.series_evicted_total = 0
        self.bucket_evictions_total = 0
        self.collector_errors_total = 0
        # Batched-ingest efficiency counters: how many lock acquisitions
        # the batch path saved is (batched_samples - batches).
        self.ingest_batch_total = 0
        self.ingest_batched_samples_total = 0

    # -- writes --------------------------------------------------------------

    def _record_locked(
        self,
        name: str,
        value: float,
        ts: float,
        labels: Optional[Dict[str, Any]],
    ) -> None:
        """Fold one (validated, floated) sample into the raw ring and every
        rollup tier. Caller holds ``self._lock``."""
        key = _series_key(name, labels)
        s = self._series.get(key)
        if s is None:
            s = _Series(
                name,
                {str(k): str(v) for k, v in (labels or {}).items()},
                self.raw_capacity,
                self.tiers,
            )
            self._series[key] = s
            while len(self._series) > self.max_series:
                self._series.popitem(last=False)
                self.series_evicted_total += 1
        else:
            self._series.move_to_end(key)
        s.raw.append((ts, value))
        s.last_ts = ts if s.last_ts is None else max(s.last_ts, ts)
        for (width, max_buckets) in self.tiers:
            od = s.tiers[width]
            idx = int(ts // width)
            b = od.get(idx)
            if b is None:
                od[idx] = [1, value, value, value, ts, value, ts, value]
                while len(od) > max_buckets:
                    od.popitem(last=False)
                    self.bucket_evictions_total += 1
            else:
                b[_B_COUNT] += 1
                b[_B_SUM] += value
                if value < b[_B_MIN]:
                    b[_B_MIN] = value
                if value > b[_B_MAX]:
                    b[_B_MAX] = value
                if ts < b[_B_FTS]:
                    b[_B_FTS], b[_B_FIRST] = ts, value
                if ts >= b[_B_LTS]:
                    b[_B_LTS], b[_B_LAST] = ts, value
        self.samples_total += 1

    def record(
        self,
        name: str,
        value: float,
        ts: Optional[float] = None,
        labels: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Append one sample; folds into the raw ring and every rollup tier."""
        if value is None or not isinstance(value, (int, float)):
            return
        ts = self.clock() if ts is None else float(ts)
        with self._lock:
            self._record_locked(name, float(value), ts, labels)

    def observe_batch(
        self,
        samples: Any,
        ts: Optional[float] = None,
        labels: Optional[Dict[str, Any]] = None,
    ) -> int:
        """Batched ingest: one lock acquisition for the whole batch, then
        the same raw+rollup fold per sample as :meth:`record`.

        ``samples`` is a mapping ``name → value`` or an iterable of
        ``(name, value)`` / ``(name, value, labels)`` tuples (the tuple
        form carries per-sample labels; the ``labels`` argument is the
        default). Non-numeric values are skipped exactly as
        :meth:`record` skips them. Returns the number of samples
        retained. This is the hot-path entry: at control-plane scale the
        per-sample lock round-trip in ``record`` dominated ingest cost,
        so :meth:`record_many` and :meth:`tick` both route through here.
        """
        ts = self.clock() if ts is None else float(ts)
        if isinstance(samples, dict):
            items: List[Tuple[str, Any, Optional[Dict[str, Any]]]] = [
                (name, value, labels) for name, value in samples.items()
            ]
        else:
            items = [
                (it[0], it[1], it[2] if len(it) > 2 else labels)
                for it in samples
            ]
        n = 0
        with self._lock:
            for name, value, lab in items:
                if value is None or not isinstance(value, (int, float)):
                    continue
                self._record_locked(name, float(value), ts, lab)
                n += 1
            self.ingest_batch_total += 1
            self.ingest_batched_samples_total += n
        return n

    def record_many(
        self,
        samples: Dict[str, float],
        ts: Optional[float] = None,
        labels: Optional[Dict[str, Any]] = None,
    ) -> None:
        self.observe_batch(samples, ts=ts, labels=labels)

    # -- scrape tick ---------------------------------------------------------

    def add_collector(self, fn: Callable[[float], Any]) -> None:
        """Register ``fn(now) -> {name: value} | [(name, value, labels)]``;
        every :meth:`tick` runs it and retains what it returns."""
        with self._lock:
            self._collectors.append(fn)

    def tick(self, now: Optional[float] = None) -> int:
        """One scrape tick: run every registered collector at an explicit
        timestamp. Returns the number of samples retained; collector
        failures are counted, never raised (a broken collector must not
        break the scrape path that drives the tick)."""
        now = self.clock() if now is None else float(now)
        recorded = 0
        with self._lock:
            collectors = list(self._collectors)
        # Collectors run outside the lock (they may be arbitrarily slow);
        # their combined output lands through ONE batched fold.
        batch: List[Tuple[str, Any, Optional[Dict[str, Any]]]] = []
        for fn in collectors:
            try:
                out = fn(now)
            except Exception:
                self.collector_errors_total += 1
                continue
            if not out:
                continue
            if isinstance(out, dict):
                for name, value in out.items():
                    batch.append((name, value, None))
                    recorded += 1
            else:
                for name, value, labels in out:
                    batch.append((name, value, labels))
                    recorded += 1
        if batch:
            self.observe_batch(batch, ts=now)
        with self._lock:
            self.ticks_total += 1
        return recorded

    # -- queries -------------------------------------------------------------

    def _get(self, name: str, labels: Optional[Dict[str, Any]]) -> Optional[_Series]:
        return self._series.get(_series_key(name, labels))

    def raw_len(self, name: str, labels: Optional[Dict[str, Any]] = None) -> int:
        with self._lock:
            s = self._get(name, labels)
            return len(s.raw) if s is not None else 0

    def last_n(
        self, name: str, n: int, labels: Optional[Dict[str, Any]] = None
    ) -> List[float]:
        """Values of the most recent ``n`` raw samples (count-based window)."""
        with self._lock:
            s = self._get(name, labels)
            if s is None:
                return []
            n = max(0, int(n))
            return [v for _, v in list(s.raw)[-n:]] if n else []

    def _pick_tier(self, s: _Series, t0: float) -> Optional[float]:
        """Finest rollup tier whose retained buckets still cover ``t0``."""
        for (width, _) in self.tiers:
            od = s.tiers[width]
            if od and next(iter(od)) * width <= t0:
                return width
        return self.tiers[-1][0] if self.tiers else None

    def query(
        self,
        name: str,
        t0: Optional[float] = None,
        t1: Optional[float] = None,
        agg: str = "avg",
        labels: Optional[Dict[str, Any]] = None,
        tier: str = "auto",
        max_points: int = 512,
    ) -> Dict[str, Any]:
        """Range query. ``tier`` is ``raw``/``10s``/``1m``/``auto``; auto
        serves from raw when the ring still covers the window start and
        falls back to the finest rollup tier that does. ``p99`` and
        ``rate`` always derive from the raw points inside the window
        (percentiles don't survive downsampling); when raw no longer
        covers the window, ``p99`` degrades to the bucket max (an upper
        bound) and the result is marked ``approx``."""
        if agg not in AGGS:
            raise ValueError(f"unknown agg {agg!r}; one of {AGGS}")
        with self._lock:
            s = self._get(name, labels)
            empty = {
                "name": name, "labels": dict(labels or {}), "agg": agg,
                "tier": tier, "t0": t0, "t1": t1, "value": None, "count": 0,
                "aggregates": {}, "points": [], "approx": False,
            }
            if s is None or (not s.raw and not any(s.tiers[w] for w, _ in self.tiers)):
                return empty
            if t1 is None:
                t1 = s.last_ts if s.last_ts is not None else self.clock()
            if t0 is None:
                t0 = t1 - 600.0
            t0, t1 = float(t0), float(t1)
            raw_pts = [(ts, v) for ts, v in s.raw if t0 <= ts <= t1]
            raw_covers = bool(s.raw) and (
                len(s.raw) < s.raw.maxlen or s.raw[0][0] <= t0
            )
            chosen = tier
            if tier == "auto":
                chosen = "raw" if raw_covers else None
            if chosen == "raw":
                count = len(raw_pts)
                total = sum(v for _, v in raw_pts)
                aggs: Dict[str, Any] = {
                    "count": count,
                    "sum": total,
                    "avg": (total / count) if count else None,
                    "min": min((v for _, v in raw_pts), default=None),
                    "max": max((v for _, v in raw_pts), default=None),
                    "last": raw_pts[-1][1] if raw_pts else None,
                }
                points = raw_pts
                approx = False
            else:
                if chosen in (None, "auto"):
                    width = self._pick_tier(s, t0)
                else:
                    width = {"10s": 10.0, "1m": 60.0}.get(chosen)
                    if width is None:
                        try:
                            width = float(chosen)
                        except (TypeError, ValueError):
                            raise ValueError(f"unknown tier {tier!r}")
                if width is None:
                    return empty
                chosen = {10.0: "10s", 60.0: "1m"}.get(width, str(width))
                bs = [
                    b for idx, b in s.tiers[width].items()
                    if idx * width < t1 and (idx + 1) * width > t0
                ]
                count = sum(b[_B_COUNT] for b in bs)
                total = sum(b[_B_SUM] for b in bs)
                aggs = {
                    "count": count,
                    "sum": total,
                    "avg": (total / count) if count else None,
                    "min": min((b[_B_MIN] for b in bs), default=None),
                    "max": max((b[_B_MAX] for b in bs), default=None),
                    "last": bs[-1][_B_LAST] if bs else None,
                }
                points = [
                    (b[_B_LTS], b[_B_SUM] / b[_B_COUNT]) for b in bs
                ]
                approx = True
            if agg == "rate":
                src = raw_pts if raw_pts else points
                if len(src) >= 2 and src[-1][0] > src[0][0]:
                    value: Any = (src[-1][1] - src[0][1]) / (src[-1][0] - src[0][0])
                else:
                    value = None
            elif agg == "p99":
                if raw_pts:
                    value = percentile([v for _, v in raw_pts], 0.99)
                else:
                    value, approx = aggs["max"], True
            else:
                value = aggs[agg]
            return {
                "name": name,
                "labels": dict(s.labels),
                "agg": agg,
                "tier": chosen,
                "t0": t0,
                "t1": t1,
                "value": value,
                "count": aggs["count"],
                "aggregates": aggs,
                "points": [[ts, v] for ts, v in points[-max(0, int(max_points)):]],
                "approx": approx,
            }

    def buckets(
        self, name: str, width_s: float, labels: Optional[Dict[str, Any]] = None
    ) -> List[Dict[str, float]]:
        """The retained rollup buckets of one tier (for invariant checks)."""
        with self._lock:
            s = self._get(name, labels)
            if s is None:
                return []
            od = s.tiers.get(float(width_s))
            if od is None:
                return []
            return [
                {
                    "t0": idx * float(width_s),
                    "width_s": float(width_s),
                    "count": b[_B_COUNT],
                    "sum": b[_B_SUM],
                    "min": b[_B_MIN],
                    "max": b[_B_MAX],
                    "first": b[_B_FIRST],
                    "last": b[_B_LAST],
                }
                for idx, b in od.items()
            ]

    def series_list(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                {
                    "name": s.name,
                    "labels": dict(s.labels),
                    "raw_samples": len(s.raw),
                    "last_ts": s.last_ts,
                }
                for s in self._series.values()
            ]

    # -- ingestion from recorder / JSONL --------------------------------------

    def ingest_counter_events(self, events: Iterable[Dict[str, Any]]) -> int:
        """Fold recorder ``kind="counter"`` events into series: each numeric
        attr of a counter named ``N`` becomes a sample of series ``N.attr``
        at the event's timestamp. Replaying a recorded JSONL through this
        rebuilds the live run's series exactly (same explicit timestamps)."""
        n = 0
        for ev in events:
            if ev.get("kind") != "counter":
                continue
            ts = ev.get("ts")
            name = ev.get("name")
            if ts is None or not name:
                continue
            for k, v in (ev.get("attrs") or {}).items():
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    self.record(f"{name}.{k}", float(v), ts=float(ts))
                    n += 1
        return n

    def ingest_jsonl_records(self, records: Iterable[Dict[str, Any]]) -> int:
        """Same, over raw flight-recorder JSONL records (``record="event"``)."""
        return self.ingest_counter_events(
            r for r in records if r.get("record") == "event"
        )

    # -- export ---------------------------------------------------------------

    def export_chrome_counters(
        self,
        names: Optional[List[str]] = None,
        t0: Optional[float] = None,
        t1: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Any queried series as Perfetto counter tracks (``ph="C"``), the
        same rendering ``FlightRecorder.export_chrome_trace`` gives its
        own counter events — so a historian range query drops straight
        into the Perfetto UI next to the span lanes that explain it."""
        out: List[Dict[str, Any]] = []
        with self._lock:
            series = list(self._series.values())
        for s in series:
            if names is not None and s.name not in names:
                continue
            label = s.name
            if s.labels:
                label += "{" + ",".join(
                    f"{k}={v}" for k, v in sorted(s.labels.items())
                ) + "}"
            for ts, v in list(s.raw):
                if t0 is not None and ts < t0:
                    continue
                if t1 is not None and ts > t1:
                    continue
                out.append(
                    {
                        "name": label,
                        "cat": "counter",
                        "ph": "C",
                        "ts": ts * 1e6,
                        "pid": 0,
                        "tid": 0,
                        "args": {"value": v},
                    }
                )
        out.sort(key=lambda ev: ev["ts"])
        return {
            "traceEvents": out,
            "displayTimeUnit": "ms",
            "otherData": {"exporter": "tpu_engine.historian"},
        }

    # -- health ----------------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            raw = sum(len(s.raw) for s in self._series.values())
            buckets = {
                {10.0: "10s", 60.0: "1m"}.get(w, str(w)): sum(
                    len(s.tiers[w]) for s in self._series.values()
                )
                for w, _ in self.tiers
            }
            # Rough but monotone-with-reality: a raw sample is a 2-tuple of
            # floats, a bucket an 8-slot list, a series the fixed overhead.
            est = raw * 72 + sum(buckets.values()) * 144 + len(self._series) * 512
            return {
                "series": len(self._series),
                "samples_total": self.samples_total,
                "raw_samples": raw,
                "rollup_buckets": buckets,
                "ticks_total": self.ticks_total,
                "series_evicted_total": self.series_evicted_total,
                "bucket_evictions_total": self.bucket_evictions_total,
                "collector_errors_total": self.collector_errors_total,
                "ingest_batch_total": self.ingest_batch_total,
                "ingest_batched_samples_total": self.ingest_batched_samples_total,
                "estimated_bytes": est,
                "raw_capacity": self.raw_capacity,
                "max_series": self.max_series,
            }


# ---------------------------------------------------------------------------
# Incident correlation
# ---------------------------------------------------------------------------

#: Trigger kinds: records in these kinds open an incident when nothing
#: existing claims them.
_TRIGGER_KINDS = ("fault", "anomaly", "slo_alert")
#: Action kinds: attach to an incident (via parent link or adjacency) and
#: move it to ``mitigating``. ``autopilot`` spans are the control loop's
#: DecisionRecord mirrors (``tpu_engine/autopilot.py``).
_ACTION_KINDS = (
    "scheduler", "admission", "emergency_save", "compile", "hetero",
    "autopilot",
)
#: Records that resolve an incident.
_RESOLUTION_NAMES = ("resume", "grow_back", "hetero_quarantine_release")


def _classify(kind: str, name: str, attrs: Dict[str, Any]) -> Optional[str]:
    """Map one recorder record to a timeline role (None = not of interest)."""
    if kind == "slo_alert":
        return "resolution" if attrs.get("transition") == "resolve" else "detect"
    if kind in ("fault", "anomaly"):
        return "detect"
    if kind == "supervisor" and "resume" in name:
        return "resolution"
    if name in _RESOLUTION_NAMES:
        return "resolution"
    if kind in _ACTION_KINDS:
        return "action"
    return None


class Incident:
    """One causally-linked incident: trigger, timeline, resolution state."""

    __slots__ = (
        "incident_id", "trigger", "t0", "t1", "state", "trace_id",
        "device_index", "submission_id", "slo", "timeline",
    )

    def __init__(self, incident_id: str, trigger: str, rec: Dict[str, Any]):
        self.incident_id = incident_id
        self.trigger = trigger
        self.t0 = rec["ts"]
        self.t1 = rec["ts"]
        self.state = "open"
        self.trace_id = rec.get("trace_id")
        attrs = rec.get("attrs") or {}
        self.device_index = attrs.get("device") if attrs.get(
            "device"
        ) is not None else attrs.get("device_index")
        self.submission_id = attrs.get("submission_id")
        self.slo = attrs.get("slo")
        self.timeline: List[Dict[str, Any]] = []

    def add(self, role: str, rec: Dict[str, Any]) -> None:
        attrs = rec.get("attrs") or {}
        entry = {
            "ts": rec["ts"],
            "role": role,
            "kind": rec["kind"],
            "name": rec["name"],
            "attrs": dict(attrs),
        }
        if role == "action":
            # Who acted: autopilot decision mirrors carry their own
            # source (``autopilot`` | ``autopilot-dryrun``); every other
            # action leg is human-operated machinery.
            entry["action_source"] = attrs.get("action_source") or "human"
        self.timeline.append(entry)
        self.t1 = max(self.t1, rec.get("t_end") or rec["ts"])
        if self.device_index is None:
            d = attrs.get("device", attrs.get("device_index"))
            if d is not None:
                self.device_index = d
        if self.submission_id is None and attrs.get("submission_id") is not None:
            self.submission_id = attrs.get("submission_id")

    def roles(self) -> List[str]:
        return [e["role"] for e in self.timeline]

    def to_dict(
        self,
        historian: Optional[MetricHistorian] = None,
        snippet_series: Optional[List[str]] = None,
        snippet_pad_s: float = 60.0,
        max_points: int = 50,
    ) -> Dict[str, Any]:
        out = {
            "incident_id": self.incident_id,
            "trigger": self.trigger,
            "state": self.state,
            "t0": self.t0,
            "t1": self.t1,
            "duration_s": round(self.t1 - self.t0, 6),
            "trace_id": self.trace_id,
            "device_index": self.device_index,
            "submission_id": self.submission_id,
            "slo": self.slo,
            "timeline": list(self.timeline),
        }
        if historian is not None:
            names = snippet_series or [
                info["name"] for info in historian.series_list()
            ][:4]
            snippets = {}
            for name in names:
                q = historian.query(
                    name,
                    t0=self.t0 - snippet_pad_s,
                    t1=self.t1 + snippet_pad_s,
                    agg="avg",
                    max_points=max_points,
                )
                if q["count"]:
                    snippets[name] = {
                        "aggregates": q["aggregates"], "points": q["points"],
                    }
            out["metric_snippets"] = snippets
        return out


class IncidentCorrelator:
    """Stitches recorder spans/events into bounded incident objects.

    Attachment precedence per record: (1) walk the span parent chain —
    the recorder's causal links are ground truth; (2) for detect-class
    records, merge into a same-device incident within ``merge_window_s``
    (dedups the live double-record: a ``detect`` span plus the
    ``FaultEvent`` mirror at the same instant) or a same-SLO open alert
    incident; (3) for action/resolution records with no parent link
    (live scheduler events are not parented to faults), attach to the
    most recent open incident on the same trace — or any open incident —
    within ``attach_gap_s``. Anything unclaimed and non-triggering is
    ignored, counted.
    """

    def __init__(
        self,
        max_incidents: int = 256,
        merge_window_s: float = 0.25,
        attach_gap_s: float = 120.0,
        stale_after_s: float = 900.0,
        clock: Optional[Callable[[], float]] = None,
    ):
        self._lock = threading.RLock()
        self.max_incidents = int(max_incidents)
        self.merge_window_s = float(merge_window_s)
        self.attach_gap_s = float(attach_gap_s)
        self.stale_after_s = float(stale_after_s)
        self.clock = clock or time.time
        self._seen: set = set()
        self._seen_order: deque = deque(maxlen=65536)
        self._record_to_incident: Dict[str, Incident] = {}
        self._parents: Dict[str, Optional[str]] = {}
        self._open: List[Incident] = []
        self._closed: deque = deque(maxlen=self.max_incidents)
        self._seq = 0
        self.opened_by_trigger: Dict[str, int] = {}
        self.resolved_total = 0
        self.correlated_total = 0
        self.ignored_total = 0

    # -- normalization --------------------------------------------------------

    @staticmethod
    def _normalize(
        spans: Iterable[Dict[str, Any]], events: Iterable[Dict[str, Any]]
    ) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        for s in spans:
            out.append(
                {
                    "id": s["span_id"],
                    "ts": s["t0"],
                    "t_end": s.get("t1"),
                    "kind": s["kind"],
                    "name": s["name"],
                    "parent_id": s.get("parent_id"),
                    "trace_id": s.get("trace_id"),
                    "attrs": s.get("attrs") or {},
                }
            )
        for e in events:
            if e.get("kind") == "counter":
                continue
            out.append(
                {
                    "id": e["event_id"],
                    "ts": e["ts"],
                    "t_end": e["ts"],
                    "kind": e["kind"],
                    "name": e["name"],
                    "parent_id": e.get("parent_id"),
                    "trace_id": e.get("trace_id"),
                    "attrs": e.get("attrs") or {},
                }
            )
        # Stable by timestamp: chains recorded at one instant keep their
        # recording order (spans arrive t0-sorted from the recorder).
        out.sort(key=lambda r: r["ts"])
        return out

    @staticmethod
    def normalize_jsonl(records: Iterable[Dict[str, Any]]) -> List[Dict[str, Any]]:
        """Split raw flight-recorder JSONL records into (spans, events) and
        normalize — the twin replay path."""
        spans = [r for r in records if r.get("record") == "span"]
        events = [r for r in records if r.get("record") == "event"]
        return IncidentCorrelator._normalize(spans, events)

    # -- ingestion ------------------------------------------------------------

    def ingest(
        self,
        recorder: Any = None,
        records: Optional[List[Dict[str, Any]]] = None,
        now: Optional[float] = None,
    ) -> int:
        """Pull new activity and stitch it. ``recorder`` is any object with
        the FlightRecorder query surface; ``records`` is raw JSONL
        records (twin replay). Idempotent: records are deduped by id."""
        if recorder is not None:
            normalized = self._normalize(
                recorder.spans(limit=0, include_open=False),
                recorder.events(limit=0),
            )
        elif records is not None:
            normalized = self.normalize_jsonl(records)
        else:
            normalized = []
        n = 0
        with self._lock:
            for rec in normalized:
                rid = rec["id"]
                if rid in self._seen:
                    continue
                self._note_seen(rid)
                self._parents[rid] = rec.get("parent_id")
                if len(self._parents) > 65536:
                    self._parents.pop(next(iter(self._parents)))
                if self._process(rec):
                    n += 1
            self._expire(self.clock() if now is None else float(now))
        return n

    def _note_seen(self, rid: str) -> None:
        if len(self._seen_order) == self._seen_order.maxlen:
            self._seen.discard(self._seen_order[0])
        self._seen_order.append(rid)
        self._seen.add(rid)

    def _process(self, rec: Dict[str, Any]) -> bool:
        role = _classify(rec["kind"], rec["name"], rec["attrs"])
        if role is None:
            return False
        inc = self._find_by_parent(rec)
        if inc is None and role == "detect":
            inc = self._find_mergeable(rec)
            if inc is None:
                inc = self._open_incident(rec)
        if inc is None and role in ("action", "resolution"):
            inc = self._find_adjacent(rec)
        if inc is None:
            self.ignored_total += 1
            return False
        inc.add(role, rec)
        self._record_to_incident[rec["id"]] = inc
        if len(self._record_to_incident) > 65536:
            self._record_to_incident.pop(next(iter(self._record_to_incident)))
        self.correlated_total += 1
        if role == "action" and inc.state == "open":
            inc.state = "mitigating"
        elif role == "resolution" and inc.state != "resolved":
            inc.state = "resolved"
            self.resolved_total += 1
            if inc in self._open:
                self._open.remove(inc)
                self._closed.append(inc)
        return True

    def _find_by_parent(self, rec: Dict[str, Any]) -> Optional[Incident]:
        p, hops = rec.get("parent_id"), 0
        while p and hops < 64:
            inc = self._record_to_incident.get(p)
            if inc is not None:
                return inc
            p = self._parents.get(p)
            hops += 1
        return None

    def _find_mergeable(self, rec: Dict[str, Any]) -> Optional[Incident]:
        attrs = rec["attrs"]
        if rec["kind"] == "slo_alert":
            slo = attrs.get("slo")
            for inc in reversed(self._open):
                if inc.trigger == "slo_alert" and inc.slo == slo:
                    return inc
            return None
        device = attrs.get("device", attrs.get("device_index"))
        for inc in self._all_recent():
            if (
                device is not None
                and inc.device_index == device
                and abs(rec["ts"] - inc.t1) <= self.merge_window_s
            ):
                return inc
        return None

    def _find_adjacent(self, rec: Dict[str, Any]) -> Optional[Incident]:
        tid = rec.get("trace_id")
        best = None
        for inc in reversed(self._open):
            if rec["ts"] - inc.t1 > self.attach_gap_s or rec["ts"] < inc.t0:
                continue
            if tid is not None and inc.trace_id == tid:
                return inc
            if best is None:
                best = inc
        return best

    def _all_recent(self) -> List[Incident]:
        return list(self._open) + list(self._closed)[-8:]

    def _open_incident(self, rec: Dict[str, Any]) -> Incident:
        self._seq += 1
        trigger = rec["kind"]
        inc = Incident(f"inc-{self._seq}", trigger, rec)
        self._open.append(inc)
        self.opened_by_trigger[trigger] = self.opened_by_trigger.get(trigger, 0) + 1
        return inc

    def _expire(self, now: float) -> None:
        for inc in list(self._open):
            if now - inc.t1 > self.stale_after_s:
                inc.state = "unresolved"
                self._open.remove(inc)
                self._closed.append(inc)

    # -- queries --------------------------------------------------------------

    def incidents(
        self,
        state: Optional[str] = None,
        limit: int = 50,
        historian: Optional[MetricHistorian] = None,
        snippet_series: Optional[List[str]] = None,
    ) -> List[Dict[str, Any]]:
        """Incidents newest-first, optionally filtered and with historian
        metric snippets around each window."""
        with self._lock:
            all_inc = list(self._closed) + list(self._open)
        all_inc.sort(key=lambda i: i.t0)
        if state is not None:
            all_inc = [i for i in all_inc if i.state == state]
        if limit:
            all_inc = all_inc[-max(0, int(limit)):]
        return [
            i.to_dict(historian=historian, snippet_series=snippet_series)
            for i in reversed(all_inc)
        ]

    def open_refs(self, limit: int = 8) -> List[Dict[str, Any]]:
        """Lightweight refs to open incidents, newest-first — the
        autopilot copies these ids into every DecisionRecord's inputs
        without paying for full timelines."""
        with self._lock:
            out = [
                {
                    "incident_id": inc.incident_id,
                    "trigger": inc.trigger,
                    "state": inc.state,
                    "t0": inc.t0,
                    "trace_id": inc.trace_id,
                    "device_index": inc.device_index,
                    "slo": inc.slo,
                }
                for inc in reversed(self._open)
            ]
        return out[: max(0, int(limit))] if limit else out

    def get(
        self, incident_id: str, historian: Optional[MetricHistorian] = None
    ) -> Optional[Dict[str, Any]]:
        with self._lock:
            for inc in list(self._open) + list(self._closed):
                if inc.incident_id == incident_id:
                    return inc.to_dict(historian=historian)
        return None

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "open": len(self._open),
                "opened_total": sum(self.opened_by_trigger.values()),
                "opened_by_trigger": dict(self.opened_by_trigger),
                "resolved_total": self.resolved_total,
                "correlated_total": self.correlated_total,
                "ignored_total": self.ignored_total,
            }


# ---------------------------------------------------------------------------
# Process-wide singletons (same pattern as goodput.get_ledger)
# ---------------------------------------------------------------------------

_historian: Optional[MetricHistorian] = None
_correlator: Optional[IncidentCorrelator] = None
_singleton_lock = threading.RLock()


def get_historian() -> MetricHistorian:
    global _historian
    with _singleton_lock:
        if _historian is None:
            _historian = MetricHistorian()
        return _historian


def set_historian(historian: Optional[MetricHistorian]) -> None:
    """Swap the process-wide historian (tests/sims install a fresh one)."""
    global _historian
    with _singleton_lock:
        _historian = historian


def get_correlator() -> IncidentCorrelator:
    global _correlator
    with _singleton_lock:
        if _correlator is None:
            _correlator = IncidentCorrelator()
        return _correlator


def set_correlator(correlator: Optional[IncidentCorrelator]) -> None:
    global _correlator
    with _singleton_lock:
        _correlator = correlator
