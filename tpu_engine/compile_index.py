"""Fleet compile-cache index: layout-keyed warm-start bookkeeping.

JAX's persistent compilation cache (``tpu_engine/compile_cache.py``) makes a
resume-after-preempt pay a cache *hit* instead of a cold XLA compile — but
the cache is invisible above the runtime: the scheduler cannot ask "is this
layout warm?" before it preempts a job into a resize, and the placement
planner ranks layouts as if compiles were free. This module is the fleet's
view of that cache:

- :class:`CompileCacheIndex` keys entries by (model config digest, layout
  label, jax/jaxlib version) — the layout label is exactly what
  :attr:`~tpu_engine.placement.PlacementPlan.label` encodes (mesh axes,
  sharding stage, pipeline schedule, quant/comm toggles) — records warm/cold
  outcomes from the supervisor's compile span, maintains a per-layout EMA of
  measured *cold* compile seconds, and persists a bounded JSON sidecar next
  to the XLA cache dir so warmth survives the process.
- ``is_warm(plan)`` / ``expected_compile_s(plan)`` feed the placement
  planner's ranking (equal-step-time layouts tie-break toward warm ones)
  and the scheduler's admission / grow-back decisions.
- :class:`PrecompileWorker` warms a target layout in the background (AOT
  lowering through the planner's existing seam, ``benchmarks/aot.py``) so
  grow-back preempts only once the destination mesh is warm — or a deadline
  lapses. Fault-injectable via the ``precompile-error`` kind in
  ``tpu_engine/faults.py``.

Consumers: ``PlacementPlanner`` (ranking), ``FleetScheduler`` (admission +
precompile-before-grow-back), the supervisor's compile span (feeds the
index), ``GET /api/v1/compile-cache`` and ``tpu_engine_compile_cache_*``
Prometheus families (observability).
"""

from __future__ import annotations

import collections
import hashlib
import json
import logging
import os
import threading
import time
from typing import Any, Callable, Optional

log = logging.getLogger(__name__)

# Sidecar file written next to (inside) the XLA persistent cache dir.
SIDECAR_NAME = "compile_index.json"
# Nominal cold XLA compile seconds when a layout has no measured EMA yet —
# the order of a real multi-minute TPU compile, pessimistic on purpose so
# an unknown-cold layout never out-ranks a measured-warm one for free.
DEFAULT_COLD_COMPILE_S = 90.0


class PrecompileError(RuntimeError):
    """A background precompile attempt failed (including injected faults)."""


# -- keying -------------------------------------------------------------------


def model_digest(config: Any) -> str:
    """Digest of the model-shape fields that change the compiled program.

    Mesh/schedule/quant live in the layout label; this digest covers what
    the label does not: which model, at what sequence length and precision.
    """
    parts = {
        "model": getattr(config, "model_name", None),
        "seq_len": getattr(config, "seq_len", None),
        "precision": str(getattr(config, "precision", None)),
        "micro": getattr(config, "micro_batch_size", None),
    }
    blob = json.dumps(parts, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


_runtime_fp: Optional[str] = None


def runtime_fingerprint() -> str:
    """jax/jaxlib (and libtpu when present) versions — a cache keyed for one
    runtime is cold for another; XLA itself keys the same way."""
    global _runtime_fp
    if _runtime_fp is None:
        try:
            import jax
            import jaxlib

            fp = f"jax{jax.__version__}-jaxlib{jaxlib.__version__}"
        except Exception:  # pragma: no cover - jax is a hard dep in-tree
            fp = "jax-unknown"
        try:
            from importlib import metadata

            for dist in ("libtpu", "libtpu-nightly"):
                try:
                    fp += f"-libtpu{metadata.version(dist)}"
                    break
                except metadata.PackageNotFoundError:
                    continue
        except Exception:
            pass
        _runtime_fp = fp
    return _runtime_fp


def layout_label(
    mesh: dict[str, int],
    sharding_stage: int,
    pipeline_schedule: str,
    quant_training: str = "none",
    comm_compress: bool = False,
) -> str:
    """The layout half of the key — byte-identical to
    :attr:`tpu_engine.placement.PlacementPlan.label` for the same layout."""
    axes = "x".join(
        f"{k}{v}" for k, v in mesh.items() if v > 1 and k != "dcn_data"
    ) or "data1"
    tags = [pipeline_schedule] if mesh.get("pipe", 1) > 1 else []
    if quant_training != "none":
        tags.append(quant_training)
    if comm_compress:
        tags.append("commq")
    return "·".join([axes, f"s{sharding_stage}", *tags])


def label_for_config(
    config: Any,
    mesh: Optional[Any] = None,
    gang: Optional[int] = None,
) -> str:
    """Layout label for a :class:`~tpu_engine.sharding.TPUTrainConfig`.

    ``mesh`` overrides the config's mesh (a :class:`MeshConfig` or a plain
    axis dict — the elastic-shrink path runs a different mesh than the one
    configured); ``gang`` resolves elastic ``data=-1`` axes.
    """
    from tpu_engine.sharding import resolve_pipeline_schedule

    m = mesh if mesh is not None else config.mesh
    if isinstance(m, dict):
        mesh_d = dict(m)
    else:
        if gang is None:
            gang = 1
            for v in (m.data, m.fsdp, m.pipe, m.sequence, m.model):
                gang *= max(int(v), 1)
        data, fsdp, pipe, seq_ax, model_ax = m.resolved_shape(gang)
        mesh_d = {
            "data": data, "fsdp": fsdp, "pipe": pipe,
            "sequence": seq_ax, "model": model_ax,
            "dcn_data": getattr(m, "dcn_data", 1),
        }
    return layout_label(
        mesh_d,
        int(config.sharding_stage),
        resolve_pipeline_schedule(config),
        quant_training=getattr(config, "quant_training", "none"),
        comm_compress=bool(
            getattr(config, "comm_quant_weights", False)
            or getattr(config, "comm_quant_grads", False)
        ),
    )


def index_key(label: str, config: Any) -> str:
    return f"{model_digest(config)}|{runtime_fingerprint()}|{label}"


def key_for_config(
    config: Any, mesh: Optional[Any] = None, gang: Optional[int] = None
) -> str:
    return index_key(label_for_config(config, mesh=mesh, gang=gang), config)


# -- index --------------------------------------------------------------------


class CompileCacheIndex:
    """Layout-keyed warm/cold ledger over the persistent XLA cache.

    Thread-safe; every mutation persists the sidecar (atomic rename) when a
    ``path`` is attached. Bounded: least-recently-used entries beyond
    ``max_entries`` are evicted — the sidecar can never grow without bound.
    """

    def __init__(
        self,
        path: Optional[str] = None,
        max_entries: int = 256,
        ema_alpha: float = 0.3,
        default_cold_s: float = DEFAULT_COLD_COMPILE_S,
        clock: Callable[[], float] = time.time,
    ):
        self.path = path
        self.max_entries = max_entries
        self.ema_alpha = ema_alpha
        self.default_cold_s = default_cold_s
        self.clock = clock
        self._lock = threading.Lock()
        self._entries: dict[str, dict[str, Any]] = {}
        self.hits_total = 0
        self.misses_total = 0
        self.records_total = 0
        self.cold_compile_s_total = 0.0
        self.evictions_total = 0
        self.persist_errors_total = 0
        self.sidecar_load_errors_total = 0
        self._global_cold_ema: Optional[float] = None
        if path:
            self._load()

    # -- keying helpers -------------------------------------------------------

    @staticmethod
    def key_for(config: Any, mesh: Any = None, gang: Optional[int] = None) -> str:
        return key_for_config(config, mesh=mesh, gang=gang)

    @staticmethod
    def key_for_plan(plan: Any) -> str:
        """Key for a :class:`~tpu_engine.placement.PlacementPlan` (which
        carries its fully-validated config)."""
        return index_key(plan.label, plan.config)

    def _resolve_key(self, key_or_plan: Any) -> str:
        if isinstance(key_or_plan, str):
            return key_or_plan
        return self.key_for_plan(key_or_plan)

    # -- queries --------------------------------------------------------------

    def is_warm(self, key_or_plan: Any) -> bool:
        key = self._resolve_key(key_or_plan)
        with self._lock:
            e = self._entries.get(key)
            return bool(e and e.get("warm"))

    def expected_cold_s(self, key_or_plan: Any) -> Optional[float]:
        """Measured cold-compile EMA for this layout (or the global EMA as a
        fallback); None when nothing has ever been measured."""
        key = self._resolve_key(key_or_plan)
        with self._lock:
            e = self._entries.get(key)
            if e and e.get("cold_ema_s"):
                return float(e["cold_ema_s"])
            return self._global_cold_ema

    def expected_compile_s(self, key_or_plan: Any) -> float:
        """Expected compile seconds the next admission of this layout pays:
        0 when warm, the cold EMA (global fallback, then the pessimistic
        default) when not."""
        key = self._resolve_key(key_or_plan)
        with self._lock:
            e = self._entries.get(key)
            if e and e.get("warm"):
                return 0.0
            if e and e.get("cold_ema_s"):
                return float(e["cold_ema_s"])
            if self._global_cold_ema is not None:
                return self._global_cold_ema
            return self.default_cold_s

    # -- recording ------------------------------------------------------------

    def record(
        self,
        key_or_plan: Any,
        compile_s: float,
        cache_hit: bool,
        label: str = "",
        model: str = "",
        via: str = "supervisor",
    ) -> dict[str, Any]:
        """One observed compile outcome. A cold observation updates the
        per-layout EMA; either outcome marks the layout warm (the XLA cache
        now holds its executable)."""
        key = self._resolve_key(key_or_plan)
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                e = {
                    "label": label, "model": model, "warm": False,
                    "cold_ema_s": None, "hits": 0, "misses": 0,
                    "last_compile_s": None, "last_via": via,
                    "last_used": 0.0,
                }
                self._entries[key] = e
            if label:
                e["label"] = label
            if model:
                e["model"] = model
            self.records_total += 1
            e["last_compile_s"] = round(float(compile_s), 6)
            e["last_via"] = via
            e["last_used"] = self.clock()
            if cache_hit:
                self.hits_total += 1
                e["hits"] += 1
            else:
                self.misses_total += 1
                e["misses"] += 1
                self.cold_compile_s_total += float(compile_s)
                prev = e.get("cold_ema_s")
                e["cold_ema_s"] = round(
                    float(compile_s) if prev is None
                    else (1 - self.ema_alpha) * prev + self.ema_alpha * float(compile_s),
                    6,
                )
                g = self._global_cold_ema
                self._global_cold_ema = (
                    float(compile_s) if g is None
                    else (1 - self.ema_alpha) * g + self.ema_alpha * float(compile_s)
                )
            e["warm"] = True
            self._evict_locked()
            snap = dict(e)
        self._persist()
        return snap

    def touch(self, key_or_plan: Any) -> None:
        """LRU bump without an outcome (a consult that led to admission)."""
        key = self._resolve_key(key_or_plan)
        with self._lock:
            e = self._entries.get(key)
            if e is not None:
                e["last_used"] = self.clock()

    def invalidate(self, key: Optional[str] = None) -> int:
        """Drop one entry (or all) — e.g. when the XLA cache dir is wiped."""
        with self._lock:
            if key is None:
                n = len(self._entries)
                self._entries.clear()
            else:
                n = 1 if self._entries.pop(key, None) is not None else 0
        self._persist()
        return n

    def _evict_locked(self) -> None:
        while len(self._entries) > self.max_entries:
            victim = min(
                self._entries, key=lambda k: self._entries[k].get("last_used", 0.0)
            )
            del self._entries[victim]
            self.evictions_total += 1

    # -- persistence ----------------------------------------------------------

    def attach_dir(self, cache_dir: str) -> None:
        """Point the sidecar at (inside) ``cache_dir`` and merge anything a
        previous process persisted there — called when the persistent XLA
        cache is enabled/re-pointed."""
        path = os.path.join(cache_dir, SIDECAR_NAME)
        with self._lock:
            if self.path == path:
                return
            self.path = path
        self._load(merge=True)
        self._persist()

    def _load(self, merge: bool = False) -> None:
        path = self.path
        if not path or not os.path.exists(path):
            return
        try:
            with open(path) as f:
                doc = json.load(f)
            if not isinstance(doc, dict):
                raise ValueError(f"sidecar is not a JSON object: {type(doc).__name__}")
            entries = doc.get("entries", {})
            if not isinstance(entries, dict):
                raise ValueError("sidecar 'entries' is not a JSON object")
            with self._lock:
                for k, v in entries.items():
                    if merge and k in self._entries:
                        continue
                    if isinstance(v, dict):
                        self._entries[k] = v
                g = doc.get("global_cold_ema_s")
                if g and self._global_cold_ema is None:
                    self._global_cold_ema = float(g)
                self._evict_locked()
        except Exception:
            # A torn/garbage sidecar (host died mid-write, disk corruption)
            # must never take the process down: warn, count, start fresh.
            with self._lock:
                self.sidecar_load_errors_total += 1
            log.warning("compile index sidecar unreadable: %s", path, exc_info=True)

    def _persist(self) -> None:
        path = self.path
        if not path:
            return
        with self._lock:
            doc = {
                "version": 1,
                "runtime": runtime_fingerprint(),
                "global_cold_ema_s": self._global_cold_ema,
                "entries": self._entries,
            }
            blob = json.dumps(doc, sort_keys=True)
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(blob)
            os.replace(tmp, path)
        except OSError:
            self.persist_errors_total += 1
            log.warning("compile index sidecar write failed: %s", path, exc_info=True)

    # -- views ----------------------------------------------------------------

    def entries(self) -> list[dict[str, Any]]:
        with self._lock:
            return [
                {"key": k, **v}
                for k, v in sorted(
                    self._entries.items(),
                    key=lambda kv: -kv[1].get("last_used", 0.0),
                )
            ]

    def stats(self) -> dict[str, Any]:
        with self._lock:
            entries = len(self._entries)
            warm = sum(1 for e in self._entries.values() if e.get("warm"))
            return {
                "entries": entries,
                "warm_entries": warm,
                "hits_total": self.hits_total,
                "misses_total": self.misses_total,
                "records_total": self.records_total,
                "cold_compile_s_total": round(self.cold_compile_s_total, 6),
                "global_cold_ema_s": (
                    round(self._global_cold_ema, 6)
                    if self._global_cold_ema is not None else None
                ),
                "evictions_total": self.evictions_total,
                "persist_errors_total": self.persist_errors_total,
                "sidecar_load_errors_total": self.sidecar_load_errors_total,
                "sidecar_path": self.path,
                "max_entries": self.max_entries,
            }


# -- background precompile ----------------------------------------------------


class PrecompileTask:
    """One background warm-up request (grow-back target, usually)."""

    __slots__ = (
        "key", "label", "config", "gang", "state", "requested_at",
        "started_at", "finished_at", "compile_s", "error",
    )

    def __init__(self, key: str, label: str, config: Any, gang: Optional[int], now: float):
        self.key = key
        self.label = label
        self.config = config
        self.gang = gang
        self.state = "queued"  # queued | running | warm | failed
        self.requested_at = now
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.compile_s: Optional[float] = None
        self.error: Optional[str] = None

    def describe(self) -> dict[str, Any]:
        return {
            "key": self.key,
            "label": self.label,
            "gang": self.gang,
            "state": self.state,
            "requested_at": self.requested_at,
            "started_at": self.started_at,
            "finished_at": self.finished_at,
            "compile_s": self.compile_s,
            "error": self.error,
        }


def _default_precompile(task: PrecompileTask) -> None:
    """AOT-lower-and-compile through the planner's existing seam
    (``benchmarks/aot.py``). Raises on CPU backends / unknown topologies —
    the worker degrades that to a failed task, and the grow-back deadline
    then proceeds cold, exactly as if no precompiler existed."""
    cfg = task.config
    if cfg is None:
        raise PrecompileError("no config attached to precompile task")
    import jax

    # Fail fast off-TPU: aot_lowered's topology discovery can stall for
    # minutes on hosts without libtpu (GCP metadata retries), which would
    # pin grow-backs against the deadline instead of degrading instantly.
    if jax.default_backend() == "cpu":
        raise PrecompileError("AOT precompile needs a TPU runtime (backend=cpu)")
    from benchmarks.aot import aot_lowered

    gang = task.gang or 1
    m = cfg.mesh
    data, fsdp, pipe, seq_ax, model_ax = m.resolved_shape(gang)
    lowered = aot_lowered(
        cfg.model_name,
        f"v5e-{gang}",
        {"data": data, "fsdp": fsdp, "pipe": pipe,
         "sequence": seq_ax, "model": model_ax},
        cfg.micro_batch_size,
        cfg.gradient_accumulation_steps,
        cfg.seq_len,
    )
    lowered.compile()


class PrecompileWorker:
    """Bounded background thread that warms layouts ahead of a resize.

    ``compile_fn(task)`` does the actual work — the default drives AOT
    lowering via ``benchmarks/aot.py``; tests and simulators inject a stub.
    Consults the process fault injector's ``precompile-error`` seam before
    every attempt, so chaos plans can break this path deterministically.

    With ``background=False`` no worker thread is ever spawned: requests
    queue and a caller drains them synchronously via :meth:`pump` — the
    autopilot's unified tick subsumes this worker that way, keeping the
    whole control loop single-threaded and virtual-clock-driven.
    """

    def __init__(
        self,
        index: CompileCacheIndex,
        compile_fn: Optional[Callable[[PrecompileTask], None]] = None,
        max_pending: int = 4,
        clock: Callable[[], float] = time.time,
        background: bool = True,
    ):
        self.index = index
        self.compile_fn = compile_fn or _default_precompile
        self.max_pending = max_pending
        self.clock = clock
        self.background = bool(background)
        self._lock = threading.Lock()
        self._tasks: dict[str, PrecompileTask] = {}
        self._queue: collections.deque[str] = collections.deque()
        self._wake = threading.Event()
        self._shutdown = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.started_total = 0
        self.completed_total = 0
        self.failed_total = 0
        self.rejected_total = 0

    def request(
        self,
        key: str,
        label: str = "",
        config: Any = None,
        gang: Optional[int] = None,
    ) -> str:
        """Ask for ``key`` to be warmed; returns the task state ("warm" when
        the index already has it, "rejected" when the bounded queue is
        full). Idempotent per key while a task is in flight."""
        if self.index.is_warm(key):
            return "warm"
        with self._lock:
            task = self._tasks.get(key)
            if task is not None and task.state in ("queued", "running"):
                return task.state
            pending = sum(
                1 for t in self._tasks.values() if t.state in ("queued", "running")
            )
            if pending >= self.max_pending:
                self.rejected_total += 1
                return "rejected"
            task = PrecompileTask(key, label, config, gang, self.clock())
            self._tasks[key] = task
            self._queue.append(key)
            # Bound the terminal-task history alongside the live queue.
            if len(self._tasks) > 4 * self.max_pending + 16:
                for k in [
                    k for k, t in self._tasks.items()
                    if t.state in ("warm", "failed")
                ][: len(self._tasks) - (4 * self.max_pending + 16)]:
                    del self._tasks[k]
        if self.background:
            self._ensure_thread()
            self._wake.set()
        return "queued"

    def status(self, key: str) -> Optional[str]:
        if self.index.is_warm(key):
            return "warm"
        with self._lock:
            task = self._tasks.get(key)
            return task.state if task is not None else None

    def queue_view(self) -> list[dict[str, Any]]:
        with self._lock:
            return [
                t.describe()
                for t in sorted(self._tasks.values(), key=lambda t: t.requested_at)
            ]

    def stats(self) -> dict[str, Any]:
        with self._lock:
            depth = sum(
                1 for t in self._tasks.values() if t.state in ("queued", "running")
            )
            return {
                "queue_depth": depth,
                "started_total": self.started_total,
                "completed_total": self.completed_total,
                "failed_total": self.failed_total,
                "rejected_total": self.rejected_total,
                "max_pending": self.max_pending,
            }

    def pump(self, max_tasks: Optional[int] = None) -> int:
        """Drain queued tasks inline on the caller's thread (the same
        locked pop as the background loop, so both modes can coexist).
        Returns the number of tasks run."""
        ran = 0
        while max_tasks is None or ran < max_tasks:
            with self._lock:
                key = self._queue.popleft() if self._queue else None
                task = self._tasks.get(key) if key else None
            if task is None:
                break
            self._run_one(task)
            ran += 1
        return ran

    def shutdown(self) -> None:
        self._shutdown.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    # -- internals ------------------------------------------------------------

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="precompile-worker"
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._shutdown.is_set():
            with self._lock:
                key = self._queue.popleft() if self._queue else None
                task = self._tasks.get(key) if key else None
            if task is None:
                self._wake.wait(timeout=0.05)
                self._wake.clear()
                continue
            self._run_one(task)

    def _run_one(self, task: PrecompileTask) -> None:
        task.state = "running"
        task.started_at = self.clock()
        with self._lock:
            self.started_total += 1
        from tpu_engine import faults

        try:
            inj = faults.get_active()
            if inj is not None and inj.take_precompile_fault(step=0):
                raise PrecompileError(
                    f"injected precompile-error for {task.label or task.key}"
                )
            t0 = self.clock()
            self.compile_fn(task)
            task.compile_s = max(self.clock() - t0, 0.0)
            self.index.record(
                task.key, task.compile_s, cache_hit=False,
                label=task.label,
                model=getattr(task.config, "model_name", "") or "",
                via="precompile",
            )
            task.state = "warm"
            task.finished_at = self.clock()
            with self._lock:
                self.completed_total += 1
            log.info(
                "precompile: warmed %s in %.2fs", task.label or task.key,
                task.compile_s,
            )
        except Exception as e:  # noqa: BLE001 — worker must survive anything
            task.state = "failed"
            task.error = f"{type(e).__name__}: {e}"
            task.finished_at = self.clock()
            with self._lock:
                self.failed_total += 1
            log.warning(
                "precompile: %s failed — %s (grow-back will proceed cold)",
                task.label or task.key, task.error,
            )


# -- process-wide index (the supervisor/scheduler/router default) -------------

_index: Optional[CompileCacheIndex] = None
_index_lock = threading.Lock()


def get_index() -> CompileCacheIndex:
    """The process compile index (created in-memory on first use; attaches
    its sidecar when/if the persistent XLA cache is enabled)."""
    global _index
    with _index_lock:
        if _index is None:
            _index = CompileCacheIndex()
        return _index


def set_index(index: Optional[CompileCacheIndex]) -> None:
    global _index
    with _index_lock:
        _index = index


def reset_index() -> None:
    set_index(None)
