"""Shared utilities: pytree helpers, structured logging, native-extension shims."""
