"""Input pipeline: tokenized datasets → sharded global batches.

The reference has no data pipeline at all — training data is the external
training script's problem (the launcher only passes script args,
``deepspeed_launcher.py:302-367``). A complete in-process engine owns its
input path:

- :class:`TokenFileDataset` — flat binary token files (uint16/int32), read
  through the native mmap+prefetch reader (``tpu_engine/native``) when the
  toolchain is available, else a NumPy memmap fallback with the same
  deterministic shuffle;
- :class:`SyntheticDataset` — deterministic random tokens (smoke/bench);
- :func:`make_data_fn` — adapts a dataset to the supervisor's ``data_fn``
  contract: ``step -> [accum, global_micro_batch, seq_len] int32`` placed
  with the program's batch sharding (single- and multi-process aware).
"""

from __future__ import annotations

import json
import os
import threading
from typing import Any, Callable, Optional

import jax
import numpy as np

from tpu_engine import native

_DTYPE_CODES = {"uint16": 2, "int32": 4}
_NP_DTYPES = {"uint16": np.uint16, "int32": np.int32}


def write_token_file(tokens: np.ndarray, path: str, dtype: str = "uint16") -> str:
    """Serialize a token array to the flat binary format both readers use.

    Values outside the target dtype's range are rejected rather than
    silently wrapped — in particular, SFT-masked streams
    (:func:`pack_sft_examples`) carry negative codes and must be written
    with ``dtype="int32"``; a uint16 cast would corrupt every masked
    position into a large positive token id with no error anywhere
    downstream.

    A 2-D ``[n, row_len]`` array (row-structured data — packed SFT
    examples) additionally writes a ``<path>.meta.json`` sidecar recording
    the row length: rows are only meaningful if the training config slices
    the stream at exactly that seq_len, and :class:`TokenFileDataset`
    enforces the sidecar at open time instead of silently misaligning
    masks (round-1 advisor finding).
    """
    arr = np.asarray(tokens)
    info = np.iinfo(_NP_DTYPES[dtype])
    lo, hi = int(arr.min(initial=0)), int(arr.max(initial=0))
    if lo < info.min or hi > info.max:
        raise ValueError(
            f"token values [{lo}, {hi}] do not fit dtype {dtype} "
            f"[{info.min}, {info.max}]"
            + ("; SFT-masked streams need dtype='int32'" if lo < 0 else "")
        )
    if arr.ndim == 2:
        with open(path + ".meta.json", "w") as f:
            json.dump({"row_len": int(arr.shape[1]), "dtype": dtype}, f)
    elif arr.ndim == 1:
        # Rewriting a row-structured path with a plain stream must not
        # leave a stale sidecar vetoing valid seq_len choices.
        try:
            os.remove(path + ".meta.json")
        except FileNotFoundError:
            pass
    else:
        raise ValueError(f"tokens must be 1-D or 2-D, got shape {arr.shape}")
    arr.astype(_NP_DTYPES[dtype]).tofile(path)
    return path


def tokenize_text_file(
    text_path: str,
    out_path: str,
    tokenizer: Any,
    dtype: str = "uint16",
    append_eos: bool = True,
) -> int:
    """Tokenize a text file (one document per line) into the flat binary
    token format, streaming — the whole corpus is never held in memory.

    ``tokenizer`` is anything with an ``encode`` method: a HF
    ``PreTrainedTokenizer(Fast)`` loaded from a local directory, or a raw
    ``tokenizers.Tokenizer``. Returns the number of tokens written.
    ``dtype="uint16"`` requires every id < 65536 (checked).
    """
    np_dtype = _NP_DTYPES[dtype]
    limit = np.iinfo(np_dtype).max
    eos_id = getattr(tokenizer, "eos_token_id", None)
    total = 0
    with open(text_path, "r", encoding="utf-8") as fin, open(out_path, "wb") as fout:
        for line in fin:
            line = line.rstrip("\r\n")  # CRLF corpora must not leak \r tokens
            if not line:
                continue
            enc = tokenizer.encode(line)
            ids = enc if isinstance(enc, list) else enc.ids  # HF vs raw tokenizers
            if append_eos and eos_id is not None:
                ids = list(ids) + [eos_id]
            arr = np.asarray(ids, dtype=np.int64)
            if arr.size and int(arr.max()) > limit:
                raise ValueError(
                    f"token id {int(arr.max())} exceeds {dtype} range; use dtype='int32'"
                )
            fout.write(arr.astype(np_dtype).tobytes())
            total += int(arr.size)
    return total


def _splitmix64(state: np.uint64) -> tuple[np.uint64, np.uint64]:
    """One splitmix64 step — must match the native RNG bit-for-bit so the
    Python fallback yields the identical shuffle order."""
    mask = np.uint64(0xFFFFFFFFFFFFFFFF)
    state = (state + np.uint64(0x9E3779B97F4A7C15)) & mask
    z = state
    z = ((z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)) & mask
    z = ((z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)) & mask
    return state, z ^ (z >> np.uint64(31))


def _shuffled_perm(n: int, seed: int, epoch: int) -> np.ndarray:
    """Fisher–Yates with splitmix64 — identical to Reader::reshuffle()."""
    perm = np.arange(n, dtype=np.int64)
    state = np.uint64(seed) ^ (np.uint64(0xA5A5A5A5) * np.uint64(epoch + 1))
    with np.errstate(over="ignore"):
        for i in range(n - 1, 0, -1):
            state, z = _splitmix64(state)
            j = int(z % np.uint64(i + 1))
            perm[i], perm[j] = perm[j], perm[i]
    return perm


class _PermWalk:
    """The deterministic shuffle walk every stream shares: per-epoch
    permutation keyed by (seed, epoch) with mid-batch epoch wrap —
    bit-identical to ``Reader::next_batch`` in the native C++ reader.
    One implementation, consumed by :class:`_PyTokenReader` (single-host
    sequential stream) AND :class:`_ShardedTokenStream` (multi-host
    sharded reads), so the two can never silently de-synchronise."""

    def __init__(self, n: int, seed: int, shuffle: bool):
        self.n, self.seed, self.shuffle = int(n), int(seed), shuffle
        self.epoch = 0
        self._cursor = 0
        self._perm = self._make_perm()

    def _make_perm(self) -> np.ndarray:
        if self.shuffle:
            return _shuffled_perm(self.n, self.seed, self.epoch)
        return np.arange(self.n, dtype=np.int64)

    def next_indices(self, k: int) -> np.ndarray:
        out = np.empty(k, dtype=np.int64)
        for i in range(k):
            if self._cursor >= self.n:
                self.epoch += 1
                self._cursor = 0
                self._perm = self._make_perm()
            out[i] = self._perm[self._cursor]
            self._cursor += 1
        return out


class _PyTokenReader:
    """NumPy-memmap fallback with the same stream semantics as the native
    reader (deterministic epoch shuffle, sequential cursor)."""

    def __init__(self, path: str, seq_len: int, dtype: str):
        self.seq_len = int(seq_len)
        self._mm = np.memmap(path, dtype=_NP_DTYPES[dtype], mode="r")
        self.num_tokens = int(self._mm.shape[0])
        self.num_sequences = self.num_tokens // self.seq_len
        if self.num_sequences < 1:
            raise FileNotFoundError(f"{path}: smaller than one sequence")
        self._batch: Optional[int] = None
        self._walk: Optional[_PermWalk] = None

    @property
    def epoch(self) -> int:
        return self._walk.epoch if self._walk is not None else 0

    def read_batch(self, indices: np.ndarray, n_threads: int = 0) -> np.ndarray:
        out = np.empty((len(indices), self.seq_len), dtype=np.int32)
        for i, idx in enumerate(np.asarray(indices, dtype=np.int64)):
            if not 0 <= idx < self.num_sequences:
                raise IndexError(f"sequence index {idx} out of range")
            out[i] = self._mm[idx * self.seq_len:(idx + 1) * self.seq_len]
        return out

    def start_prefetch(self, batch: int, seed: int = 0, shuffle: bool = True) -> None:
        if batch > self.num_sequences:
            raise ValueError("batch > num_sequences")
        self._batch = int(batch)
        self._walk = _PermWalk(self.num_sequences, seed, shuffle)

    def next_batch(self) -> np.ndarray:
        if self._batch is None or self._walk is None:
            raise RuntimeError("call start_prefetch first")
        return self.read_batch(self._walk.next_indices(self._batch))

    def close(self) -> None:
        self._mm = None


class TokenFileDataset:
    """Sequences from a flat binary token file; native reader when possible.

    The stream is deterministic given (seed, batch): restarting after a crash
    replays the same shuffle order, so resume-from-checkpoint sees the data
    it would have seen (the step index keys the stream position).
    """

    def __init__(self, path: str, seq_len: int, dtype: str = "uint16",
                 prefer_native: bool = True):
        if dtype not in _DTYPE_CODES:
            raise ValueError(f"dtype must be one of {sorted(_DTYPE_CODES)}")
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        # Row-structured files (packed SFT examples) carry a sidecar with
        # their row length; slicing them at any other seq_len would split
        # rows and silently shift mask boundaries.
        meta_path = path + ".meta.json"
        if os.path.exists(meta_path):
            try:
                with open(meta_path) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                meta = {}
            row_len = meta.get("row_len")
            if row_len is not None and int(row_len) != int(seq_len):
                raise ValueError(
                    f"{path} was written with row_len={row_len} "
                    f"(see {meta_path}); reading it at seq_len={seq_len} "
                    "would misalign rows and SFT mask boundaries"
                )
        self.path, self.seq_len, self.dtype = path, int(seq_len), dtype
        self.native = False
        if prefer_native and native.available():
            self._reader: Any = native.NativeTokenReader(
                path, seq_len, _DTYPE_CODES[dtype]
            )
            self.native = True
        else:
            self._reader = _PyTokenReader(path, seq_len, dtype)

    @property
    def num_sequences(self) -> int:
        return self._reader.num_sequences

    @property
    def num_tokens(self) -> int:
        return self._reader.num_tokens

    @property
    def epoch(self) -> int:
        return self._reader.epoch

    def read_batch(self, indices: np.ndarray) -> np.ndarray:
        return self._reader.read_batch(np.asarray(indices, dtype=np.int64))

    def start(self, batch: int, seed: int = 0, shuffle: bool = True) -> None:
        self._reader.start_prefetch(batch, seed, shuffle)

    def next_batch(self) -> np.ndarray:
        return self._reader.next_batch()

    def close(self) -> None:
        self._reader.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class SyntheticDataset:
    """Deterministic random tokens (the default when no dataset is given)."""

    def __init__(self, vocab_size: int, seq_len: int):
        self.vocab_size, self.seq_len = vocab_size, seq_len
        self._batch: Optional[int] = None
        self._seed = 0
        self._step = 0

    def start(self, batch: int, seed: int = 0, shuffle: bool = True) -> None:
        self._batch, self._seed, self._step = int(batch), int(seed), 0

    def next_batch(self) -> np.ndarray:
        if self._batch is None:
            raise RuntimeError("call start first")
        rng = np.random.default_rng((self._seed << 20) ^ self._step)
        self._step += 1
        return rng.integers(
            0, self.vocab_size, (self._batch, self.seq_len), dtype=np.int32
        )

    def close(self) -> None:
        pass


class _ShardedTokenStream:
    """Per-process view of the deterministic global sample stream.

    Every process derives the SAME (seed, epoch)-keyed permutation walk the
    single-host readers use, but only this process's contiguous row block
    of each step's [accum, global_micro] index matrix is actually READ —
    per-process I/O volume scales as 1/process_count instead of every host
    reading (and then discarding most of) the full global batch
    (round-2 VERDICT weak #5: at 64 hosts that is 64x redundant read+gather
    work per step, and every host must hold the whole token file).

    A one-deep background prefetch thread hides the read behind the
    previous step's compute, preserving the latency-hiding the readers'
    own prefetch pipelines provide on the single-host path.
    """

    def __init__(self, dataset: Any, accum: int, global_micro: int,
                 row_start: int, row_count: int, seed: int,
                 shuffle: bool = True, prefetch: bool = True):
        n = dataset.num_sequences
        batch = accum * global_micro
        if batch > n:
            raise ValueError(f"batch {batch} > num_sequences {n}")
        self._ds = dataset
        self._accum, self._gm = accum, global_micro
        # One tuple, swapped atomically: the prefetch thread reads the
        # window mid-step and a reassign must never hand it a torn
        # (new start, old count) pair.
        self._window = (int(row_start), int(row_count))
        self._walk = _PermWalk(n, seed, shuffle)
        self._queue: Any = None
        self._dead: Optional[Exception] = None
        if prefetch:
            import queue as _queue

            self._queue = _queue.Queue(maxsize=1)
            self._stop = threading.Event()
            self._thread = threading.Thread(
                target=self._producer, daemon=True, name="sharded-data-prefetch"
            )
            self._thread.start()

    @property
    def epoch(self) -> int:
        return self._walk.epoch

    def reassign(self, row_start: int, row_count: int) -> None:
        """Move this process's row window (heterogeneous rebalance).

        The walk itself is untouched — every process still derives the
        identical global index matrix each step, so as long as all
        processes reassign at the same step boundary the global batch
        stays covered exactly once. With prefetch on, the one in-flight
        batch was read under the old window; the new window takes effect
        from the next produced batch — the same step skew on every
        process, because the prefetch depth is fixed at one.
        """
        r0, rows = int(row_start), int(row_count)
        if rows < 1 or r0 < 0 or r0 + rows > self._gm:
            raise ValueError(
                f"row window [{r0}, {r0 + rows}) outside global micro "
                f"batch of {self._gm} rows"
            )
        self._window = (r0, rows)

    def _read_local(self) -> np.ndarray:
        r0, rows = self._window
        g = self._walk.next_indices(self._accum * self._gm).reshape(
            self._accum, self._gm
        )
        block = g[:, r0:r0 + rows]  # [accum, rows]
        flat = self._ds.read_batch(block.reshape(-1))
        return flat.reshape(self._accum, rows, -1)

    def _producer(self) -> None:
        while not self._stop.is_set():
            try:
                item = self._read_local()
            except Exception as e:  # surface in next(); never die silently
                item = e
            while not self._stop.is_set():
                try:
                    self._queue.put(item, timeout=0.2)
                    break
                except Exception:
                    continue
            if isinstance(item, Exception):
                return

    def next(self) -> np.ndarray:
        """This process's [accum, rows, seq] slab for the next step."""
        if self._queue is None:
            return self._read_local()
        if self._dead is not None:
            # The producer delivered an exception and exited; re-raise on
            # every later call instead of blocking forever on an empty
            # queue (a retry loop around data_fn would otherwise deadlock).
            raise self._dead
        item = self._queue.get()
        if isinstance(item, Exception):
            self._dead = item
            raise item
        return item

    def close(self) -> None:
        if self._queue is not None:
            self._stop.set()
            try:
                while True:
                    self._queue.get_nowait()
            except Exception:
                pass
            self._thread.join(timeout=2.0)


def validate_row_assignment(
    assignment: Any, global_micro: int, process_count: int, accum: int = 1
) -> list[int]:
    """Validate a non-uniform rows-per-process vector (heterogeneous
    sharding, ``tpu_engine/hetero.py``): one positive entry per process,
    summing to the declared global micro batch exactly — a bad vector
    would silently drop or double-read rows of every step's
    ``accum × global_micro`` batch, so it is rejected loudly instead."""
    rows = [int(r) for r in assignment]
    if len(rows) != process_count:
        raise ValueError(
            f"row assignment has {len(rows)} entries for "
            f"{process_count} processes"
        )
    if any(r < 1 for r in rows):
        raise ValueError(f"row assignment entries must be >= 1, got {rows}")
    if sum(rows) != int(global_micro):
        raise ValueError(
            f"row assignment {rows} covers {accum} x {sum(rows)} rows per "
            f"step, expected accum x global micro batch = "
            f"{accum} x {global_micro}"
        )
    return rows


def _sharding_batch_partition(
    sharding: Any, global_shape: Any
) -> Optional[list[int]]:
    """Batch-axis (dim 1) rows each process's devices address under
    ``sharding``, ordered by process index — the fixed partition GSPMD
    places; ``None`` when the sharding cannot tell (mock shardings in
    tests). A non-uniform row assignment can only be placed from
    per-process local blocks when it equals this partition exactly:
    anything else either fails jax's per-dimension size check or — worse,
    when only the prefix offsets drift — silently misplaces rows."""
    try:
        idx_map = sharding.devices_indices_map(tuple(global_shape))
        per_proc: dict[int, set] = {}
        for dev, idx in idx_map.items():
            s = idx[1]
            start = 0 if s.start is None else int(s.start)
            stop = int(global_shape[1]) if s.stop is None else int(s.stop)
            per_proc.setdefault(int(dev.process_index), set()).add((start, stop))
        if not per_proc:
            return None
        return [
            sum(b - a for a, b in spans)
            for _, spans in sorted(per_proc.items())
        ]
    except Exception:
        return None


def _check_stream_assignment_feasible(
    rows: list[int], sharding: Any, global_shape: Any
) -> None:
    """A sharded-stream process reads ONLY its own row window, so on a
    real multi-process runtime a non-uniform assignment is placeable only
    when it matches the sharding's fixed per-process batch partition —
    rows a process read but whose devices live on another host cannot
    cross hosts here. Reject loudly (the supervisor audits the rejection
    as ``hetero_reassign_rejected`` and keeps the old split) instead of
    letting the placement misplace or drop rows mid-step."""
    if jax.process_count() <= 1:
        return  # single-process runtime, incl. the process_count test seam
    partition = _sharding_batch_partition(sharding, global_shape)
    if partition is None or partition == rows:
        return
    raise ValueError(
        f"row assignment {rows} does not match the sharding's per-process "
        f"batch partition {partition}; a sharded stream cannot place rows "
        "its own devices do not address (heterogeneous sharding is limited "
        "to partition-compatible assignments on multi-host runtimes)"
    )


def _place_global(
    batch: np.ndarray, sharding: Any, row_assignment: Optional[list[int]] = None
) -> jax.Array:
    """Place a host [accum, global_micro, seq] batch onto the mesh.

    Multi-process SYNTHETIC batches: every process holds the identical
    global batch and contributes its contiguous row block (mesh devices
    are ordered by process, so batch-axis shards are process-contiguous;
    the sequence axis, if sharded, stays process-local on one host's slice
    under the canonical (data, fsdp, sequence, model) order). A
    ``row_assignment`` replaces the implicit equal split with per-process
    block sizes (prefix sums give the offsets) — but GSPMD's batch
    partition is fixed per process, so when the assignment deviates from
    it the per-process block cannot be assembled; since every process
    holds the identical batch anyway, placement then falls back to the
    full array (each device slices its own shard directly). File-backed
    multi-process reads do NOT come through here — ``make_data_fn``
    shards the reads themselves (``_ShardedTokenStream``).
    """
    if jax.process_count() > 1:
        pi = jax.process_index()
        if row_assignment is not None:
            partition = _sharding_batch_partition(sharding, batch.shape)
            if partition != [int(r) for r in row_assignment]:
                return jax.make_array_from_process_local_data(
                    sharding, batch, global_shape=batch.shape
                )
            r0 = sum(row_assignment[:pi])
            rows = row_assignment[pi]
        else:
            rows = batch.shape[1] // jax.process_count()
            r0 = pi * rows
        local = batch[:, r0:r0 + rows]
        return jax.make_array_from_process_local_data(
            sharding, local, global_shape=batch.shape
        )
    return jax.device_put(batch, sharding)


def _check_seq_len(dataset: Any, seq_len: int) -> None:
    if dataset.seq_len != seq_len:
        raise ValueError(
            f"dataset seq_len {dataset.seq_len} != program seq_len {seq_len}"
        )


def make_data_fn(
    program: Any,
    dataset: Any,
    seed: int = 0,
    *,
    process_count: Optional[int] = None,
    process_index: Optional[int] = None,
    row_assignment: Optional[Any] = None,
) -> Callable[[int], jax.Array]:
    """Adapt a dataset into the supervisor's ``data_fn(step)`` contract.

    Single host: pulls ``accum × global_micro`` sequences per step from the
    (shuffled, prefetching) stream and places them with the program's batch
    sharding.

    Multi-process with a random-access dataset (``read_batch``): each
    process reads ONLY its own contiguous row block of the deterministic
    global stream (``_ShardedTokenStream``) — per-process read volume
    scales as 1/process_count, and hosts need not even hold rows outside
    their block in page cache. ``process_count``/``process_index``
    override the runtime's view (test seam).

    ``row_assignment`` replaces the implicit equal split with a
    non-uniform rows-per-process vector (throughput-weighted heterogeneous
    sharding, ``tpu_engine/hetero.py``); it must sum to the global micro
    batch exactly. The returned ``data_fn`` additionally exposes
    ``data_fn.reassign(assignment)`` so a live rebalance can move the row
    windows without rebuilding the stream. Cross-process agreement is the
    rebalancer's job, not a caller convention: ``HeteroRebalancer`` runs
    step-keyed consults from broadcast (rank-0) estimates with a
    step-based cooldown, so every process calls ``reassign`` with the
    identical vector at the identical step boundary. On real multi-host
    runtimes the vector must additionally match the sharding's fixed
    per-process batch partition (a stream process cannot feed devices on
    another host) — incompatible vectors raise ``ValueError``, which the
    supervisor audits as ``hetero_reassign_rejected``.
    """
    accum, global_micro, seq_len = program.global_batch_shape()
    _check_seq_len(dataset, seq_len)
    pc = process_count if process_count is not None else jax.process_count()
    pi = process_index if process_index is not None else jax.process_index()
    sharding = program.batch_sharding

    if pc > 1 and hasattr(dataset, "read_batch"):
        if row_assignment is not None:
            rows_vec = validate_row_assignment(
                row_assignment, global_micro, pc, accum
            )
            _check_stream_assignment_feasible(
                rows_vec, sharding, (accum, global_micro, seq_len)
            )
        else:
            if global_micro % pc != 0:
                raise ValueError(
                    f"global micro batch {global_micro} not divisible by "
                    f"process count {pc}"
                )
            rows_vec = [global_micro // pc] * pc
        stream = _ShardedTokenStream(
            dataset, accum, global_micro, sum(rows_vec[:pi]), rows_vec[pi], seed
        )

        def data_fn(step: int) -> jax.Array:
            local = stream.next()  # [accum, rows, seq_len]
            return jax.make_array_from_process_local_data(
                sharding, local, global_shape=(accum, global_micro, seq_len)
            )

        def reassign(assignment: Any) -> list[int]:
            rv = validate_row_assignment(assignment, global_micro, pc, accum)
            _check_stream_assignment_feasible(
                rv, sharding, (accum, global_micro, seq_len)
            )
            stream.reassign(sum(rv[:pi]), rv[pi])
            return rv

        # Owners must stop the prefetch thread with the job (the supervisor
        # calls this in its finally block).
        data_fn.close = stream.close  # type: ignore[attr-defined]
        data_fn.reassign = reassign  # type: ignore[attr-defined]
        return data_fn

    dataset.start(accum * global_micro, seed=seed)
    assign_box: list[Optional[list[int]]] = [
        validate_row_assignment(row_assignment, global_micro, pc, accum)
        if row_assignment is not None else None
    ]

    def data_fn(step: int) -> jax.Array:
        flat = dataset.next_batch()  # [accum*global_micro, seq_len] int32
        return _place_global(
            flat.reshape(accum, global_micro, seq_len), sharding, assign_box[0]
        )

    def reassign(assignment: Any) -> list[int]:
        assign_box[0] = validate_row_assignment(assignment, global_micro, pc, accum)
        return assign_box[0]

    data_fn.reassign = reassign  # type: ignore[attr-defined]
    return data_fn


def make_eval_data_fn(program: Any, dataset: "TokenFileDataset") -> Callable[[int], jax.Array]:
    """Fixed held-out batches: call index ``i`` always reads the same
    sequences (the i-th contiguous block of the file, wrapping), so eval
    losses are comparable across training steps — unlike the consuming
    shuffled stream :func:`make_data_fn` adapts."""
    accum, global_micro, seq_len = program.global_batch_shape()
    _check_seq_len(dataset, seq_len)
    bs = accum * global_micro
    sharding = program.batch_sharding

    def eval_fn(i: int) -> jax.Array:
        idx = (np.arange(bs, dtype=np.int64) + i * bs) % dataset.num_sequences
        flat = dataset.read_batch(idx)
        return _place_global(flat.reshape(accum, global_micro, seq_len), sharding)

    return eval_fn


# -- SFT packing -------------------------------------------------------------


def pack_sft_examples(
    pairs: "list[tuple[list[int], list[int]]]", seq_len: int
) -> np.ndarray:
    """Pack (prompt, completion) token pairs into fixed-length rows with
    in-band loss masking: prompt tokens are stored as ``-(t+1)`` (real
    context whose prediction is not trained on), completion tokens as-is,
    and padding as ``-1`` (masked token 0). The loss then trains only on
    predicting the completion — the standard SFT objective.

    The result is ``[n, seq_len] int32``; write it with
    :func:`write_token_file` using ``dtype="int32"`` (the masked encoding
    needs the sign bit — uint16 streams cannot carry masks). Writing the
    2-D array records ``seq_len`` in a ``.meta.json`` sidecar, and
    :class:`TokenFileDataset` refuses to open the file at any other
    seq_len — rows are only aligned when the training config's seq_len
    equals the packing seq_len.
    """
    rows = np.full((len(pairs), seq_len), -1, np.int32)
    for i, (prompt, completion) in enumerate(pairs):
        if any(t < 0 for t in prompt) or any(t < 0 for t in completion):
            raise ValueError(f"pair {i}: token ids must be >= 0")
        seq = [-(t + 1) for t in prompt] + list(completion)
        if len(seq) > seq_len:
            raise ValueError(
                f"pair {i}: prompt+completion is {len(seq)} tokens, "
                f"exceeds seq_len={seq_len} (truncating would silently "
                "change the example; split or shorten it)"
            )
        rows[i, : len(seq)] = np.asarray(seq, np.int32)
    return rows
