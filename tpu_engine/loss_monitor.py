"""Training-metrics anomaly detection.

Capability parity with the reference's ``LossSpikeMonitor``
(``ai_engine/loss_monitor.py``): the same five detectors with the same
default thresholds and the same check ordering —

1. divergence: NaN/Inf (critical, early-return) or loss > 1e6
   (``loss_monitor.py:126-150``),
2. loss spike: rolling mean + 3σ over a 100-step window, critical at 5σ,
   min history 10, 20-step per-type cooldown (``:153-173``),
3. plateau: best-loss tracking with 500-step patience, 1e-4 min delta
   (``:176-197``),
4. gradient explosion: grad-norm > 100 (``:200-215``),
5. LR anomaly: lr > 10× rolling average, min history 5 (``:218-234``).

Deliberately preserved quirks (SURVEY.md §5): the rolling window *excludes*
the current step (append-after-check, ``:237``) and NaN/Inf losses never
enter the window (early return, ``:126-138``) — diverged values cannot poison
the statistics.

Deliberately fixed (SURVEY.md §5): the reference's unbounded
``_all_metrics``/``_all_alerts`` lists (``:108-109``) leak memory over long
runs and ``max_alerts_per_type`` is defined but never enforced (``:65``).
Here both histories are bounded deques and the per-type alert cap is real.

"""

from __future__ import annotations

import math
import statistics
import threading
import time
from collections import deque
from enum import Enum
from typing import Any, Optional

from pydantic import BaseModel, Field


class AlertSeverity(str, Enum):
    """Mirrors reference ``AlertSeverity`` (``loss_monitor.py:23-27``)."""

    INFO = "info"
    WARNING = "warning"
    CRITICAL = "critical"


class SpikeAlert(BaseModel):
    """Mirrors reference ``SpikeAlert`` (``loss_monitor.py:29-42``)."""

    alert_type: str
    severity: AlertSeverity
    step: int
    job_id: str = ""
    message: str
    current_value: float
    threshold_value: float
    timestamp: float = Field(default_factory=time.time)
    remediation: list[str] = Field(default_factory=list)


class TrainingMetrics(BaseModel):
    """Mirrors reference ``TrainingMetrics`` (``loss_monitor.py:44-53``)."""

    step: int
    loss: float
    learning_rate: Optional[float] = None
    gradient_norm: Optional[float] = None
    throughput_tokens_per_sec: Optional[float] = None
    timestamp: float = Field(default_factory=time.time)


class MonitorConfig(BaseModel):
    """Mirrors reference ``MonitorConfig`` (``loss_monitor.py:56-66``)."""

    window_size: int = Field(default=100, ge=2)
    min_history_for_spike: int = Field(default=10, ge=2)
    spike_sigma: float = Field(default=3.0, gt=0)
    critical_sigma: float = Field(default=5.0, gt=0)
    divergence_threshold: float = Field(default=1e6, gt=0)
    plateau_patience_steps: int = Field(default=500, ge=1)
    plateau_min_delta: float = Field(default=1e-4, ge=0)
    gradient_norm_threshold: float = Field(default=100.0, gt=0)
    lr_anomaly_ratio: float = Field(default=10.0, gt=1)
    min_history_for_lr: int = Field(default=5, ge=2)
    alert_cooldown_steps: int = Field(default=20, ge=0)
    max_alerts_per_type: int = Field(default=50, ge=1)  # enforced (unlike reference :65)
    max_history: int = Field(default=10_000, ge=100)  # bounded (reference is unbounded :108)


class LossSpikeMonitor:
    """Per-job anomaly monitor; pure in-memory, no I/O (reference ``loss_monitor.py:79``)."""

    def __init__(self, job_id: str = "", config: Optional[MonitorConfig] = None):
        self.job_id = job_id
        self.config = config or MonitorConfig()
        # The training thread ingests while HTTP handlers read summaries:
        # all public entry points take this lock (the reference mutates its
        # monitor dict unlocked — SURVEY.md §5 race detection).
        self._lock = threading.RLock()
        self._loss_window: deque[float] = deque(maxlen=self.config.window_size)
        self._lr_window: deque[float] = deque(maxlen=self.config.window_size)
        self._metrics: deque[TrainingMetrics] = deque(maxlen=self.config.max_history)
        self._alerts: deque[SpikeAlert] = deque(maxlen=self.config.max_history)
        self._alert_counts: dict[str, int] = {}
        self._last_alert_step: dict[str, int] = {}
        self._best_loss: float = math.inf
        self._best_loss_step: int = 0
        self._plateau_alerted_at_best: float = math.nan

    # -- ingestion (the per-step hot path; reference ``ingest`` :111-243) ----

    def ingest(self, m: TrainingMetrics) -> list[SpikeAlert]:
        with self._lock:
            return self._ingest_locked(m)

    def _ingest_locked(self, m: TrainingMetrics) -> list[SpikeAlert]:
        alerts: list[SpikeAlert] = []

        # 1. Divergence: NaN/Inf — EARLY RETURN, do not append to history.
        if math.isnan(m.loss) or math.isinf(m.loss):
            a = self._emit(
                "divergence",
                AlertSeverity.CRITICAL,
                m.step,
                f"Loss is {m.loss} at step {m.step} — training has diverged",
                current=m.loss,
                threshold=self.config.divergence_threshold,
                remediation=[
                    "Halt training immediately",
                    "Restore from last stable checkpoint",
                    "Reduce learning rate by 2-10x",
                    "Check input data for corrupt batches",
                ],
            )
            if a:
                alerts.append(a)
            self._metrics.append(m)
            return alerts

        # 1b. Divergence by magnitude.
        if m.loss > self.config.divergence_threshold:
            a = self._emit(
                "divergence",
                AlertSeverity.CRITICAL,
                m.step,
                f"Loss {m.loss:.4g} exceeds divergence threshold "
                f"{self.config.divergence_threshold:.4g}",
                current=m.loss,
                threshold=self.config.divergence_threshold,
                remediation=[
                    "Halt training immediately",
                    "Restore from last stable checkpoint",
                    "Reduce learning rate",
                ],
            )
            if a:
                alerts.append(a)

        # 2. Spike: rolling mean + kσ over window EXCLUDING current step.
        if len(self._loss_window) >= self.config.min_history_for_spike:
            mean = statistics.fmean(self._loss_window)
            std = statistics.pstdev(self._loss_window)
            if std > 0:
                spike_thr = mean + self.config.spike_sigma * std
                crit_thr = mean + self.config.critical_sigma * std
                if m.loss > spike_thr:
                    severity = (
                        AlertSeverity.CRITICAL if m.loss > crit_thr else AlertSeverity.WARNING
                    )
                    a = self._emit(
                        "loss_spike",
                        severity,
                        m.step,
                        f"Loss {m.loss:.4f} spiked above rolling mean {mean:.4f} "
                        f"+ {self.config.spike_sigma:.0f}σ ({spike_thr:.4f})",
                        current=m.loss,
                        threshold=spike_thr,
                        remediation=[
                            "Inspect recent data batches for outliers",
                            "Consider reducing learning rate",
                            "Restore from last checkpoint if loss does not recover",
                        ],
                    )
                    if a:
                        alerts.append(a)

        # 3. Plateau: best-loss tracking + patience.
        if m.loss < self._best_loss - self.config.plateau_min_delta:
            self._best_loss = m.loss
            self._best_loss_step = m.step
        elif (
            m.step - self._best_loss_step >= self.config.plateau_patience_steps
            and self._plateau_alerted_at_best != self._best_loss
        ):
            a = self._emit(
                "plateau",
                AlertSeverity.INFO,
                m.step,
                f"No improvement > {self.config.plateau_min_delta} for "
                f"{m.step - self._best_loss_step} steps (best {self._best_loss:.4f} "
                f"at step {self._best_loss_step})",
                current=m.loss,
                threshold=self._best_loss,
                remediation=[
                    "Consider learning-rate schedule changes",
                    "Evaluate early stopping",
                    "Check for data pipeline repetition",
                ],
            )
            if a:
                alerts.append(a)
                self._plateau_alerted_at_best = self._best_loss

        # 4. Gradient explosion.
        if m.gradient_norm is not None and m.gradient_norm > self.config.gradient_norm_threshold:
            a = self._emit(
                "gradient_explosion",
                AlertSeverity.CRITICAL,
                m.step,
                f"Gradient norm {m.gradient_norm:.2f} exceeds "
                f"{self.config.gradient_norm_threshold:.0f}",
                current=m.gradient_norm,
                threshold=self.config.gradient_norm_threshold,
                remediation=[
                    "Enable/tighten gradient clipping",
                    "Reduce learning rate",
                    "Check for bad batches or numerical issues",
                ],
            )
            if a:
                alerts.append(a)

        # 5. LR anomaly: lr > ratio × rolling average.
        if m.learning_rate is not None:
            if len(self._lr_window) >= self.config.min_history_for_lr:
                lr_avg = statistics.fmean(self._lr_window)
                if lr_avg > 0 and m.learning_rate > self.config.lr_anomaly_ratio * lr_avg:
                    a = self._emit(
                        "lr_anomaly",
                        AlertSeverity.WARNING,
                        m.step,
                        f"Learning rate {m.learning_rate:.3g} is more than "
                        f"{self.config.lr_anomaly_ratio:.0f}x the rolling average {lr_avg:.3g}",
                        current=m.learning_rate,
                        threshold=self.config.lr_anomaly_ratio * lr_avg,
                        remediation=[
                            "Verify the LR scheduler configuration",
                            "Check for scheduler restarts or warm restarts",
                        ],
                    )
                    if a:
                        alerts.append(a)
            self._lr_window.append(m.learning_rate)

        # Append AFTER all checks: the window never includes the current step.
        self._loss_window.append(m.loss)
        self._metrics.append(m)
        return alerts

    # -- alert bookkeeping ---------------------------------------------------

    def _can_alert(self, alert_type: str, step: int) -> bool:
        """Cooldown + per-type cap (reference ``_can_alert`` :301-309, cap enforced here)."""
        if self._alert_counts.get(alert_type, 0) >= self.config.max_alerts_per_type:
            return False
        last = self._last_alert_step.get(alert_type)
        if last is not None and step - last < self.config.alert_cooldown_steps:
            return False
        return True

    def _emit(
        self,
        alert_type: str,
        severity: AlertSeverity,
        step: int,
        message: str,
        current: float,
        threshold: float,
        remediation: list[str],
    ) -> Optional[SpikeAlert]:
        if not self._can_alert(alert_type, step):
            return None
        alert = SpikeAlert(
            alert_type=alert_type,
            severity=severity,
            step=step,
            job_id=self.job_id,
            message=message,
            current_value=current,
            threshold_value=threshold,
            remediation=remediation,
        )
        self._alerts.append(alert)
        self._alert_counts[alert_type] = self._alert_counts.get(alert_type, 0) + 1
        self._last_alert_step[alert_type] = step
        return alert

    # -- views (reference ``get_summary`` :245-259, ``get_loss_curve`` :261-271)

    @property
    def alerts(self) -> list[SpikeAlert]:
        with self._lock:
            return list(self._alerts)

    def has_critical_alert(self) -> bool:
        with self._lock:
            return any(a.severity == AlertSeverity.CRITICAL for a in self._alerts)

    def get_summary(self) -> dict[str, Any]:
        with self._lock:
            return self._summary_locked()

    def _summary_locked(self) -> dict[str, Any]:
        losses = [m.loss for m in self._metrics if not (math.isnan(m.loss) or math.isinf(m.loss))]
        return {
            "job_id": self.job_id,
            "total_steps_seen": len(self._metrics),
            "current_loss": self._metrics[-1].loss if self._metrics else None,
            "best_loss": None if math.isinf(self._best_loss) else self._best_loss,
            "best_loss_step": self._best_loss_step if losses else None,
            "rolling_mean_loss": statistics.fmean(self._loss_window) if self._loss_window else None,
            "rolling_std_loss": statistics.pstdev(self._loss_window)
            if len(self._loss_window) >= 2
            else None,
            "total_alerts": len(self._alerts),
            "alerts_by_type": dict(self._alert_counts),
            "critical_alerts": sum(
                1 for a in self._alerts if a.severity == AlertSeverity.CRITICAL
            ),
        }

    def get_loss_curve(self) -> dict[str, list]:
        """Visualization feed: steps/losses/lrs/grad-norms/spike-steps arrays."""
        with self._lock:
            return self._loss_curve_locked()

    def _loss_curve_locked(self) -> dict[str, list]:
        return {
            "steps": [m.step for m in self._metrics],
            "losses": [m.loss for m in self._metrics],
            "learning_rates": [m.learning_rate for m in self._metrics],
            "gradient_norms": [m.gradient_norm for m in self._metrics],
            "throughputs": [m.throughput_tokens_per_sec for m in self._metrics],
            "spike_steps": [a.step for a in self._alerts if a.alert_type == "loss_spike"],
        }

    def reset(self) -> None:
        """Clear all state, e.g. after checkpoint restore (reference :273-280)."""
        with self._lock:
            self._reset_locked()

    def _reset_locked(self) -> None:
        self._loss_window.clear()
        self._lr_window.clear()
        self._metrics.clear()
        self._alerts.clear()
        self._alert_counts.clear()
        self._last_alert_step.clear()
        self._best_loss = math.inf
        self._best_loss_step = 0
        self._plateau_alerted_at_best = math.nan
